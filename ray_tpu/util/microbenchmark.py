"""Core-runtime microbenchmarks.

Reference analog: python/ray/_private/ray_perf.py:93-315 (the `ray
microbenchmark` CLI): put/get ops, task throughput sync/async, 1:1 and
n:n actor call rates — the numbers the release pipeline tracks per build.
Run via `python -m ray_tpu.scripts microbenchmark [--scale N]`.
"""

from __future__ import annotations

import json
import time
from typing import Dict, List


def _rate(n: int, seconds: float) -> float:
    return n / max(seconds, 1e-9)


def run(scale: float = 1.0, num_cpus: int = 4) -> List[Dict]:
    import numpy as np

    import ray_tpu

    owns_cluster = not ray_tpu.is_initialized()
    if owns_cluster:
        ray_tpu.init(num_cpus=num_cpus)
    results: List[Dict] = []

    def record(name: str, n: int, seconds: float, unit: str = "ops/s"):
        results.append({"benchmark": name, "value": round(_rate(n, seconds), 1),
                        "unit": unit, "n": n})

    try:
        # -- object store ------------------------------------------------
        n = int(1000 * scale)
        t0 = time.perf_counter()
        refs = [ray_tpu.put(i) for i in range(n)]
        record("put_small_ops", n, time.perf_counter() - t0)
        t0 = time.perf_counter()
        ray_tpu.get(refs)
        record("get_small_ops", n, time.perf_counter() - t0)
        del refs

        m = max(4, int(64 * scale))
        payload = np.zeros(1 << 20, dtype=np.uint8)  # 1 MiB
        # Warmup: settle cluster-boot CPU contention and page-fault the
        # arena region this loop will reuse (steady-state bandwidth is the
        # number the release pipeline tracks; ray_perf.py warms up too).
        # The first large put triggers the driver's lazy arena-prefault
        # walk. On small boxes that walk competes with the copy loop for
        # the same cores, so wait for it to finish before timing
        # (production hosts hide the walk behind spare cores; the steady
        # state is the tracked number).
        from ray_tpu.core.worker import global_worker

        warm_refs = [ray_tpu.put(payload)]
        store = global_worker().store
        deadline = time.monotonic() + 15.0
        while (store is not None and not store.prefaulted
               and store.prefault_inflight  # never-warm hosts: don't stall
               and time.monotonic() < deadline):
            time.sleep(0.1)
        warm_refs += [ray_tpu.put(payload) for _ in range(min(32, m))]
        # Free the warmup objects deterministically so trial occupancy
        # (3 x m MiB) doesn't depend on GC timing on small stores.
        del warm_refs
        # Best of 3 trials: on small/shared boxes a single descheduling
        # blip inside one trial halves the apparent bandwidth, so the
        # bandwidth legs report peak steady state (standard for bandwidth
        # suites — STREAM does the same).
        put_best = get_best = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            big = [ray_tpu.put(payload) for _ in range(m)]
            dt = time.perf_counter() - t0
            put_best = max(put_best, m / (1 << 10) / max(dt, 1e-9))
            t0 = time.perf_counter()
            ray_tpu.get(big)
            dt = time.perf_counter() - t0
            get_best = max(get_best, m / (1 << 10) / max(dt, 1e-9))
            del big
        results.append({"benchmark": "put_1mib_gbps",
                        "value": round(put_best, 3),
                        "unit": "GiB/s", "n": m, "trials": 3})
        results.append({"benchmark": "get_1mib_gbps",
                        "value": round(get_best, 3),
                        "unit": "GiB/s", "n": m, "trials": 3})

        # -- tasks -------------------------------------------------------
        @ray_tpu.remote
        def nop():
            return None

        # Warm the WHOLE worker pool (a single probe task would leave the
        # batch benchmarks measuring process-spawn ramp, not steady state).
        ray_tpu.get([nop.remote() for _ in range(num_cpus * 8)], timeout=300)
        n = int(100 * scale)
        t0 = time.perf_counter()
        for _ in range(n):
            ray_tpu.get(nop.remote(), timeout=120)
        record("tasks_sync", n, time.perf_counter() - t0)

        n = int(500 * scale)
        t0 = time.perf_counter()
        ray_tpu.get([nop.remote() for _ in range(n)], timeout=300)
        record("tasks_async_batch", n, time.perf_counter() - t0)

        # -- actors ------------------------------------------------------
        @ray_tpu.remote
        class Actor:
            def noop(self):
                return None

        a = Actor.remote()
        ray_tpu.get(a.noop.remote(), timeout=120)
        n = int(200 * scale)
        t0 = time.perf_counter()
        for _ in range(n):
            ray_tpu.get(a.noop.remote(), timeout=120)
        record("actor_calls_sync_1_1", n, time.perf_counter() - t0)

        n = int(1000 * scale)
        t0 = time.perf_counter()
        ray_tpu.get([a.noop.remote() for _ in range(n)], timeout=300)
        record("actor_calls_async_1_1", n, time.perf_counter() - t0)

        workers = [Actor.remote() for _ in range(4)]
        for w in workers:
            ray_tpu.get(w.noop.remote(), timeout=120)
        n = int(250 * scale)
        t0 = time.perf_counter()
        ray_tpu.get([w.noop.remote() for w in workers for _ in range(n)],
                    timeout=300)
        record("actor_calls_async_n_n", n * len(workers),
               time.perf_counter() - t0)
        # Benchmark actors must not outlive the run on a shared cluster.
        for actor in [a, *workers]:
            try:
                ray_tpu.kill(actor)
            except Exception:
                pass

        # -- compiled-graph channels vs actor RPC ------------------------
        # The zero-copy number the compiled-DAG work exists for: hand a
        # 1 MiB device activation to another actor and back, once over
        # DeviceChannels (raw bytes through the shm ring, no pickle) and
        # once as a plain actor call (task submission + object store).
        results.extend(_bench_channel_vs_rpc(scale))

        # -- out-of-graph collectives: ring vs hub -----------------------
        results.extend(_bench_collectives(scale))

        # -- LLM serving plane: router affinity + disaggregation ---------
        results.extend(_bench_serve_mixed(scale))

        # -- LLM fleet resilience: failover replay + live migration ------
        results.extend(_bench_serve_resilience(scale))

        # -- tiered prefix store: cluster-table adopt vs re-prefill ------
        results.extend(_bench_serve_prefix_store(scale))

        # -- closed-loop load sweep: 1->N replicas, drain churn mid-run --
        results.extend(_bench_serve_load_sweep(scale))

        # -- RLHF pipeline: colocated vs disaggregated placement ---------
        results.extend(_bench_rlhf(scale))

        # -- checkpoint plane: sync stall vs async snapshot-only stall ---
        results.extend(_bench_checkpoint(scale))

        # -- streaming data plane: pipelined ingestion vs bulk batch -----
        results.extend(_bench_data_stream(scale))

        # -- metrics history plane: ingest rate, query ms, serve overhead
        results.extend(_bench_metrics_history(scale))

        # -- control-plane scale envelope: batched vs per-item leases ----
        results.extend(_bench_scale_envelope(scale))
    finally:
        if owns_cluster:
            ray_tpu.shutdown()
    return results


def _bench_channel_vs_rpc(scale: float) -> List[Dict]:
    """1 MiB activation stream: driver -> actor -> driver, via DeviceChannels
    and via actor RPC. This is the pipeline-parallel steady state — a stream
    of microbatch activations through a stage — not a synchronous ping-pong,
    so both legs are run with in-flight depth (ring capacity / async task
    batch) and report the best of 3 steady-state windows (same rationale as
    the put/get bandwidth legs above: one descheduling blip on a small box
    halves a single trial). Items/s and effective GiB/s (2 MiB per item)."""
    import jax.numpy as jnp

    import ray_tpu
    from ray_tpu.dag.channel import ChannelClosed
    from ray_tpu.dag.device_channel import DeviceChannel

    @ray_tpu.remote
    class _Relay:
        def pump(self, in_ch, out_ch):
            n = 0
            try:
                while True:
                    out_ch.write(in_ch.read())
                    n += 1
            except ChannelClosed:
                pass
            finally:
                in_ch.close_read()
                try:
                    out_ch.close_write(timeout=10)
                except BaseException:
                    pass
                in_ch.drain()
            return n

        def echo(self, x):
            return x

    payload = jnp.zeros((1 << 18,), dtype=jnp.float32)  # 1 MiB on device
    n = max(8, int(64 * scale))
    depth = 8  # in-flight items: ring slack / async task window
    out: List[Dict] = []

    def _record(name: str, items: int, dt: float):
        out.append({"benchmark": name, "value": round(_rate(items, dt), 1),
                    "unit": "items/s", "n": items, "trials": 3})
        out.append({"benchmark": f"{name}_gbps",
                    "value": round(2 * items / (1 << 10) / max(dt, 1e-9), 3),
                    "unit": "GiB/s", "n": items, "trials": 3})

    relay = _Relay.remote()
    in_ch = DeviceChannel(capacity=depth + 1)
    out_ch = DeviceChannel(capacity=depth + 1)
    pump_ref = relay.pump.remote(in_ch, out_ch)
    for _ in range(4):  # warmup: channel opens + jit-free steady state
        in_ch.write(payload, timeout=60)
        out_ch.read(timeout=60)
    # Fill the ring to depth once, then time windows with the pipeline kept
    # full throughout — every timed item is one write + one read at steady
    # state, never the fill/drain ramps.
    for _ in range(depth):
        in_ch.write(payload, timeout=60)
    chan_best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            in_ch.write(payload, timeout=60)
            out_ch.read(timeout=60)
        chan_best = max(chan_best, n / (time.perf_counter() - t0))
    for _ in range(depth):
        out_ch.read(timeout=60)
    _record("channel_stream_1mib", n, n / chan_best)
    in_ch.close_write(timeout=10)
    try:
        while True:
            out_ch.read(timeout=10)
    except (ChannelClosed, TimeoutError):
        pass
    out_ch.close_read()
    out_ch.drain()
    ray_tpu.get(pump_ref, timeout=60)

    for _ in range(4):
        ray_tpu.get(relay.echo.remote(payload), timeout=60)
    pending = []
    for _ in range(depth):
        pending.append(relay.echo.remote(payload))
    rpc_best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            pending.append(relay.echo.remote(payload))
            ray_tpu.get(pending.pop(0), timeout=60)
        rpc_best = max(rpc_best, n / (time.perf_counter() - t0))
    for ref in pending:
        ray_tpu.get(ref, timeout=60)
    _record("rpc_stream_1mib", n, n / rpc_best)
    try:
        ray_tpu.kill(relay)
    except Exception:
        pass
    out.extend(_bench_pipeline_step(scale))
    return out


def _bench_pipeline_step(scale: float) -> List[Dict]:
    """End-to-end pipeline steady state: a 2-stage ActorPipeline train step
    over DeviceChannels (persistent loops, static schedules, zero host
    pickling) vs the same step over per-op actor RPC (one task per fwd/bwd,
    activations through the object plane). The channel win here is the
    number the compiled-DAG work exists for — it includes everything the
    raw stream legs leave out: task dispatch, driver coordination, and
    stage overlap."""
    import jax
    import jax.numpy as jnp

    import ray_tpu
    from ray_tpu.models import llama
    from ray_tpu.parallel.pipeline import ActorPipeline

    config = llama.LlamaConfig.tiny(n_layers=4, max_seq=32,
                                    dtype=jnp.float32, remat=False)
    params = llama.init_params(config, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (8, 33), 0,
                                config.vocab_size)
    n = max(3, int(16 * scale))
    out: List[Dict] = []
    for transport in ("channel", "rpc"):
        pipe = ActorPipeline(config, params, n_stages=2, lr=1e-3,
                             transport=transport)
        for _ in range(2):  # warmup: jit compilation + loop launch
            pipe.train_step(tokens, n_microbatches=4)
        t0 = time.perf_counter()
        for _ in range(n):
            pipe.train_step(tokens, n_microbatches=4)
        dt = time.perf_counter() - t0
        pipe.shutdown()
        for actor in pipe.actors:
            try:
                ray_tpu.kill(actor)
            except Exception:
                pass
        out.append({"benchmark": f"pipeline_step_{transport}",
                    "value": round(_rate(n, dt), 2), "unit": "steps/s",
                    "n": n})
    return out


def _bench_collectives(scale: float) -> List[Dict]:
    """Out-of-graph collective data plane: chunked zero-pickle ring vs the
    legacy rank-0 hub, 4 thread-hosted TCPCommunicators over an in-memory
    KV (pure transport, no cluster in the loop). Two pairs of legs:

      * allreduce_{ring,hub}_16mib — one 16 MiB float32 allreduce at 4
        ranks; MiB/s of reduced payload (best of 3: the ring-vs-hub RATIO
        is the tracked number and one descheduling blip inside a trial on
        a small box would corrupt it).
      * ddp_grads_{bucketed,flat} — allreduce_gradients steady state on a
        32-leaf ~8 MiB gradient pytree: per-dtype 4 MiB buckets launched
        async as they fill (overlapped) vs the old concatenate-everything
        single blocking reduction.
    """
    import threading

    import numpy as np

    from ray_tpu.collective.cpu_group import TCPCommunicator
    from ray_tpu.train.backend import reduce_gradients

    out: List[Dict] = []
    kv, kv_lock = {}, threading.Lock()

    def kv_put(key, value):
        with kv_lock:
            kv[key] = value

    def kv_get(key):
        with kv_lock:
            return kv.get(key)

    world = 4

    def make_group(name, **kwargs):
        comms = [None] * world

        def build(r):
            comms[r] = TCPCommunicator(r, world, name, kv_put, kv_get,
                                       timeout=60, **kwargs)

        ts = [threading.Thread(target=build, args=(r,), daemon=True,
                               name=f"bench-build-{r}") for r in range(world)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        assert all(comms), comms
        return comms

    def par(comms, fn):
        errs = []

        def run_rank(c):
            try:
                fn(c)
            except BaseException as e:  # pragma: no cover
                errs.append(e)

        ts = [threading.Thread(target=run_rank, args=(c,), daemon=True,
                               name=f"bench-rank-{c.rank}") for c in comms]
        for t in ts:
            t.start()
        for t in ts:
            t.join(300)
        if errs:
            raise errs[0]

    mib = 16
    payload = np.ones((mib << 20) // 4, dtype=np.float32)
    for algo in ("hub", "ring"):
        comms = make_group(f"bench-allreduce-{algo}", topology=algo)
        try:
            par(comms, lambda c: c.allreduce(np.ones(64, np.float32), "sum"))
            best = 0.0
            for _ in range(3):
                t0 = time.perf_counter()
                par(comms, lambda c: c.allreduce(payload, "sum"))
                best = max(best, mib / (time.perf_counter() - t0))
            out.append({"benchmark": f"allreduce_{algo}_16mib",
                        "value": round(best, 1), "unit": "MiB/s",
                        "n": mib, "trials": 3})
        finally:
            for c in comms:
                c.close()

    # DDP gradient sync: same tree, flat (the old np.concatenate-everything
    # path) vs bucketed-overlapped (the shipped reduce_gradients).
    grads = {f"layer{i}": np.ones(1 << 16, np.float32) for i in range(32)}

    def flat_reduce(comm):
        flat = np.concatenate([v.ravel() for v in grads.values()])
        reduced = comm.allreduce(flat, op="mean")
        offset, res = 0, {}
        for k, v in grads.items():
            res[k] = reduced[offset:offset + v.size].reshape(v.shape)
            offset += v.size
        return res

    comms = make_group("bench-ddp")
    try:
        steps = max(2, int(4 * scale))
        for name, step_fn in (("ddp_grads_flat", flat_reduce),
                              ("ddp_grads_bucketed",
                               lambda c: reduce_gradients(c, grads))):
            par(comms, step_fn)  # warmup: links + first-op ramp
            best = 0.0
            for _ in range(3):
                t0 = time.perf_counter()
                for _ in range(steps):
                    par(comms, step_fn)
                best = max(best, steps / (time.perf_counter() - t0))
            out.append({"benchmark": name, "value": round(best, 2),
                        "unit": "steps/s", "n": steps, "trials": 3})
    finally:
        for c in comms:
            c.close()
    return out


def _bench_serve_mixed(scale: float) -> List[Dict]:
    """LLM serving plane (llm/router.py + llm/disagg.py), in-process — two
    tiny fp32 engines on CPU, no serve actors in the loop, so the legs
    isolate routing policy and prefill placement rather than RPC cost.

      * serve_mixed_*_{affinity,random} — a shared-system-prompt workload
        (6 distinct 33-token prefixes, repeated) routed by RouterCore
        prefix affinity vs uniform random over 2 replicas: p99 TTFT,
        aggregate tokens/s, and prefix tokens saved (the hit-rate signal).
      * serve_{colocated,disagg}_itl_p99_ms — a chatty stream's p99
        inter-token gap while long prompts continuously arrive: colocated
        (prefill chunks interleave with the chatty decode on one replica)
        vs disaggregated (a PrefillServer runs the long prefills and
        streams KV pages over the handoff wire; decode only decodes).
    """
    import random as _random
    import threading

    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.llm.disagg import PrefillServer
    from ray_tpu.llm.router import RouterCore
    from ray_tpu.llm.sampling import SamplingParams
    from ray_tpu.llm.serving import LLMConfig, LLMServer, build_engine
    from ray_tpu.models import llama

    out: List[Dict] = []
    config = llama.LlamaConfig.tiny(vocab_size=128, max_seq=256,
                                    dtype=jnp.float32)

    def cfg(**kw):
        base = dict(model_config=config, num_kv_blocks=128, block_size=8,
                    max_batch_size=4, prefill_chunk=8, warmup_buckets="off")
        base.update(kw)
        return LLMConfig(**base)

    # ---- router: prefix affinity vs random over 2 replicas -------------
    sys_prompts = [[(s * 11 + 5 * i + 2) % 128 for i in range(65)]
                   for s in range(6)]
    reps = max(2, int(3 * scale))
    order = [sys_prompts[i % 6] for i in range(6 * reps)]

    def drive(eng, prompt, max_tokens=8):
        t0 = time.perf_counter()
        eng.add_request(prompt, SamplingParams(max_tokens=max_tokens))
        ttft, n = None, 0
        while eng.has_unfinished():
            for o in eng.step():
                if o.new_token_ids and ttft is None:
                    ttft = time.perf_counter() - t0
                n += len(o.new_token_ids)
        return ttft if ttft is not None else time.perf_counter() - t0, n

    for mode in ("affinity", "random"):
        # Best of 2 trials (fresh engines + router state each): tokens/s on
        # a small shared box swings ~20% on scheduler noise, while the
        # prefix-savings number is deterministic per policy.
        best_tps, best_ttft, saved, total_tokens = 0.0, float("inf"), 0, 0
        for _ in range(2):
            engines = [build_engine(cfg()) for _ in range(2)]
            for e in engines:  # pay first-hit XLA compiles outside timing
                drive(e, [(3 * i + 1) % 128 for i in range(33)])
            core = RouterCore(2, block_size=8)
            rng = _random.Random(0)
            ttfts: List[float] = []
            total_tokens = 0
            t0 = time.perf_counter()
            for p in order:
                idx = (core.pick(p)[0] if mode == "affinity"
                       else rng.randrange(2))
                ttft, n = drive(engines[idx], p)
                ttfts.append(ttft)
                total_tokens += n
            elapsed = time.perf_counter() - t0
            best_tps = max(best_tps, total_tokens / elapsed)
            best_ttft = min(best_ttft, float(np.percentile(ttfts, 99)))
            saved = sum(e.block_manager.prefix_tokens_saved for e in engines)
        out.append({"benchmark": f"serve_mixed_ttft_p99_ms_{mode}",
                    "value": round(best_ttft * 1e3, 2),
                    "unit": "ms", "n": len(order), "trials": 2})
        out.append({"benchmark": f"serve_mixed_tokens_per_s_{mode}",
                    "value": round(best_tps, 1),
                    "unit": "tokens/s", "n": total_tokens, "trials": 2})
        out.append({"benchmark": f"serve_mixed_prefix_tokens_saved_{mode}",
                    "value": saved, "unit": "tokens", "n": len(order)})

    # ---- disaggregation: chatty inter-token latency under long-prompt
    # pressure. Each long prompt is unique (a shared prefix would let the
    # prefix cache hide the very prefill cost the leg measures).
    chatty_tokens = max(40, int(120 * scale))
    long_seq = [0]

    def next_long():
        long_seq[0] += 1
        j = long_seq[0]
        return [(13 * i + 7 * j + j * j) % 128 for i in range(225)]

    def chatty_gaps(server, submit_long):
        stop = threading.Event()
        done = [0]                 # pressure completions (2 tokens each)

        def pressure():
            while not stop.is_set():
                try:
                    submit_long()
                except Exception:
                    return
                done[0] += 1

        # Two pressure threads keep a long prefill in flight continuously —
        # a lone thread leaves idle windows between requests that let the
        # colocated leg decode unimpeded and corrupt the comparison.
        ts = [threading.Thread(target=pressure, daemon=True,
                               name=f"bench-pressure-{i}")
              for i in range(2)]
        gen = server.completions_stream(
            {"prompt": [3, 1, 4, 1, 5], "max_tokens": chatty_tokens})
        next(gen)                  # chatty decoding before pressure starts
        for t in ts:
            t.start()
        gaps, t0 = [], time.perf_counter()
        last = t0
        for chunk in gen:
            now = time.perf_counter()
            if chunk.get("token") is not None:
                gaps.append(now - last)
                last = now
        elapsed = last - t0
        stop.set()
        for t in ts:
            t.join(60)
        return gaps, elapsed, done[0]

    # One-shot 225-token prefill chunks: the regime disaggregation targets
    # is an expensive chunk stalling the decode batch (big models / long
    # prompts); chunk=8 on the tiny model makes a chunk as cheap as a
    # decode step and measures nothing.
    # colocated pins unified_ticks=False: it IS the split-phase baseline the
    # unified leg is measured against. The unified leg runs the same server
    # config with unified ragged ticks (the default) and a 64-token budget:
    # the composer slices the 225-token prefills across ticks with the
    # chatty decode row riding EVERY launch, so the inter-token gap is one
    # small mixed launch instead of a whole 256-token chunk dispatch plus a
    # decode tick. (The split path can't do this: its scheduling quantum IS
    # the prefill chunk, and decode waits out each chunk.)
    colo = LLMServer(cfg(prefill_chunk=256, unified_ticks=False))
    unified = LLMServer(cfg(prefill_chunk=256, token_budget=64))
    decode = LLMServer(cfg(prefill_chunk=256, disaggregate=1))
    addr = decode.handoff_address()

    # The prefill tier runs on its own hardware in production; on this
    # shared bench box, running its compute concurrently would bill the
    # decode leg for the very work disaggregation moves off-replica. So
    # prefill the long prompts UNTIMED and have the pressure thread replay
    # the captured handoffs over the real wire — socket receive, page
    # adoption, and the adopted requests' decode ARE the decode replica's
    # steady-state costs, and they stay in the timed window.
    from ray_tpu.llm.disagg import send_handoff

    peng = build_engine(cfg(prefill_chunk=256), prefill_only=True)

    def capture_handoffs(n):
        pre = []
        for _ in range(n):
            rid = peng.add_request(next_long(), SamplingParams(max_tokens=2))
            while not any(o.request_id == rid for o in peng.step()):
                pass
            state = peng.export_request(rid)
            blocks = state.pop("blocks")
            k, v = peng.runner.gather_pages(blocks)
            peng.block_manager.release_blocks(blocks)
            pre.append((state, k, v))
        return pre

    def replay_handoff(pre):
        state, k, v = pre.pop()   # IndexError when drained ends the thread
        send_handoff(addr, state, k, v)
        decode.completions_collect(state["id"])

    # The unified leg runs with tracing OFF and the traced leg — the SAME
    # server, same workload, already warm — with tracing ON: their tokens/s
    # ratio is the per-request tracing overhead, budgeted at <=2% (the
    # spans are ring appends and a handful of time.time() calls; anything
    # bigger means a span landed on the per-token hot path). Sharing the
    # engine keeps compile/warmup state identical across the pair.
    from ray_tpu.util import tracing as _tracing

    legs = (("colocated", colo,
             lambda _pre: colo.completions(
                 {"prompt": next_long(), "max_tokens": 2}),
             lambda: None, None),
            ("unified", unified,
             lambda _pre: unified.completions(
                 {"prompt": next_long(), "max_tokens": 2}),
             lambda: None, False),
            ("traced", unified,
             lambda _pre: unified.completions(
                 {"prompt": next_long(), "max_tokens": 2}),
             lambda: None, True),
            ("disagg", decode, replay_handoff,
             lambda: capture_handoffs(80), None))
    tps_by_leg: Dict[str, float] = {}
    # Best of 2 trials per leg: a descheduling blip in the pressure thread
    # on a small box corrupts the tail the leg exists to compare.
    for name, server, submit_long, setup, trace_on in legs:
        was_enabled = _tracing.enabled()
        if trace_on is not None:
            _tracing.set_enabled(trace_on)
        try:
            best, best_tps, n = float("inf"), 0.0, 0
            for _ in range(2):
                pre = setup()
                gaps, elapsed, done = chatty_gaps(server,
                                                  lambda: submit_long(pre))
                n = len(gaps)
                best = min(best, float(np.percentile(gaps, 99)))
                if elapsed > 0:
                    best_tps = max(best_tps,
                                   (len(gaps) + 2 * done) / elapsed)
        finally:
            _tracing.set_enabled(was_enabled)
        out.append({"benchmark": f"serve_{name}_itl_p99_ms",
                    "value": round(best * 1e3, 2),
                    "unit": "ms", "n": n, "trials": 2})
        # tokens/s under the same pressure (chatty + pressure completions):
        # the guard that a better tail wasn't bought by starving throughput.
        # The disagg leg's pressure tokens ride pre-captured handoffs, not
        # comparable work — only the apples-to-apples legs report it.
        if name in ("colocated", "unified", "traced"):
            tps_by_leg[name] = best_tps
            out.append({"benchmark": f"serve_{name}_tokens_per_s",
                        "value": round(best_tps, 1),
                        "unit": "tokens/s", "n": n, "trials": 2})
    if tps_by_leg.get("unified") and tps_by_leg.get("traced"):
        overhead = 100.0 * (1.0 - tps_by_leg["traced"]
                            / tps_by_leg["unified"])
        out.append({"benchmark": "serve_tracing_overhead_pct",
                    "value": round(overhead, 2), "unit": "%",
                    "n": 1, "trials": 2})
    return out


def _bench_serve_resilience(scale: float) -> List[Dict]:
    """LLM fleet resilience (llm/router.py FleetSupervisor + llm/serving.py
    migrate_sessions), in-process — tiny fp32 engines, no actors, so the
    legs price the recovery MACHINERY rather than RPC or respawn cost.

      * serve_failover_recovery_ms — wall-clock from a replica call
        failing mid-request to the router handing back the COMPLETED
        response replayed on the survivor (ejection + affinity prune +
        seeded replay, end to end).
      * serve_migrate_session_ms — marginal cost of live-draining one
        mid-decode session: export + KV-page gather, raw-frame wire,
        adoption on a QUIET target. One session per timed migrate, and
        engines are reused across trials, so min-of-trials prices the
        warm machinery — not XLA compiles, and not the target's resumed
        decode of earlier adoptees (that is the request's own remaining
        work, which on this 1-core box would otherwise serialize into
        the measurement).
      * serve_reprefill_baseline_ms — what the same session costs WITHOUT
        migration: full re-prefill of the accumulated context to the
        first token, same reuse discipline. On the tiny CPU model
        re-prefill is cheap, so the gap here is a floor, not the
        headline — it widens with model size and context length.
    """
    import threading

    import jax.numpy as jnp

    from ray_tpu.llm.router import FleetSupervisor, LocalReplica, RouterCore
    from ray_tpu.llm.sampling import SamplingParams
    from ray_tpu.llm.serving import LLMConfig, LLMServer, build_engine
    from ray_tpu.models import llama

    out: List[Dict] = []
    config = llama.LlamaConfig.tiny(vocab_size=128, max_seq=256,
                                    dtype=jnp.float32)

    def cfg(**kw):
        base = dict(model_config=config, num_kv_blocks=128, block_size=8,
                    max_batch_size=4, prefill_chunk=8, warmup_buckets="off")
        base.update(kw)
        return LLMConfig(**base)

    def prompt(seed, n=65):
        return [(seed * 11 + 5 * i + 2) % 128 for i in range(n)]

    # ---- failover recovery: dead replica -> replayed completion --------
    class DeadReplica:
        """First-pick victim: takes the request, then the 'actor' dies."""

        def completions(self, request):
            raise ConnectionError("replica died mid-call")

        def engine_stats(self):
            return {"running": 0, "waiting": 0, "prefilling": 0,
                    "free_kv_blocks": 128, "total_kv_blocks": 128}

        def abort(self, rid):
            return False

    survivor = LLMServer(cfg())
    survivor.completions({"prompt": prompt(0), "max_tokens": 4})  # compiles
    trials = max(3, int(5 * scale))
    recovery: List[float] = []
    for t in range(trials):
        core = RouterCore(2, fail_threshold=1)
        sup = FleetSupervisor(core, [LocalReplica(DeadReplica(), "dead"),
                                     LocalReplica(survivor, "live")])
        # Pin the session to the dead replica so the timed request always
        # pays the failure (pow2 would dodge it half the time).
        core._session_owner["bench"] = 0
        t0 = time.perf_counter()
        resp = sup.completions({"prompt": prompt(t + 1), "max_tokens": 8,
                                "session_id": "bench"})
        recovery.append(time.perf_counter() - t0)
        assert "choices" in resp and sup.failovers == 1, resp
    out.append({"benchmark": "serve_failover_recovery_ms",
                "value": round(min(recovery) * 1e3, 2),
                "unit": "ms", "n": trials})

    # ---- live migration vs re-prefill ----------------------------------
    # A mid-size model for this pair: migration moves KV BYTES while
    # re-prefill re-runs the MODEL over every context token, so the
    # 2-layer/d64 toy (where 129 tokens prefill in ~8 ms) would understate
    # the gap to nothing. d256x4 keeps compile time tolerable on a CI box
    # while giving prefill real work; production models widen it further.
    mid = llama.LlamaConfig(vocab_size=128, d_model=256, n_layers=4,
                            n_heads=8, n_kv_heads=4, d_ff=1024,
                            max_seq=256, dtype=jnp.float32)
    trials = max(3, int(4 * scale))
    ctx_tokens = 129          # long context = the cost re-prefill repays
    src = LLMServer(cfg(model_config=mid))
    dst = LLMServer(cfg(model_config=mid))
    migrate_ms, reprefill_ms = [], []
    for trial in range(trials):
        rid = f"mig-{trial}"
        req = {"prompt": prompt(trial + 7, ctx_tokens), "max_tokens": 64,
               "request_id": rid}
        th = threading.Thread(target=lambda r=dict(req):
                              _swallow(src.completions, r), daemon=True,
                              name=f"bench-migrate-src-{trial}")
        th.start()
        deadline = time.monotonic() + 30
        while (src.engine_stats()["running"] < 1
               and time.monotonic() < deadline):
            time.sleep(0.005)
        t0 = time.perf_counter()
        summary = src.migrate_sessions(dst.handoff_address())
        if len(summary["migrated"]) == 1:
            migrate_ms.append((time.perf_counter() - t0) * 1e3)
        th.join(30)
        src.resume_admission()
        # Let the adoptee decode out so the next trial's target is quiet.
        deadline = time.monotonic() + 30
        while (dst.engine_stats()["running"] > 0
               and time.monotonic() < deadline):
            time.sleep(0.005)
    # Baseline: the same accumulated context re-prefilled from scratch to
    # its first token (what failover-without-migration costs). One engine
    # reused across trials for the same warm-compile discipline.
    eng = build_engine(cfg(model_config=mid))
    for trial in range(trials):
        t0 = time.perf_counter()
        rid = eng.add_request(prompt(trial + 7, ctx_tokens),
                              SamplingParams(max_tokens=1))
        while not any(o.request_id == rid and o.new_token_ids
                      for o in eng.step()):
            pass
        reprefill_ms.append((time.perf_counter() - t0) * 1e3)
    out.append({"benchmark": "serve_migrate_session_ms",
                "value": round(min(migrate_ms), 2) if migrate_ms else -1.0,
                "unit": "ms", "n": 1, "trials": trials})
    out.append({"benchmark": "serve_reprefill_baseline_ms",
                "value": round(min(reprefill_ms), 2),
                "unit": "ms", "n": 1, "trials": trials})
    return out


def _swallow(fn, *args):
    """Bench collector thread body: resilience errors are the scenario."""
    try:
        fn(*args)
    except Exception:
        pass


def _bench_serve_prefix_store(scale: float) -> List[Dict]:
    """Tiered prefix store (llm/prefix_store.py): what adopting a spilled
    prefix from the GCS cluster table costs vs re-prefilling it.

      * serve_prefix_adopt_ms — first token for the SAME d256x4 /
        129-token contexts as serve_reprefill_baseline_ms, but the
        context's 16 KV blocks were published into the cluster prefix
        table by a (since churned-out) owner engine, so the adopter pays
        a table lookup + page scatter + a 1-block tail prefill instead of
        re-running the model over the full context. The table transport
        is the GCS handler invoked in-process, so the leg prices the
        store machinery (codec, verification, scatter), not RPC.
    """
    import asyncio

    import jax.numpy as jnp

    from ray_tpu.llm.prefix_store import ClusterPrefixStore, HostPrefixTier
    from ray_tpu.llm.sampling import SamplingParams
    from ray_tpu.llm.serving import LLMConfig, build_engine
    from ray_tpu.models import llama
    from ray_tpu.runtime.gcs.server import GcsServer

    mid = llama.LlamaConfig(vocab_size=128, d_model=256, n_layers=4,
                            n_heads=8, n_kv_heads=4, d_ff=1024,
                            max_seq=256, dtype=jnp.float32)
    cfg = LLMConfig(model_config=mid, num_kv_blocks=48, block_size=8,
                    max_batch_size=4, prefill_chunk=8, warmup_buckets="off")

    def prompt(seed, n=65):
        # Same generator as _bench_serve_resilience: seeds trial+7 give
        # bit-identical contexts to the re-prefill baseline's.
        return [(seed * 11 + 5 * i + 2) % 128 for i in range(n)]

    srv = GcsServer()

    def transport(method, m, payload=b""):
        r = asyncio.run(getattr(srv, f"handle_{method}")(None, m, payload))
        return r.m, r.payload

    trials = max(3, int(4 * scale))
    ctx_tokens = 129

    # The owner: a tiny host tier whose watermark demotes straight into
    # the cluster table. Serving each context then churning the pool
    # publishes the context's blocks — the owner then "dies" (is dropped).
    src = build_engine(cfg)
    src.attach_prefix_store(
        host_tier=HostPrefixTier(96 << 10, low_watermark=0.05),
        cluster_store=ClusterPrefixStore(8, replica="bench-owner",
                                         transport=transport))

    def first_token(eng, toks):
        rid = eng.add_request(toks, SamplingParams(max_tokens=1))
        while not any(o.request_id == rid and o.new_token_ids
                      for o in eng.step()):
            pass

    for trial in range(-1, trials):          # -1 = warmup context
        first_token(src, prompt(trial + 7, ctx_tokens))
        for f in range(6):                   # churn: evict -> spill -> demote
            first_token(src, prompt(1000 + trial * 10 + f, 41))
    published = src.cluster_store.published
    del src

    adopter = build_engine(cfg)
    adopter.attach_prefix_store(
        cluster_store=ClusterPrefixStore(8, replica="bench-adopter",
                                         transport=transport))
    first_token(adopter, prompt(6, ctx_tokens))  # warm compile, via adopt
    adopt_ms: List[float] = []
    for trial in range(trials):
        hits0 = adopter.cluster_prefix_hits
        t0 = time.perf_counter()
        first_token(adopter, prompt(trial + 7, ctx_tokens))
        dt = (time.perf_counter() - t0) * 1e3
        if adopter.cluster_prefix_hits - hits0 >= ctx_tokens // 8 - 1:
            adopt_ms.append(dt)              # only count real adoptions
    return [{"benchmark": "serve_prefix_adopt_ms",
             "value": round(min(adopt_ms), 2) if adopt_ms else -1.0,
             "unit": "ms", "n": 1, "trials": trials,
             "published_blocks": published}]


def _bench_serve_load_sweep(scale: float) -> List[Dict]:
    """Closed-loop load sweep over fleet sizes (ROADMAP 2b): N client
    threads each keep exactly one request in flight against a
    FleetSupervisor fronting 1 then 2 in-process replicas, reporting
    decode throughput and p99 TTFT per (replicas, clients) point. Every
    third request asks for max_tokens=1, so its wall latency IS the
    time-to-first-token under the surrounding load — no streaming hooks
    needed. The last point repeats (2 replicas, 4 clients) with a
    drain-based scale-down fired mid-window: the sweep's churn leg, where
    every request must still complete (drain migrates, it never kills).
    """
    import threading

    import jax.numpy as jnp

    from ray_tpu.llm.router import FleetSupervisor, LocalReplica, RouterCore
    from ray_tpu.llm.serving import LLMConfig, LLMServer
    from ray_tpu.models import llama

    config = llama.LlamaConfig.tiny(vocab_size=128, max_seq=256,
                                    dtype=jnp.float32)
    cfg = LLMConfig(model_config=config, num_kv_blocks=128, block_size=8,
                    max_batch_size=4, prefill_chunk=8, warmup_buckets="off")

    def prompt(seed, n=33):
        return [(seed * 11 + 5 * i + 2) % 128 for i in range(n)]

    servers = [LLMServer(cfg), LLMServer(cfg)]
    for s in servers:
        s.completions({"prompt": prompt(0), "max_tokens": 4})  # compiles

    def run_point(n_replicas, clients, n_reqs, churn=False):
        sup = FleetSupervisor(
            RouterCore(n_replicas, block_size=8),
            [LocalReplica(servers[i], f"sweep-{i}")
             for i in range(n_replicas)])
        lock = threading.Lock()
        state = {"next": 0, "tokens": 0, "ttft": [], "errors": 0}

        def client():
            while True:
                with lock:
                    i = state["next"]
                    state["next"] += 1
                if i >= n_reqs:
                    return
                probe = i % 3 == 0
                t0 = time.perf_counter()
                try:
                    resp = sup.completions(
                        {"prompt": prompt(100 + i),
                         "max_tokens": 1 if probe else 16})
                except Exception:
                    with lock:
                        state["errors"] += 1
                    continue
                dt = time.perf_counter() - t0
                with lock:
                    state["tokens"] += len(
                        resp["choices"][0]["token_ids"])
                    if probe:
                        state["ttft"].append(dt)

        threads = [threading.Thread(target=client, daemon=True,
                                    name=f"sweep-client-{c}")
                   for c in range(clients)]
        t0 = time.perf_counter()
        for th in threads:
            th.start()
        if churn:
            while state["next"] < n_reqs // 3:
                time.sleep(0.002)
            _swallow(sup.drain_replica, 1, 0)  # scale-down under load
        for th in threads:
            th.join(300)
        wall = time.perf_counter() - t0
        ttft = sorted(state["ttft"])
        p99 = ttft[min(len(ttft) - 1, int(0.99 * len(ttft)))] if ttft \
            else -1.0
        return (state["tokens"] / wall, p99 * 1e3, state["errors"])

    out: List[Dict] = []
    n_reqs = max(9, int(18 * scale))
    for n_replicas, clients, churn in ((1, 1, False), (1, 4, False),
                                       (2, 4, False), (2, 4, True)):
        tps, p99_ms, errors = run_point(n_replicas, clients, n_reqs,
                                        churn=churn)
        tag = f"r{n_replicas}_c{clients}" + ("_churn" if churn else "")
        out.append({"benchmark": f"serve_sweep_tokens_per_s_{tag}",
                    "value": round(tps, 1), "unit": "tokens/s",
                    "n": n_reqs, "errors": errors})
        out.append({"benchmark": f"serve_sweep_ttft_p99_ms_{tag}",
                    "value": round(p99_ms, 2), "unit": "ms",
                    "n": n_reqs, "errors": errors})
    return out


def _bench_rlhf(scale: float) -> List[Dict]:
    """RLHF pipeline (rlhf/): the full rollout -> PPO update -> weight-sync
    loop on a tiny fp32 model, once per placement mode.

      * rlhf_colocated_steps_per_s — generator in-process with the driver,
        weight sync via device-channel hot-swap.
      * rlhf_disagg_steps_per_s — generator as a dedicated actor, weight
        sync via object-plane publish + fanout broadcast.
      * rlhf_weight_sync_ms — mean per-iteration sync latency, one value
        per mode. The gap between the modes is the sync tax the adaptive
        placement policy trades against rollout/update goodput.
    """
    from ray_tpu.rlhf import RLHFConfig, RLHFTrainer

    out: List[Dict] = []
    iters = max(2, int(3 * scale))
    model = dict(vocab_size=128, d_model=32, n_layers=2, n_heads=2,
                 n_kv_heads=2, d_ff=64, max_seq=128)
    for mode in ("colocated", "disaggregated"):
        trainer = RLHFTrainer(RLHFConfig(
            model_kwargs=model, placement_mode=mode,
            iterations=iters, prompts_per_iter=2, prompt_len=4,
            max_new_tokens=4, run_name=f"bench-rlhf-{mode}"))
        try:
            t0 = time.perf_counter()
            result = trainer.run()
            elapsed = time.perf_counter() - t0
        finally:
            trainer.shutdown()
        tag = "colocated" if mode == "colocated" else "disagg"
        out.append({"benchmark": f"rlhf_{tag}_steps_per_s",
                    "value": round(iters / elapsed, 3),
                    "unit": "steps/s", "n": iters, "trials": 1})
        sync = result["sync_ms"]
        out.append({"benchmark": "rlhf_weight_sync_ms",
                    "value": round(sum(sync) / max(1, len(sync)), 2),
                    "unit": f"ms ({tag})", "n": len(sync), "trials": 1})
    return out


def run_scale_envelope(n_requests: int = 192, fake_nodes: int = 1000,
                       trials: int = 3) -> Dict[str, Dict]:
    """Control-plane scale envelope: lease throughput and time-to-first-
    lease against a real GCS + real raylet carrying a 1k-fake-node
    cluster view, with worker SPAWN stubbed out (granted leases resolve
    to instantly-ready fake workers) so the numbers isolate the
    scheduling/RPC path — batched LeaseBatchRequestMsg frames vs one
    lease_worker2 call per request.

    Returns {leg_name: {"value", "unit", "n", "trials"}}; shared by the
    microbench CLI and tests/test_scale_envelope.py.
    """
    import asyncio
    import os
    import tempfile
    import time as _time
    from types import SimpleNamespace

    from ray_tpu.config import cfg
    from ray_tpu.runtime import wire
    from ray_tpu.runtime.gcs.server import GcsServer, NodeRecord
    from ray_tpu.runtime.raylet.raylet import Raylet, WorkerHandle
    from ray_tpu.runtime.rpc import RpcClient

    async def _run() -> Dict[str, Dict]:
        gcs = await GcsServer().start()
        # A 1k-node cluster's worth of node records: the raylet's first
        # heartbeat pulls this as its full view snapshot, and every GCS
        # pass that walks nodes walks all of them.
        fakes = []
        for i in range(fake_nodes):
            nid = b"fake" + i.to_bytes(12, "big")
            rec = NodeRecord(nid, ("127.0.0.1", 30000 + i), {"CPU": 4.0},
                             "", False, {})
            gcs._nodes[nid] = rec
            gcs._bump_view(rec)
            fakes.append(rec)
        session = tempfile.mkdtemp(prefix="ray-tpu-scale-bench-")
        raylet = Raylet(gcs.address, session, {"CPU": 1e9}, {},
                        object_store_memory=32 << 20)

        def fake_spawn():
            wid = os.urandom(16)
            proc = SimpleNamespace(poll=lambda: None,
                                   terminate=lambda: None,
                                   kill=lambda: None,
                                   wait=lambda timeout=None: 0, pid=0)
            h = WorkerHandle(wid, proc)
            h.address = ("127.0.0.1", 1)
            h.ready.set()
            raylet._workers[wid] = h
            return h

        raylet._spawn_worker = fake_spawn
        await raylet.start()

        waiters: Dict[bytes, asyncio.Future] = {}

        async def on_push(method, data):
            if method != "lease_grant":
                return
            fut = waiters.pop(data.get("req_id"), None)
            if fut is not None and not fut.done():
                fut.set_result(
                    wire.LeaseReplyMsg.decode(data["m"]).to_reply())

        client = RpcClient(*raylet.server.address, on_push=on_push)
        await client.connect(timeout=15)

        def _reqs(n):
            return [wire.LeaseRequestMsg(resources={"CPU": 1.0},
                                         req_id=os.urandom(8))
                    for _ in range(n)]

        async def lease_batched(reqs) -> List[asyncio.Future]:
            """One lease_batch2 frame; returns a future per entry
            (inline entries resolved, pending ones resolve via push)."""
            loop = asyncio.get_event_loop()
            futs = {r.req_id: loop.create_future() for r in reqs}
            waiters.update(futs)
            encoded = await client.call(
                "lease_batch2",
                m=wire.LeaseBatchRequestMsg(entries=reqs).encode())
            reply = wire.LeaseBatchReplyMsg.decode(encoded)
            for entry in reply.entries:
                fut = futs.get(entry.req_id)
                if fut is not None and not fut.done():
                    waiters.pop(entry.req_id, None)
                    fut.set_result(entry.to_reply())
            return list(futs.values())

        async def lease_per_item(req) -> dict:
            encoded = await client.call("lease_worker2", m=req.encode())
            return wire.LeaseReplyMsg.decode(encoded).to_reply()

        def _refresh_fakes():
            now = _time.monotonic()
            for rec in fakes:
                rec.last_heartbeat = now

        batch_max = cfg().lease_batch_max

        async def leg_batched(n) -> float:
            _refresh_fakes()
            reqs = _reqs(n)
            t0 = _time.perf_counter()
            futs = await asyncio.gather(
                *(lease_batched(reqs[i:i + batch_max])
                  for i in range(0, n, batch_max)))
            replies = await asyncio.gather(
                *(f for group in futs for f in group))
            dt = _time.perf_counter() - t0
            assert all(r.get("ok") for r in replies)
            return dt

        async def leg_per_item(n) -> float:
            _refresh_fakes()
            reqs = _reqs(n)
            t0 = _time.perf_counter()
            replies = await asyncio.gather(*(lease_per_item(r)
                                             for r in reqs))
            dt = _time.perf_counter() - t0
            assert all(r.get("ok") for r in replies)
            return dt

        async def leg_ttfl(batched: bool) -> float:
            """Time from frame(s) leaving the client to the FIRST granted
            lease, cold queues, 1k-node view live on both sides."""
            _refresh_fakes()
            reqs = _reqs(batch_max)
            t0 = _time.perf_counter()
            if batched:
                futs = await lease_batched(reqs)
                done, rest = await asyncio.wait(
                    futs, return_when=asyncio.FIRST_COMPLETED)
            else:
                done, rest = await asyncio.wait(
                    [asyncio.ensure_future(lease_per_item(r))
                     for r in reqs],
                    return_when=asyncio.FIRST_COMPLETED)
            dt = _time.perf_counter() - t0
            assert next(iter(done)).result().get("ok")
            await asyncio.gather(*rest)  # drain so legs don't overlap
            return dt

        try:
            best: Dict[str, float] = {}
            for _ in range(trials):
                dt = await leg_batched(n_requests)
                best["sched_tasks_per_s"] = max(
                    best.get("sched_tasks_per_s", 0.0),
                    _rate(n_requests, dt))
                dt = await leg_per_item(n_requests)
                best["sched_tasks_per_s_per_item"] = max(
                    best.get("sched_tasks_per_s_per_item", 0.0),
                    _rate(n_requests, dt))
                best["time_to_first_lease_1k_fake_nodes"] = min(
                    best.get("time_to_first_lease_1k_fake_nodes",
                             float("inf")),
                    await leg_ttfl(batched=True))
                best["time_to_first_lease_1k_fake_nodes_per_item"] = min(
                    best.get("time_to_first_lease_1k_fake_nodes_per_item",
                             float("inf")),
                    await leg_ttfl(batched=False))
            return {
                name: {"value": round(v, 1 if "per_s" in name else 4),
                       "unit": "leases/s" if "per_s" in name else "s",
                       "n": (n_requests if "per_s" in name else batch_max),
                       "trials": trials}
                for name, v in best.items()}
        finally:
            await client.close()
            raylet._shutdown.set()
            try:
                await asyncio.wait_for(raylet._cleanup(), timeout=10)
            except Exception:
                pass
            if gcs._health_task is not None:
                gcs._health_task.cancel()
            await gcs.server.close()

    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(_run())
    finally:
        loop.close()


def _bench_checkpoint(scale: float) -> List[Dict]:
    """Checkpoint plane (checkpoint/): what a train step actually stalls
    for, per save of a ~64 MiB fp32 state, best of 3.

      * ckpt_sync_stall_ms — the old way: snapshot + serialize + fsync +
        commit inline with the step.
      * ckpt_async_stall_ms — `save_async` return latency: the
        device->host snapshot only; persistence runs on the background
        thread (flushed between trials so runs don't overlap).
      * ckpt_restore_reshard_ms — read a 4-way checkpoint back as one
        rank of a 2-way world (manifest read + global reassembly +
        re-slice), the elastic-restore path.
    """
    import os
    import shutil
    import tempfile

    import numpy as np

    from ray_tpu.checkpoint import CheckpointPlane, restore_shard, save_sharded

    mib = max(8, int(64 * scale))
    n_arrays = 8
    per = (mib * (1 << 20)) // (4 * n_arrays)
    tree = {f"layer_{i}": np.arange(per, dtype=np.float32) + i
            for i in range(n_arrays)}
    root = tempfile.mkdtemp(prefix="ckpt-bench-")
    plane = CheckpointPlane()
    out: List[Dict] = []
    try:
        sync_ms, async_ms = [], []
        for trial in range(3):
            d = os.path.join(root, f"sync-{trial}")
            t0 = time.perf_counter()
            save_sharded(tree, d, name="state", rank=0, world=1, step=trial)
            sync_ms.append((time.perf_counter() - t0) * 1e3)
        for trial in range(3):
            d = os.path.join(root, f"async-{trial}")
            t0 = time.perf_counter()
            plane.save_async(tree, d, name="state", rank=0, world=1,
                             step=trial)
            async_ms.append((time.perf_counter() - t0) * 1e3)
            plane.flush(60)
        out.append({"benchmark": "ckpt_sync_stall_ms",
                    "value": round(min(sync_ms), 3),
                    "unit": f"ms ({mib} MiB)", "n": 1, "trials": 3})
        out.append({"benchmark": "ckpt_async_stall_ms",
                    "value": round(min(async_ms), 3),
                    "unit": f"ms ({mib} MiB)", "n": 1, "trials": 3})
        d4 = os.path.join(root, "sharded-4way")
        for r in range(4):
            save_sharded(tree, d4, name="state", rank=r, world=4)
        reshard_ms = []
        for _ in range(3):
            t0 = time.perf_counter()
            restore_shard(d4, rank=0, world=2, name="state")
            reshard_ms.append((time.perf_counter() - t0) * 1e3)
        out.append({"benchmark": "ckpt_restore_reshard_ms",
                    "value": round(min(reshard_ms), 3),
                    "unit": f"ms ({mib} MiB, 4->2)", "n": 1, "trials": 3})
    finally:
        plane.close()
        shutil.rmtree(root, ignore_errors=True)
    return out


def _bench_data_stream(scale: float) -> List[Dict]:
    """Streaming vs batch ingestion on a transform-heavy dataset, best of
    3 — the data plane's tentpole number.

      * data_batch_steps_per_s  — bulk execution: materialize every block
        (all reads + transforms run to completion), THEN run the consume
        loop. Ingestion and compute serialize.
      * data_stream_steps_per_s — StreamingIterator: blocks produce in a
        pipelined, backpressured graph while the consumer computes, so
        ingestion hides behind the step.
      * data_prefetch_hit_rate  — fraction of batches served without the
        consumer blocking, from the same streaming trials.

    The transform sleeps (IO-shaped work: decode/augment/fetch) so the
    legs measure overlap, not this host's arithmetic throughput; the
    consumer's per-batch "train step" is a matched sleep."""
    from ray_tpu import data as rdata

    nblocks = max(8, int(24 * scale))
    rows_per_block = 64
    step_s = 0.020       # consumer compute per batch (one batch per block)
    transform_s = 0.060  # per-block transform cost, runs on the cluster

    def slow_transform(batch):
        time.sleep(transform_s)
        return {"x": batch["id"] * 2}

    def make_ds():
        return rdata.range(nblocks * rows_per_block,
                           parallelism=nblocks).map_batches(slow_transform)

    def consume(it) -> int:
        steps = 0
        for _ in it:
            time.sleep(step_s)
            steps += 1
        return steps

    batch_best = stream_best = hit_best = 0.0
    for _ in range(3):
        # Bulk: materialize first (every read+transform completes), then
        # iterate the resident blocks.
        t0 = time.perf_counter()
        mat = make_ds().materialize()
        steps = consume(mat.iter_batches(batch_size=rows_per_block))
        batch_best = max(batch_best,
                         steps / max(time.perf_counter() - t0, 1e-9))
        t0 = time.perf_counter()
        it = make_ds().iter_batches(batch_size=rows_per_block,
                                    prefetch_batches=4)
        steps = consume(it)
        stream_best = max(stream_best,
                          steps / max(time.perf_counter() - t0, 1e-9))
        hit_best = max(hit_best, it.prefetch_hit_rate)
    return [
        {"benchmark": "data_batch_steps_per_s",
         "value": round(batch_best, 1), "unit": "steps/s",
         "n": nblocks, "trials": 3},
        {"benchmark": "data_stream_steps_per_s",
         "value": round(stream_best, 1), "unit": "steps/s",
         "n": nblocks, "trials": 3},
        {"benchmark": "data_prefetch_hit_rate",
         "value": round(hit_best, 3), "unit": "fraction",
         "n": nblocks, "trials": 3},
    ]


def _bench_metrics_history(scale: float) -> List[Dict]:
    """GCS metrics-history plane (runtime/gcs/server.py ring ingest):

      * metrics_history_ingest_per_s — MetricsReportMsg flushes folded
        into the time-series rings per second. Each flush is a realistic
        payload (24 moving counters, 4 gauges, 2 tagged histograms, the
        json a worker actually ships), spread over 4 reporters so the
        crc32 sharding is exercised; payload encoding is pre-built so the
        leg prices ingest (json parse, delta diff, ring append, budget
        check) and nothing else.
      * metrics_history_query_ms — one windowed query (counter rate and
        histogram p99 over the ingested rings) through the public
        handler, mean wall ms.
      * metrics_history_overhead_pct — what co-hosting ingest costs a
        serving replica: the SAME warm engine decode workload run twice,
        once with a background flusher thread doing only the snapshot-KV
        write (the pre-history GCS behavior) and once with the thread
        ALSO folding every flush into the rings. The 50 ms cadence is a
        20-reporter fleet at the production 1 s flush interval, with the
        GCS sharing the replica's core — already pessimistic (deployed,
        ingest runs on the GCS host, never the serving path). Budget
        <=2%: anything bigger means ring work leaked somewhere hot.
    """
    import asyncio
    import threading

    import jax.numpy as jnp

    from ray_tpu.llm.sampling import SamplingParams
    from ray_tpu.llm.serving import LLMConfig, build_engine
    from ray_tpu.models import llama
    from ray_tpu.runtime.gcs.server import GcsServer

    out: List[Dict] = []
    srv = GcsServer()
    bounds = [0.1, 0.5, 1, 5, 10, 50, 100, 500, 1000, 5000]

    def payload(i: int) -> bytes:
        snaps = [{"name": f"ray_tpu_bench_c{j}_total", "type": "counter",
                  "values": {"[]": float(i * (j + 1))}} for j in range(24)]
        snaps += [{"name": f"ray_tpu_bench_g{j}", "type": "gauge",
                   "values": {"[]": float((i * 7 + j) % 100)}}
                  for j in range(4)]
        for hname in ("ray_tpu_bench_ttft_ms", "ray_tpu_bench_itl_ms"):
            buckets = [0] * (len(bounds) + 1)
            buckets[(i + len(hname)) % len(buckets)] = 3 * (i + 1)
            snaps.append({"name": hname, "type": "histogram",
                          "boundaries": bounds,
                          "histograms": {'[["phase", "p"]]': {
                              "buckets": buckets, "sum": 40.0 * (i + 1),
                              "count": 3 * (i + 1)}}})
        return json.dumps(snaps).encode()

    n_flushes = max(400, int(1500 * scale))
    payloads = [payload(i) for i in range(n_flushes)]
    base = time.time() - n_flushes  # one synthetic flush per second
    t0 = time.perf_counter()
    for i, p in enumerate(payloads):
        srv._ingest_metrics_history(f"{i % 4:02x}" * 14, 1, p,
                                    now=base + i)
    out.append({"benchmark": "metrics_history_ingest_per_s",
                "value": round(_rate(n_flushes, time.perf_counter() - t0),
                               1),
                "unit": "flushes/s", "n": n_flushes})

    q_trials = max(20, int(50 * scale))
    t0 = time.perf_counter()
    for i in range(q_trials):
        if i % 2:
            asyncio.run(srv.handle_metrics_history(
                None, "ray_tpu_bench_c0_total", window_s=60.0, agg="rate"))
        else:
            asyncio.run(srv.handle_metrics_history(
                None, "ray_tpu_bench_ttft_ms", window_s=60.0, agg="p99"))
    out.append({"benchmark": "metrics_history_query_ms",
                "value": round((time.perf_counter() - t0) / q_trials * 1e3,
                               3),
                "unit": "ms", "n": q_trials})

    # -- serving overhead: decode loop +/- ring ingest beside it ---------
    mid = llama.LlamaConfig(vocab_size=128, d_model=128, n_layers=2,
                            n_heads=4, n_kv_heads=4, d_ff=512,
                            max_seq=128, dtype=jnp.float32)
    eng = build_engine(LLMConfig(model_config=mid, num_kv_blocks=32,
                                 block_size=8, max_batch_size=4,
                                 prefill_chunk=16, warmup_buckets="off"))

    def decode_workload() -> int:
        for s in range(4):
            eng.add_request([(s * 13 + 5 * i) % 128 for i in range(24)],
                            SamplingParams(max_tokens=24))
        tokens = 0
        while eng.has_unfinished():
            for o in eng.step():
                tokens += len(o.new_token_ids)
        return tokens

    decode_workload()                      # warm the compile cache

    def timed_leg(with_history: bool) -> float:
        stop = threading.Event()
        counter = [0]

        def flusher():
            i = 0
            while not stop.is_set():
                p = payloads[i % n_flushes]
                srv._kv[b"metrics:bench:1"] = p        # the KV write both
                if with_history:                       # modes always paid
                    srv._ingest_metrics_history(
                        "bb" * 14, 1, p, now=base + n_flushes + i)
                counter[0] = i = i + 1
                time.sleep(0.05)

        th = threading.Thread(target=flusher, daemon=True,
                              name="bench-mh-flusher")
        th.start()
        try:
            t0 = time.perf_counter()
            tokens = decode_workload()
            return _rate(tokens, time.perf_counter() - t0)
        finally:
            stop.set()
            th.join(timeout=5)

    # Interleaved best-of-3 pairs: box-load drift on a shared 1-core host
    # swamps a small delta unless both legs see the same weather.
    tps = {"snapshot_only": 0.0, "history": 0.0}
    for _ in range(3):
        tps["snapshot_only"] = max(tps["snapshot_only"], timed_leg(False))
        tps["history"] = max(tps["history"], timed_leg(True))
    overhead = 100.0 * (1.0 - tps["history"] / tps["snapshot_only"])
    out.append({"benchmark": "metrics_history_overhead_pct",
                "value": round(overhead, 2), "unit": "%", "n": 1,
                "trials": 3})
    return out


def _bench_scale_envelope(scale: float) -> List[Dict]:
    """Batched vs per-item control-plane legs for MICROBENCH.json."""
    legs = run_scale_envelope(n_requests=max(64, int(192 * scale)))
    return [{"benchmark": name, **rec} for name, rec in legs.items()]


def main(scale: float = 1.0, as_json: bool = False) -> List[Dict]:
    results = run(scale=scale)
    if as_json:
        print(json.dumps(results))
    else:
        width = max(len(r["benchmark"]) for r in results)
        for r in results:
            digits = {"GiB/s": 3, "s": 4}.get(r["unit"], 1)
            print(f"{r['benchmark']:<{width}}  {r['value']:>12,.{digits}f} "
                  f"{r['unit']} (n={r['n']})")
    return results


if __name__ == "__main__":
    main()
