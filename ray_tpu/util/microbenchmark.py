"""Core-runtime microbenchmarks.

Reference analog: python/ray/_private/ray_perf.py:93-315 (the `ray
microbenchmark` CLI): put/get ops, task throughput sync/async, 1:1 and
n:n actor call rates — the numbers the release pipeline tracks per build.
Run via `python -m ray_tpu.scripts microbenchmark [--scale N]`.
"""

from __future__ import annotations

import json
import time
from typing import Dict, List


def _rate(n: int, seconds: float) -> float:
    return n / max(seconds, 1e-9)


def run(scale: float = 1.0, num_cpus: int = 4) -> List[Dict]:
    import numpy as np

    import ray_tpu

    owns_cluster = not ray_tpu.is_initialized()
    if owns_cluster:
        ray_tpu.init(num_cpus=num_cpus)
    results: List[Dict] = []

    def record(name: str, n: int, seconds: float, unit: str = "ops/s"):
        results.append({"benchmark": name, "value": round(_rate(n, seconds), 1),
                        "unit": unit, "n": n})

    try:
        # -- object store ------------------------------------------------
        n = int(1000 * scale)
        t0 = time.perf_counter()
        refs = [ray_tpu.put(i) for i in range(n)]
        record("put_small_ops", n, time.perf_counter() - t0)
        t0 = time.perf_counter()
        ray_tpu.get(refs)
        record("get_small_ops", n, time.perf_counter() - t0)
        del refs

        m = max(4, int(64 * scale))
        payload = np.zeros(1 << 20, dtype=np.uint8)  # 1 MiB
        # Warmup: settle cluster-boot CPU contention and page-fault the
        # arena region this loop will reuse (steady-state bandwidth is the
        # number the release pipeline tracks; ray_perf.py warms up too).
        # The first large put triggers the driver's lazy arena-prefault
        # walk. On small boxes that walk competes with the copy loop for
        # the same cores, so wait for it to finish before timing
        # (production hosts hide the walk behind spare cores; the steady
        # state is the tracked number).
        from ray_tpu.core.worker import global_worker

        warm_refs = [ray_tpu.put(payload)]
        store = global_worker().store
        deadline = time.monotonic() + 15.0
        while (store is not None and not store.prefaulted
               and store.prefault_inflight  # never-warm hosts: don't stall
               and time.monotonic() < deadline):
            time.sleep(0.1)
        warm_refs += [ray_tpu.put(payload) for _ in range(min(32, m))]
        # Free the warmup objects deterministically so trial occupancy
        # (3 x m MiB) doesn't depend on GC timing on small stores.
        del warm_refs
        # Best of 3 trials: on small/shared boxes a single descheduling
        # blip inside one trial halves the apparent bandwidth, so the
        # bandwidth legs report peak steady state (standard for bandwidth
        # suites — STREAM does the same).
        put_best = get_best = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            big = [ray_tpu.put(payload) for _ in range(m)]
            dt = time.perf_counter() - t0
            put_best = max(put_best, m / (1 << 10) / max(dt, 1e-9))
            t0 = time.perf_counter()
            ray_tpu.get(big)
            dt = time.perf_counter() - t0
            get_best = max(get_best, m / (1 << 10) / max(dt, 1e-9))
            del big
        results.append({"benchmark": "put_1mib_gbps",
                        "value": round(put_best, 3),
                        "unit": "GiB/s", "n": m, "trials": 3})
        results.append({"benchmark": "get_1mib_gbps",
                        "value": round(get_best, 3),
                        "unit": "GiB/s", "n": m, "trials": 3})

        # -- tasks -------------------------------------------------------
        @ray_tpu.remote
        def nop():
            return None

        # Warm the WHOLE worker pool (a single probe task would leave the
        # batch benchmarks measuring process-spawn ramp, not steady state).
        ray_tpu.get([nop.remote() for _ in range(num_cpus * 8)], timeout=300)
        n = int(100 * scale)
        t0 = time.perf_counter()
        for _ in range(n):
            ray_tpu.get(nop.remote(), timeout=120)
        record("tasks_sync", n, time.perf_counter() - t0)

        n = int(500 * scale)
        t0 = time.perf_counter()
        ray_tpu.get([nop.remote() for _ in range(n)], timeout=300)
        record("tasks_async_batch", n, time.perf_counter() - t0)

        # -- actors ------------------------------------------------------
        @ray_tpu.remote
        class Actor:
            def noop(self):
                return None

        a = Actor.remote()
        ray_tpu.get(a.noop.remote(), timeout=120)
        n = int(200 * scale)
        t0 = time.perf_counter()
        for _ in range(n):
            ray_tpu.get(a.noop.remote(), timeout=120)
        record("actor_calls_sync_1_1", n, time.perf_counter() - t0)

        n = int(1000 * scale)
        t0 = time.perf_counter()
        ray_tpu.get([a.noop.remote() for _ in range(n)], timeout=300)
        record("actor_calls_async_1_1", n, time.perf_counter() - t0)

        workers = [Actor.remote() for _ in range(4)]
        for w in workers:
            ray_tpu.get(w.noop.remote(), timeout=120)
        n = int(250 * scale)
        t0 = time.perf_counter()
        ray_tpu.get([w.noop.remote() for w in workers for _ in range(n)],
                    timeout=300)
        record("actor_calls_async_n_n", n * len(workers),
               time.perf_counter() - t0)
        # Benchmark actors must not outlive the run on a shared cluster.
        for actor in [a, *workers]:
            try:
                ray_tpu.kill(actor)
            except Exception:
                pass
    finally:
        if owns_cluster:
            ray_tpu.shutdown()
    return results


def main(scale: float = 1.0, as_json: bool = False) -> List[Dict]:
    results = run(scale=scale)
    if as_json:
        print(json.dumps(results))
    else:
        width = max(len(r["benchmark"]) for r in results)
        for r in results:
            digits = 3 if r["unit"] == "GiB/s" else 1
            print(f"{r['benchmark']:<{width}}  {r['value']:>12,.{digits}f} "
                  f"{r['unit']} (n={r['n']})")
    return results


if __name__ == "__main__":
    main()
