"""Dask-graph scheduler: execute dask task graphs as ray_tpu tasks.

Reference analog: python/ray/util/dask/scheduler.py (ray_dask_get) — a
drop-in `get` for dask's scheduler interface, so
`dask.compute(x, scheduler=ray_dask_get)` fans the graph out over the
cluster. The dask graph protocol is plain data (dict of key -> task,
task = (callable, *args) tuples with nested key references), so this
module implements the protocol directly and works with or without dask
installed; when dask IS present, `enable()` registers the scheduler as
dask's default.

Semantics implemented (dask/core.py's get semantics):
  * a task is a tuple whose head is callable: (fn, *args);
  * args are recursively resolved: keys -> their computed values,
    lists/tuples recurse;
  * a key mapping to a literal (non-task) is that literal;
  * nested tasks inside args execute inline (dask semantics).

Execution: one ray_tpu task per graph node (batched by a configurable
inline threshold — tiny pure-literal nodes don't deserve a round-trip),
dependencies passed as ObjectRefs so the object store moves data and
independent subgraphs run in parallel.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Hashable, List, Set

logger = logging.getLogger(__name__)


def _is_task(x: Any) -> bool:
    return isinstance(x, tuple) and len(x) > 0 and callable(x[0])


def _hashable(x: Any) -> bool:
    try:
        hash(x)
    except TypeError:
        return False
    return True


def _keys_in(x: Any, dsk: Dict) -> Set[Hashable]:
    """Keys of `dsk` referenced by argument structure x — dask/core.py's
    traversal order EXACTLY: task -> recurse args; list -> recurse
    elements; otherwise a hashable term that is `in dsk` IS a key (this
    includes non-task tuples: dask dataframe/array partitions use
    ('name', i) tuple keys, which must never be traversed as
    containers)."""
    out: Set[Hashable] = set()
    if _is_task(x):
        for a in x[1:]:
            out |= _keys_in(a, dsk)
    elif isinstance(x, list):
        for a in x:
            out |= _keys_in(a, dsk)
    elif _hashable(x) and x in dsk:
        out.add(x)
    return out


def _execute_node(task, dep_keys, *dep_values) -> Any:
    """Run one graph node on a worker: rebuild args from resolved deps.

    Dependencies arrive as TOP-LEVEL task args (dep_values), because
    ObjectRefs nested inside containers are not auto-resolved — the same
    rule as the reference's task arguments."""
    resolved = dict(zip(dep_keys, dep_values))

    def build(x):
        if _is_task(x):
            fn, *args = x
            return fn(*[build(a) for a in args])
        if isinstance(x, list):
            return [build(a) for a in x]
        if _hashable(x) and x in resolved:
            return resolved[x]
        return x

    return build(task)


def ray_dask_get(dsk: Dict, keys, **kwargs) -> Any:
    """dask scheduler entry point: compute `keys` from graph `dsk`.

    keys may be a single key or a (nested) list of keys, per dask's get
    contract; the result mirrors its shape."""
    import ray_tpu

    dsk = dict(dsk)
    # dependency map + topological order (Kahn). Self-references stay in
    # the dep set so {'a': (f, 'a')} reports as a cycle, not a dispatch
    # of the raw key.
    deps: Dict[Hashable, Set[Hashable]] = {
        k: _keys_in(v, dsk) for k, v in dsk.items()}
    pending = {k: set(d) for k, d in deps.items()}
    ready = [k for k, d in pending.items() if not d]
    order: List[Hashable] = []
    dependents: Dict[Hashable, Set[Hashable]] = {k: set() for k in dsk}
    for k, d in deps.items():
        for dep in d:
            dependents[dep].add(k)
    while ready:
        k = ready.pop()
        order.append(k)
        for child in dependents[k]:
            pending[child].discard(k)
            if not pending[child]:
                ready.append(child)
    if len(order) != len(dsk):
        cyc = sorted(set(dsk) - set(order), key=str)[:3]
        raise ValueError(f"cycle in dask graph near keys {cyc}")

    exec_node = ray_tpu.remote(_execute_node)
    refs: Dict[Hashable, Any] = {}   # key -> ObjectRef or literal
    for k in order:
        v = dsk[k]
        if not _is_task(v) and not _keys_in(v, dsk):
            refs[k] = v              # literal: no task round-trip
            continue
        dep_keys = sorted(deps[k], key=str)
        refs[k] = exec_node.remote(v, dep_keys,
                                   *[refs[d] for d in dep_keys])

    # Batch the final fetch: one ray_tpu.get for every requested ref.
    from ray_tpu.core.object_ref import ObjectRef

    flat: List[Hashable] = []

    def walk(x):
        if isinstance(x, list):
            for i in x:
                walk(i)
        else:
            flat.append(x)

    walk(keys)
    to_fetch = [k for k in flat if isinstance(refs[k], ObjectRef)]
    fetched = dict(zip(to_fetch, ray_tpu.get([refs[k] for k in to_fetch]))) \
        if to_fetch else {}
    values = {k: fetched.get(k, refs[k]) for k in flat}

    def shape(x):
        if isinstance(x, list):
            return [shape(i) for i in x]
        return values[x]

    return shape(keys)


def enable() -> bool:
    """Register as dask's default scheduler (no-op without dask)."""
    try:
        import dask
    except ImportError:
        logger.info("dask not installed; ray_dask_get still usable directly")
        return False
    dask.config.set(scheduler=ray_dask_get)
    return True
