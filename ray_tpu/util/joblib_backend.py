"""joblib parallel backend running jobs as ray_tpu tasks.

Reference analog: python/ray/util/joblib/ (register_ray +
ray_backend.RayBackend subclassing joblib's MultiprocessingBackend).
Usage::

    from ray_tpu.util.joblib_backend import register_ray_tpu
    register_ray_tpu()
    with joblib.parallel_backend("ray_tpu"):
        Parallel(n_jobs=8)(delayed(f)(i) for i in range(100))
"""

from __future__ import annotations

import ray_tpu

try:
    from joblib._parallel_backends import ThreadingBackend
    from joblib.parallel import register_parallel_backend
    _HAVE_JOBLIB = True
except Exception:  # pragma: no cover - joblib always in the image, but gate anyway
    ThreadingBackend = object
    _HAVE_JOBLIB = False


class RayTpuBackend(ThreadingBackend):
    """Each joblib batch becomes one ray_tpu task; joblib's own threads just
    block on ray_tpu.get, so n_jobs concurrency maps to cluster concurrency."""

    supports_timeout = True

    def configure(self, n_jobs=1, parallel=None, **backend_args):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self._remote_args = dict(backend_args.get("ray_remote_args", {}))
        return super().configure(n_jobs=n_jobs, parallel=parallel)

    def effective_n_jobs(self, n_jobs):
        if n_jobs == -1:
            if not ray_tpu.is_initialized():
                ray_tpu.init()
            return max(int(ray_tpu.cluster_resources().get("CPU", 1)), 1)
        return super().effective_n_jobs(n_jobs)

    def apply_async(self, func, callback=None):
        def run_remote():
            fn = ray_tpu.remote(_call_batch)
            if self._remote_args:
                fn = fn.options(**self._remote_args)
            return ray_tpu.get(fn.remote(func))

        return self._get_pool().apply_async(run_remote, callback=callback)


def _call_batch(batch):
    return batch()


def register_ray_tpu():
    if not _HAVE_JOBLIB:
        raise ImportError("joblib is not available")
    register_parallel_backend("ray_tpu", RayTpuBackend)
