"""Usage stats: opt-out, record-only telemetry summary.

Reference analog: python/ray/_private/usage/usage_lib.py:95,157 (opt-out
cluster metadata ping). This build targets air-gapped TPU clusters with zero
egress, so the report is only written to ``<session>/usage_stats.json`` —
never transmitted. RAY_TPU_USAGE_STATS_ENABLED=0 disables even that.
"""

from __future__ import annotations

import json
import os
import platform
import time


def usage_stats_enabled() -> bool:
    return os.environ.get("RAY_TPU_USAGE_STATS_ENABLED", "1") != "0"


def collect() -> dict:
    import ray_tpu

    report = {
        "schema_version": "0.1",
        "source": "ray_tpu",
        "version": ray_tpu.__version__,
        "python_version": platform.python_version(),
        "os": platform.system().lower(),
        "collected_at": time.time(),
    }
    try:
        import sys

        jax = sys.modules.get("jax")
        if jax is not None:
            report["jax_version"] = jax.__version__
            # Only report backend info if the backend is ALREADY initialized:
            # stats collection must never cold-start a PJRT client (that can
            # block for seconds on TPU runtimes).
            from jax._src import xla_bridge

            if getattr(xla_bridge, "_backends", None):
                report["backend"] = jax.default_backend()
                report["num_devices"] = jax.device_count()
    except Exception:
        pass
    return report


def write_report(session_dir: str):
    if not usage_stats_enabled():
        return
    try:
        with open(os.path.join(session_dir, "usage_stats.json"), "w") as f:
            json.dump(collect(), f)
    except Exception:
        pass
