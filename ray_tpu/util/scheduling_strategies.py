"""Public scheduling strategies.

Reference analog: python/ray/util/scheduling_strategies.py
(PlacementGroupSchedulingStrategy:15, NodeAffinitySchedulingStrategy:41,
NodeLabelSchedulingStrategy:135).
"""

from ray_tpu.runtime.scheduling import (  # noqa: F401
    DefaultStrategy,
    NodeAffinityStrategy as NodeAffinitySchedulingStrategy,
    NodeLabelStrategy as NodeLabelSchedulingStrategy,
    PlacementGroupStrategy as PlacementGroupSchedulingStrategy,
    SpreadStrategy as SpreadSchedulingStrategy,
)
