"""Cross-language function registry.

Reference analog: python/ray/cross_language.py (java_function /
cpp_function descriptors) + the function-descriptor resolution the C++
worker does by name. Non-Python peers cannot ship cloudpickle blobs, so
they invoke Python functions BY NAME: either a name registered here via
@cross_language.register, or a fully-qualified "pkg.module:attr" path
resolved by import. Resolution happens in the proxy process, which is
inside the cluster's trust domain (callers already passed wire auth).
"""

from __future__ import annotations

import importlib
import threading
from typing import Any, Callable, Dict, Optional

_lock = threading.Lock()
_registry: Dict[str, Callable] = {}


def register(name: str, fn: Optional[Callable] = None):
    """Register `fn` under `name` for cross-language callers.

    Usable as a decorator (``@register("adder")``) or a call
    (``register("adder", adder)``).
    """
    if fn is None:
        def deco(f):
            register(name, f)
            return f

        return deco
    with _lock:
        _registry[name] = fn
    return fn


def unregister(name: str) -> None:
    with _lock:
        _registry.pop(name, None)


def resolve(name: str) -> Callable:
    """Registered name first; else import "pkg.module:attr" (or the
    last-dot split of "pkg.module.attr")."""
    with _lock:
        fn = _registry.get(name)
    if fn is not None:
        return fn
    if ":" in name:
        mod_name, attr = name.split(":", 1)
    elif "." in name:
        mod_name, attr = name.rsplit(".", 1)
    else:
        raise KeyError(
            f"no cross-language function registered as {name!r} (and it "
            "is not an importable dotted path)")
    mod = importlib.import_module(mod_name)
    obj: Any = mod
    for part in attr.split("."):
        obj = getattr(obj, part)
    if not callable(obj):
        raise TypeError(f"{name!r} resolved to non-callable {obj!r}")
    return obj


def registered_names():
    with _lock:
        return sorted(_registry)


# --------------------------------------------------------- hosted workers
#
# The REVERSE direction: a non-Python worker (cpp/raytpu_cli `worker`)
# registers named functions it can EXECUTE, then pulls tasks and pushes
# results over the RTX wire. Python drivers call those functions with
# `hosted("name").remote(...)` and get a real ObjectRef back.
#
# Reference analog: the C++ task executor
# (cpp/src/ray/runtime/task/task_executor.cc:1) — tasks target function
# DESCRIPTORS (names), args/results are language-neutral values. Transport
# here is long-poll over the authenticated client-proxy wire rather than a
# raylet push: same task frames, pull-driven (the proxy cannot dial out
# through the worker's NAT side of the socket).

_hosted_lock = threading.Lock()
_hosted_workers: Dict[bytes, "_HostedWorker"] = {}
_hosted_pending: Dict[bytes, dict] = {}  # task_id -> {"oid": ..., "worker"}


class _HostedWorker:
    def __init__(self, name: str, functions):
        import os
        import queue as queue_mod

        self.worker_id = os.urandom(8)
        self.name = name
        self.functions = frozenset(functions)
        self.tasks: "queue_mod.Queue[dict]" = queue_mod.Queue()


def hosted_register(name: str, functions) -> bytes:
    """Register a worker that EXECUTES the named functions (called by the
    proxy on xworker_register). Returns the worker id used for polling."""
    hw = _HostedWorker(name, functions)
    with _hosted_lock:
        _hosted_workers[hw.worker_id] = hw
    return hw.worker_id


def hosted_unregister(worker_id: bytes) -> None:
    with _hosted_lock:
        hw = _hosted_workers.pop(worker_id, None)
        if hw is None:
            return
        # Fail every task this worker will never answer: still queued, or
        # already polled and in flight when it died.
        orphans = set()
        while not hw.tasks.empty():
            try:
                orphans.add(hw.tasks.get_nowait()["task_id"])
            except Exception:
                break
        orphans |= {tid for tid, rec in _hosted_pending.items()
                    if rec["worker"] == worker_id}
    for tid in orphans:
        hosted_result(worker_id, tid, "error",
                      error=f"hosted worker {hw.name!r} disconnected",
                      _allow_unknown_worker=True)


def hosted_names() -> list:
    """All function names currently executable by some hosted worker."""
    with _hosted_lock:
        out = set()
        for hw in _hosted_workers.values():
            out |= hw.functions
        return sorted(out)


def hosted_poll(worker_id: bytes, timeout_s: float = 10.0) -> Optional[dict]:
    """Blocking pull of the next task for `worker_id` (run by the proxy in
    an executor thread). None = idle within the timeout."""
    import queue as queue_mod

    with _hosted_lock:
        hw = _hosted_workers.get(worker_id)
    if hw is None:
        raise KeyError("unknown hosted worker (re-register)")
    try:
        return hw.tasks.get(timeout=min(timeout_s, 30.0))
    except queue_mod.Empty:
        return None


def hosted_result(worker_id: bytes, task_id: bytes, status: str,
                  value=None, error: str = "",
                  _allow_unknown_worker: bool = False) -> None:
    """Complete a hosted task: land the value (or error) on the driver's
    ObjectRef exactly the way a Python task reply would."""
    from ray_tpu.core import worker as worker_mod
    from ray_tpu.core.exceptions import RayTpuError

    with _hosted_lock:
        if not _allow_unknown_worker and worker_id not in _hosted_workers:
            raise KeyError("unknown hosted worker")
        rec = _hosted_pending.pop(task_id, None)
    if rec is None:
        return  # duplicate/late reply
    w = worker_mod.global_worker()
    result = (value if status == "ok"
              else RayTpuError(f"hosted task failed: {error}"))
    with w._mem_lock:
        w.memory_store[rec["oid"]] = result
        fut = w.result_futures.pop(rec["oid"], None)
    if fut is not None and not fut.done():
        fut.set_result(True)


class HostedFunction:
    """Handle to a function EXECUTED by a hosted (non-Python) worker."""

    def __init__(self, fn_name: str):
        self.fn_name = fn_name

    def remote(self, *args):
        import os

        from ray_tpu.core import worker as worker_mod
        from ray_tpu.core.object_ref import ObjectRef
        from ray_tpu.runtime import xlang
        from ray_tpu.utils.ids import ObjectID

        # Args must speak the xlang vocabulary — reject pickled Python
        # closures at SUBMIT time, not in the foreign worker.
        payload = xlang.encode(list(args))
        w = worker_mod.global_worker()
        oid = ObjectID.generate().binary()
        task_id = os.urandom(8)
        from concurrent.futures import Future as SyncFuture

        fut = SyncFuture()
        with w._mem_lock:
            w.result_futures[oid] = fut
        # Worker lookup, pending insert AND queue put under ONE lock hold:
        # a disconnect reap between them would scan _hosted_pending before
        # the task exists and drain the queue before the put — the task
        # would then hang forever on a dead worker.
        with _hosted_lock:
            hw = next((h for h in _hosted_workers.values()
                       if self.fn_name in h.functions), None)
            if hw is None:
                with w._mem_lock:
                    w.result_futures.pop(oid, None)
                avail = sorted({n for h in _hosted_workers.values()
                                for n in h.functions})  # lock already held
                raise KeyError(
                    f"no hosted worker executes {self.fn_name!r} "
                    f"(available: {avail})")
            _hosted_pending[task_id] = {"oid": oid, "worker": hw.worker_id}
            hw.tasks.put({"task_id": task_id, "fn": self.fn_name,
                          "args": payload})
        return ObjectRef(oid)


def hosted(fn_name: str) -> HostedFunction:
    return HostedFunction(fn_name)
