"""Cross-language function registry.

Reference analog: python/ray/cross_language.py (java_function /
cpp_function descriptors) + the function-descriptor resolution the C++
worker does by name. Non-Python peers cannot ship cloudpickle blobs, so
they invoke Python functions BY NAME: either a name registered here via
@cross_language.register, or a fully-qualified "pkg.module:attr" path
resolved by import. Resolution happens in the proxy process, which is
inside the cluster's trust domain (callers already passed wire auth).
"""

from __future__ import annotations

import importlib
import threading
from typing import Any, Callable, Dict, Optional

_lock = threading.Lock()
_registry: Dict[str, Callable] = {}


def register(name: str, fn: Optional[Callable] = None):
    """Register `fn` under `name` for cross-language callers.

    Usable as a decorator (``@register("adder")``) or a call
    (``register("adder", adder)``).
    """
    if fn is None:
        def deco(f):
            register(name, f)
            return f

        return deco
    with _lock:
        _registry[name] = fn
    return fn


def unregister(name: str) -> None:
    with _lock:
        _registry.pop(name, None)


def resolve(name: str) -> Callable:
    """Registered name first; else import "pkg.module:attr" (or the
    last-dot split of "pkg.module.attr")."""
    with _lock:
        fn = _registry.get(name)
    if fn is not None:
        return fn
    if ":" in name:
        mod_name, attr = name.split(":", 1)
    elif "." in name:
        mod_name, attr = name.rsplit(".", 1)
    else:
        raise KeyError(
            f"no cross-language function registered as {name!r} (and it "
            "is not an importable dotted path)")
    mod = importlib.import_module(mod_name)
    obj: Any = mod
    for part in attr.split("."):
        obj = getattr(obj, part)
    if not callable(obj):
        raise TypeError(f"{name!r} resolved to non-callable {obj!r}")
    return obj


def registered_names():
    with _lock:
        return sorted(_registry)
