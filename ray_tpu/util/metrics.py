"""User-defined application metrics: Counter / Gauge / Histogram.

Reference analog: python/ray/util/metrics.py (Counter/Gauge/Histogram feeding
the node metrics agent, exported to Prometheus by
_private/metrics_agent.py / _private/prometheus_exporter.py).

TPU build: each process keeps an in-process registry; snapshots are pushed
to the GCS KV under ``metrics:<pid>`` (throttled), where the dashboard /
``ray_tpu.state.metrics_snapshot`` aggregates them and renders Prometheus
text exposition format.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

_REGISTRY: Dict[str, "Metric"] = {}
_REGISTRY_LOCK = threading.Lock()
_FLUSH_INTERVAL_S = float(os.environ.get("RAY_TPU_METRICS_FLUSH_S", "1.0"))
_last_flush = 0.0


def _tag_key(tags: Optional[Dict[str, str]]) -> str:
    if not tags:
        return "[]"  # hot path: untagged metrics skip json entirely
    return json.dumps(sorted(tags.items()))


class Metric:
    """Base class; subclasses define how observations fold into the value."""

    TYPE = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Tuple[str, ...]] = None):
        if not name:
            raise ValueError("metric name is required")
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._values: Dict[str, float] = {}
        self._lock = threading.Lock()
        with _REGISTRY_LOCK:
            _REGISTRY[name] = self

    @property
    def info(self) -> Dict:
        return {"name": self._name, "description": self._description,
                "tag_keys": self._tag_keys, "default_tags": self._default_tags}

    def set_default_tags(self, tags: Dict[str, str]):
        for k in tags:
            if k not in self._tag_keys:
                raise ValueError(f"unknown tag key {k!r} (declared: {self._tag_keys})")
        self._default_tags = dict(tags)
        return self

    def _merged(self, tags: Optional[Dict[str, str]]) -> Dict[str, str]:
        merged = dict(self._default_tags)
        if tags:
            for k in tags:
                if k not in self._tag_keys:
                    raise ValueError(
                        f"unknown tag key {k!r} for metric {self._name!r} "
                        f"(declared: {self._tag_keys})")
            merged.update(tags)
        return merged

    def _observe(self, value: float, tags: Optional[Dict[str, str]]):
        raise NotImplementedError

    def snapshot(self) -> Dict:
        with self._lock:
            return {"name": self._name, "type": self.TYPE,
                    "description": self._description,
                    "values": dict(self._values)}


class Counter(Metric):
    TYPE = "counter"

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        if value <= 0:
            raise ValueError("Counter.inc requires a positive value")
        self._inc_key(_tag_key(self._merged(tags)), value)

    def _inc_key(self, key: str, value: float):
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value
        _maybe_flush()

    def bind(self, tags: Dict[str, str]) -> "BoundCounter":
        """Pre-resolve a tag set once; the returned handle increments with
        no per-call dict merge or json encode — for hot paths (per-chunk
        collective byte counters) that hit one tag combination millions of
        times."""
        return BoundCounter(self, _tag_key(self._merged(tags)))


class BoundCounter:
    __slots__ = ("_metric", "_key")

    def __init__(self, metric: Counter, key: str):
        self._metric = metric
        self._key = key

    def inc(self, value: float = 1.0):
        if value <= 0:
            raise ValueError("Counter.inc requires a positive value")
        self._metric._inc_key(self._key, value)


class Gauge(Metric):
    TYPE = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        self._set_key(_tag_key(self._merged(tags)), value)

    def _set_key(self, key: str, value: float):
        with self._lock:
            self._values[key] = float(value)
        _maybe_flush()

    def bind(self, tags: Optional[Dict[str, str]] = None) -> "BoundGauge":
        """Counter.bind/Histogram.bind symmetry: precompute the tag key so
        hot gauges (PENDING_LEASES on every dispatch tick) skip the
        per-set merge/json encode."""
        return BoundGauge(self, _tag_key(self._merged(tags)))


class BoundGauge:
    __slots__ = ("_metric", "_key")

    def __init__(self, metric: Gauge, key: str):
        self._metric = metric
        self._key = key

    def set(self, value: float):
        self._metric._set_key(self._key, value)


class Histogram(Metric):
    TYPE = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Optional[List[float]] = None,
                 tag_keys: Optional[Tuple[str, ...]] = None):
        super().__init__(name, description, tag_keys)
        self._boundaries = sorted(boundaries or [0.1, 1.0, 10.0])
        # per tag-set: [bucket counts..., +Inf count], sum, count
        self._hist: Dict[str, Dict] = {}

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        self._observe_key(_tag_key(self._merged(tags)), value)

    def bind(self, tags: Dict[str, str]) -> "BoundHistogram":
        """Counter.bind analog: precomputed tag key, no per-observe merge."""
        return BoundHistogram(self, _tag_key(self._merged(tags)))

    def _observe_key(self, key: str, value: float):
        with self._lock:
            h = self._hist.setdefault(
                key, {"buckets": [0] * (len(self._boundaries) + 1),
                      "sum": 0.0, "count": 0})
            idx = len(self._boundaries)
            for i, b in enumerate(self._boundaries):
                if value <= b:
                    idx = i
                    break
            h["buckets"][idx] += 1
            h["sum"] += value
            h["count"] += 1
            self._values[key] = h["sum"] / max(h["count"], 1)
        _maybe_flush()

    def snapshot(self) -> Dict:
        snap = super().snapshot()
        with self._lock:
            snap["boundaries"] = list(self._boundaries)
            snap["histograms"] = {k: dict(v) for k, v in self._hist.items()}
        return snap


class BoundHistogram:
    __slots__ = ("_metric", "_key")

    def __init__(self, metric: Histogram, key: str):
        self._metric = metric
        self._key = key

    def observe(self, value: float):
        self._metric._observe_key(self._key, value)


def histogram_quantile(boundaries: List[float], buckets: List[float],
                       q: float) -> Optional[float]:
    """Estimate the q-quantile (0..1) from histogram bucket counts.

    ``buckets`` has ``len(boundaries) + 1`` entries — one count per
    boundary plus the +Inf overflow bucket — exactly the shape
    ``Histogram.snapshot()`` stores and the GCS history rings replay.
    Linear interpolation inside the target bucket (the PromQL
    ``histogram_quantile`` convention); observations in the overflow
    bucket clamp to the highest finite boundary, and the first bucket
    interpolates from 0. Returns None on empty input so callers can
    leave the key out instead of reporting a fake 0.
    """
    total = sum(buckets)
    if total <= 0 or not boundaries:
        return None
    q = min(max(q, 0.0), 1.0)
    rank = q * total
    cumulative = 0.0
    for i, count in enumerate(buckets):
        if count <= 0:
            continue
        if cumulative + count >= rank:
            if i >= len(boundaries):     # +Inf bucket: no upper edge
                return float(boundaries[-1])
            lo = boundaries[i - 1] if i > 0 else 0.0
            hi = boundaries[i]
            frac = (rank - cumulative) / count
            return float(lo + (hi - lo) * frac)
        cumulative += count
    return float(boundaries[-1])


def snapshot_all() -> List[Dict]:
    with _REGISTRY_LOCK:
        metrics = list(_REGISTRY.values())
    return [m.snapshot() for m in metrics]


def _maybe_flush():
    """Throttled push of this process's metrics to the GCS KV."""
    global _last_flush
    now = time.monotonic()
    if now - _last_flush < _FLUSH_INTERVAL_S:
        return
    _last_flush = now
    try:
        flush()
    except Exception:
        pass  # metrics must never break the application


_typed_report = True


def flush():
    from ray_tpu.core import worker as worker_mod

    if not worker_mod.is_initialized():
        return
    core = worker_mod.global_worker()
    node = core.node_id.hex() if getattr(core, "node_id", None) else "unknown"
    payload = json.dumps(snapshot_all()).encode()
    # Fire-and-forget: inc()/set() run on arbitrary threads INCLUDING the io
    # loop itself (e.g. _complete_task on the actor submit path); blocking on
    # the push here would deadlock the loop against its own flush.
    core.io.spawn(_push_snapshot(core, node, payload))


async def _push_snapshot(core, node: str, payload: bytes):
    """One typed MetricsReportMsg frame per flush (the GCS files it under
    the same metrics:<node>:<pid> KV key); pickled kv_put against an old
    GCS."""
    global _typed_report
    if _typed_report:
        from ray_tpu.runtime import wire
        from ray_tpu.runtime.rpc import ConnectionLost, RpcError

        msg = wire.MetricsReportMsg(node=node, pid=os.getpid(),
                                    payload=payload)
        try:
            await core.gcs.call("report_metrics2", m=msg.encode())
            return
        except RpcError as e:
            if isinstance(e, ConnectionLost) or "no handler" not in str(e):
                raise
            _typed_report = False
    key = f"metrics:{node}:{os.getpid()}".encode()
    await core.gcs.call("kv_put", key=key, value=payload)


def prometheus_text(snapshots: List[Dict]) -> str:
    """Render metric snapshots in Prometheus text exposition format
    (the _private/prometheus_exporter.py analog)."""
    lines = []
    for snap in snapshots:
        name = snap["name"].replace(".", "_").replace("-", "_")
        if snap.get("description"):
            lines.append(f"# HELP {name} {snap['description']}")
        lines.append(f"# TYPE {name} {snap['type']}")
        if snap["type"] == "histogram":
            for key, h in snap.get("histograms", {}).items():
                labels = dict(json.loads(key))
                cumulative = 0
                for b, c in zip(snap["boundaries"], h["buckets"]):
                    cumulative += c
                    lab = _fmt_labels({**labels, "le": str(b)})
                    lines.append(f"{name}_bucket{lab} {cumulative}")
                cumulative += h["buckets"][-1]
                lab = _fmt_labels({**labels, "le": "+Inf"})
                lines.append(f"{name}_bucket{lab} {cumulative}")
                lines.append(f"{name}_sum{_fmt_labels(labels)} {h['sum']}")
                lines.append(f"{name}_count{_fmt_labels(labels)} {h['count']}")
        else:
            for key, v in snap["values"].items():
                labels = dict(json.loads(key))
                lines.append(f"{name}{_fmt_labels(labels)} {v}")
    return "\n".join(lines) + "\n"


def _escape_label_value(value) -> str:
    """Prometheus text exposition escaping for label values: backslash,
    double-quote, and newline must be escaped (in that order — escaping
    the backslash last would corrupt the other two)."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"
