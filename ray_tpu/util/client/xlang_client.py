"""Synchronous xlang client — the reference implementation of the
cross-language wire that cpp/raytpu_client implements in C++.

Pickle-free on the wire: frames use the RTX magic and carry xlang binary
envelopes (runtime/xlang.py). Auth is the same mutual HMAC handshake and
per-frame blake2b MAC as the Python dialect (runtime/rpc.py) — this
class re-derives both from primitives (hmac/hashlib) rather than reusing
rpc.py internals, so it doubles as an executable spec for non-Python
ports: if this client can talk to the server, a byte-identical C++
implementation can too.
"""

from __future__ import annotations

import hashlib
import hmac
import socket
import struct
from typing import Any, Optional

from ray_tpu.runtime import xlang
from ray_tpu.runtime.rpc import (KIND_ERROR, KIND_REPLY, KIND_REQUEST,
                                 PROTOCOL_VERSION)

_HDR = struct.Struct("<4sI")
_X_MAGIC = b"RTX" + bytes([PROTOCOL_VERSION])
_AUTH_MAGIC = b"RTA" + bytes([PROTOCOL_VERSION])
_CHALLENGE = 32
_MAC_SIZE = 16


class XlangError(Exception):
    pass


class XlangClient:
    def __init__(self, host: str, port: int,
                 token: Optional[bytes] = None, timeout: float = 30.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.settimeout(timeout)
        self._msg_id = 0
        self._send_seq = 0
        self._recv_seq = 0
        self._mac_key: Optional[bytes] = None
        if token is not None:
            self._handshake(token)

    # -- auth (mirror of rpc.py server handshake, client side) -----------

    def _handshake(self, token: bytes) -> None:
        import os

        first = self._recv_exact(len(_AUTH_MAGIC) + _CHALLENGE)
        if first[:4] != _AUTH_MAGIC:
            raise XlangError("server did not start wire authentication")
        sc = first[4:]
        cc = os.urandom(_CHALLENGE)
        proof = hmac.new(token, b"c" + sc + cc, hashlib.sha256).digest()
        self.sock.sendall(cc + proof)
        server_proof = self._recv_exact(32)
        want = hmac.new(token, b"s" + sc + cc, hashlib.sha256).digest()
        if not hmac.compare_digest(server_proof, want):
            raise XlangError("server failed mutual authentication")
        self._mac_key = hmac.new(token, b"k" + sc + cc,
                                 hashlib.sha256).digest()

    def _tag(self, direction: bytes, seq: int, body: bytes) -> bytes:
        m = hashlib.blake2b(key=self._mac_key, digest_size=_MAC_SIZE)
        m.update(direction)
        m.update(seq.to_bytes(8, "little"))
        m.update(body)
        return m.digest()

    # -- framing ----------------------------------------------------------

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise XlangError("connection closed")
            buf += chunk
        return buf

    def _send_frame(self, kind: int, msg_id, method: str,
                    data: Any) -> None:
        body = xlang.encode_envelope(kind, msg_id, method, data)
        out = _HDR.pack(_X_MAGIC, len(body)) + body
        if self._mac_key is not None:
            out += self._tag(b"C", self._send_seq, body)
            self._send_seq += 1
        self.sock.sendall(out)

    def _recv_frame(self):
        hdr = self._recv_exact(_HDR.size)
        magic, length = _HDR.unpack(hdr)
        if magic != _X_MAGIC:
            raise XlangError(f"unexpected reply magic {magic!r}")
        body = self._recv_exact(length)
        if self._mac_key is not None:
            tag = self._recv_exact(_MAC_SIZE)
            want = self._tag(b"S", self._recv_seq, body)
            self._recv_seq += 1
            if not hmac.compare_digest(tag, want):
                raise XlangError("reply MAC verification failed")
        return xlang.decode_envelope(body)

    # -- calls ------------------------------------------------------------

    def call(self, method: str, **data) -> Any:
        self._msg_id += 1
        mid = self._msg_id
        self._send_frame(KIND_REQUEST, mid, method, data)
        while True:
            kind, msg_id, m, reply = self._recv_frame()
            if kind == KIND_REPLY and msg_id == mid:
                if isinstance(reply, dict) and reply.get("error"):
                    raise XlangError(str(reply["error"]))
                return reply
            if kind == KIND_ERROR and msg_id == mid:
                raise XlangError(str(reply))
            # pushes / stale replies are skipped (sync client)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
