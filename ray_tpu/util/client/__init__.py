"""Ray-Client-style proxy driver mode.

Reference analog: python/ray/util/client/ (__init__.py:40 RayAPIStub,
server/proxier.py) + src/ray/protobuf/ray_client.proto:325. A remote
process connects to ONE endpoint on the head node; the DRIVER runs
server-side (the proxy hosts a CoreWorker per client session), and the
client speaks a small typed op set (put/get/wait/task/actor) over the
authenticated RPC wire. Unlike attach-mode remote drivers
(core/api.py remote_client), the client never needs reachability to
raylets/workers — the proxy is the only ingress, which is the whole point
of Ray Client (firewalled laptops, notebooks).

Usage:
    server:  started with the head node (client_server_port=...) or
             ClientProxyServer(...).start()
    client:  ray_tpu.init(address="client://HOST:PORT")
"""

from ray_tpu.util.client.client import ClientAPI, connect
from ray_tpu.util.client.server import ClientProxyServer

__all__ = ["ClientAPI", "ClientProxyServer", "connect"]
