"""Client side of the proxy-driver mode.

Reference analog: python/ray/util/client/__init__.py:40 (RayAPIStub) and
api.py — a thin typed facade whose refs/actors are OPAQUE IDS naming
server-side handles. One authenticated connection to the proxy is the
only network dependency.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import cloudpickle

from ray_tpu.util.client.server import _ClientRefMarker


class ClientObjectRef:
    """Opaque handle to a server-side ObjectRef."""

    __slots__ = ("id", "_api", "__weakref__")

    def __init__(self, rid: bytes, api: "ClientAPI"):
        self.id = rid
        self._api = api

    def __repr__(self):
        return f"ClientObjectRef({self.id.hex()[:16]})"

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ClientObjectRef) and other.id == self.id

    def __del__(self):
        api = self._api
        if api is not None and not api._closed:
            api._queue_release(self.id)

    def __reduce__(self):
        raise TypeError(
            "ClientObjectRef cannot be pickled directly; pass it as a task "
            "argument instead")


class _ClientActorMethod:
    def __init__(self, api: "ClientAPI", actor_id: bytes, name: str):
        self._api = api
        self._actor_id = actor_id
        self._name = name

    def remote(self, *args, **kwargs) -> ClientObjectRef:
        return self._api._actor_call(self._actor_id, self._name, args,
                                     kwargs)


class ClientActorHandle:
    def __init__(self, api: "ClientAPI", actor_id: bytes):
        self._api = api
        self._actor_id = actor_id

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return _ClientActorMethod(self._api, self._actor_id, item)


class _ClientRemoteFn:
    def __init__(self, api: "ClientAPI", fn, options: Optional[dict] = None):
        self._api = api
        self._fn = fn
        self._options = options or {}
        self._fn_id: Optional[bytes] = None

    def options(self, **opts) -> "_ClientRemoteFn":
        out = _ClientRemoteFn(self._api, self._fn, {**self._options, **opts})
        out._fn_id = None  # per-options registration happens lazily
        return out

    def remote(self, *args, **kwargs) -> ClientObjectRef:
        if self._fn_id is None:
            reply = self._api._call(
                "client_register_fn",
                fn_blob=cloudpickle.dumps(self._fn), options={})
            self._fn_id = reply["fn_id"]
        return self._api._task(self._fn_id, args, kwargs,
                               self._options or None)


class _ClientActorClass:
    def __init__(self, api: "ClientAPI", cls, options: Optional[dict] = None):
        self._api = api
        self._cls = cls
        self._options = options or {}

    def options(self, **opts) -> "_ClientActorClass":
        return _ClientActorClass(self._api, self._cls,
                                 {**self._options, **opts})

    def remote(self, *args, **kwargs) -> ClientActorHandle:
        reply = self._api._call(
            "client_actor_create", cls_blob=cloudpickle.dumps(self._cls),
            args_blob=self._api._pack_args(args, kwargs),
            options=self._options)
        if "error" in reply:
            raise RuntimeError(reply["error"])
        return ClientActorHandle(self._api, reply["actor_id"])


class ClientAPI:
    """The `ray_tpu` surface over one proxy connection."""

    def __init__(self, host: str, port: int):
        from ray_tpu.runtime import rpc as rpc_mod
        from ray_tpu.runtime.rpc import EventLoopThread, RpcClient

        rpc_mod.load_token_for_address(host, port)
        self.io = EventLoopThread("ray_tpu_client")
        self._client = RpcClient(host, port, auto_reconnect=True)
        self.io.run(self._client.connect(timeout=30))
        self._closed = False
        hello = self._call("client_hello")
        self.client_id = hello["client_id"]

    # -- plumbing ----------------------------------------------------------

    def _call(self, method: str, **kw):
        reply = self.io.run(self._client.call(method, **kw), timeout=600)
        return reply

    def _queue_release(self, rid: bytes):
        """Fire-and-forget server-side handle release."""
        try:
            self.io.spawn(self._client.call("client_release", refs=[rid]))
        except Exception:
            pass

    def _pack_args(self, args: Tuple, kwargs: Dict) -> bytes:
        def mark(v):
            if isinstance(v, ClientObjectRef):
                return _ClientRefMarker(v.id)
            return v

        return cloudpickle.dumps(
            (tuple(mark(a) for a in args),
             {k: mark(v) for k, v in kwargs.items()}))

    def _task(self, fn_id: bytes, args, kwargs, options) -> ClientObjectRef:
        reply = self._call("client_task", fn_id=fn_id,
                           args_blob=self._pack_args(args, kwargs),
                           options=options)
        if "error" in reply:
            raise RuntimeError(reply["error"])
        return ClientObjectRef(reply["ref"], self)

    def _actor_call(self, actor_id: bytes, method: str, args,
                    kwargs) -> ClientObjectRef:
        reply = self._call("client_actor_call", actor_id=actor_id,
                           method_name=method,
                           args_blob=self._pack_args(args, kwargs))
        if "error" in reply:
            raise RuntimeError(reply["error"])
        return ClientObjectRef(reply["ref"], self)

    # -- public api --------------------------------------------------------

    def put(self, value: Any) -> ClientObjectRef:
        from ray_tpu.core import serialization

        segs, _total = serialization.serialize(value)
        reply = self._call("client_put",
                           payload=serialization.join_segments(segs))
        return ClientObjectRef(reply["ref"], self)

    def get(self, refs, timeout: Optional[float] = None):
        from ray_tpu.core import serialization

        single = isinstance(refs, ClientObjectRef)
        ref_list = [refs] if single else list(refs)
        reply = self._call("client_get", refs=[r.id for r in ref_list],
                           timeout_s=timeout)
        if "error" in reply:
            exc = reply.get("exception")
            raise exc if isinstance(exc, BaseException) else RuntimeError(
                reply["error"])
        values = [serialization.deserialize(memoryview(v))
                  for v in reply["values"]]
        return values[0] if single else values

    def wait(self, refs: Sequence[ClientObjectRef], *, num_returns: int = 1,
             timeout: Optional[float] = None):
        by_id = {r.id: r for r in refs}
        reply = self._call("client_wait", refs=[r.id for r in refs],
                           num_returns=num_returns, timeout_s=timeout)
        return ([by_id[r] for r in reply["ready"]],
                [by_id[r] for r in reply["pending"]])

    def remote(self, obj=None, **options):
        if obj is None:
            return lambda o: self.remote(o, **options)
        if isinstance(obj, type):
            return _ClientActorClass(self, obj, options or None)
        return _ClientRemoteFn(self, obj, options or None)

    def get_actor(self, name: str) -> ClientActorHandle:
        reply = self._call("client_get_actor", name=name)
        if "error" in reply:
            raise ValueError(reply["error"])
        return ClientActorHandle(self, reply["actor_id"])

    def kill(self, handle: ClientActorHandle):
        self._call("client_kill_actor", actor_id=handle._actor_id)

    def disconnect(self):
        self._closed = True
        try:
            self.io.run(self._client.close(), timeout=10)
        except Exception:
            pass
        self.io.stop()


def connect(address: str) -> ClientAPI:
    """connect("host:port") or connect("client://host:port")."""
    address = address.replace("client://", "")
    host, port = address.rsplit(":", 1)
    return ClientAPI(host, int(port))
