"""Client proxy server: hosts the driver for remote clients.

Reference analog: python/ray/util/client/server/{server.py,proxier.py} —
one server-side session per client connection, executing ray ops against
an in-cluster driver and holding the object/actor references the client
names by id. A dropped client connection tears its session down
(reference: client disconnect reaps the proxied driver), releasing every
reference it held.
"""

from __future__ import annotations

import logging
import uuid
from typing import Any, Dict, Optional

logger = logging.getLogger(__name__)


class _Session:
    """Per-client-connection state: named handles the client refers to."""

    def __init__(self, client_id: str):
        self.client_id = client_id
        self.refs: Dict[bytes, Any] = {}      # ref id -> ObjectRef
        self.actors: Dict[bytes, Any] = {}    # actor id -> ActorHandle
        self.fns: Dict[bytes, Any] = {}       # fn id -> RemoteFunction
        self.hosted_workers: set = set()      # hosted worker ids (xlang)


class ClientProxyServer:
    """RPC server for client sessions; runs inside a cluster-attached
    process (the head driver, or a dedicated proxy process)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        from ray_tpu.runtime.rpc import RpcServer

        self.server = RpcServer(host, port)
        self.server.register_all(self, prefix="handle_")
        self.server.on_disconnect = self._on_disconnect
        # _sessions is confined to the IO loop (handlers + disconnect
        # callbacks all run there): no lock needed.
        self._sessions: Dict[int, _Session] = {}

    def start(self):
        from ray_tpu.core.worker import global_worker

        core = global_worker()  # must be cluster-attached already
        core.io.run(self.server.start())
        return self.server.address

    def stop(self):
        from ray_tpu.core.worker import global_worker

        try:
            global_worker().io.run(self.server.close())
        except Exception:
            pass
        pool = getattr(self, "_poll_pool", None)
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)

    # -- session plumbing --------------------------------------------------

    def _session(self, conn) -> _Session:
        key = id(conn)
        s = self._sessions.get(key)
        if s is None:
            s = _Session(uuid.uuid4().hex[:12])
            self._sessions[key] = s
            conn.meta["client_session"] = s.client_id
        return s

    async def _on_disconnect(self, conn):
        s = self._sessions.pop(id(conn), None)
        if s is None:
            return
        # Dropping the session's handle dicts releases the proxied
        # driver's references (ObjectRef __del__ -> ref_dropped).
        logger.info("client session %s disconnected (%d refs, %d actors)",
                    s.client_id, len(s.refs), len(s.actors))
        s.refs.clear()
        s.actors.clear()
        s.fns.clear()
        if s.hosted_workers:
            # A dead hosted worker must fail its queued/in-flight tasks,
            # not leave driver get()s hanging.
            from ray_tpu.util import cross_language

            for worker_id in s.hosted_workers:
                cross_language.hosted_unregister(worker_id)
            s.hosted_workers.clear()

    @staticmethod
    def _run(fn, *args, **kwargs):
        """User-facing ray ops are synchronous; run them off the IO loop."""
        import asyncio

        loop = asyncio.get_event_loop()
        return loop.run_in_executor(None, lambda: fn(*args, **kwargs))

    # -- ops ---------------------------------------------------------------

    async def handle_client_hello(self, conn):
        import ray_tpu

        s = self._session(conn)
        # Off-loop: every ray op blocks on the core worker's IO loop, and
        # these handlers RUN on that loop.
        resources = await self._run(ray_tpu.cluster_resources)
        return {"ok": True, "client_id": s.client_id,
                "cluster_resources": resources}

    async def handle_client_put(self, conn, payload: bytes):
        import ray_tpu
        from ray_tpu.core import serialization

        s = self._session(conn)
        value = serialization.deserialize(memoryview(payload))
        ref = await self._run(ray_tpu.put, value)
        return {"ref": self._track_ref(s, ref)}

    async def handle_client_get(self, conn, refs,
                                timeout_s: Optional[float] = None):
        import ray_tpu
        from ray_tpu.core import serialization

        s = self._session(conn)
        try:
            targets = [s.refs[r] for r in refs]
        except KeyError as e:
            return {"error": f"unknown ref {e}"}
        try:
            values = await self._run(ray_tpu.get, targets, timeout=timeout_s)
        except Exception as e:
            return {"error": repr(e), "exception": _safe_exc(e)}
        return {"values": [serialization.join_segments(
            serialization.serialize(v)[0]) for v in values]}

    async def handle_client_wait(self, conn, refs, num_returns: int,
                                 timeout_s: Optional[float] = None):
        import ray_tpu

        s = self._session(conn)
        try:
            targets = [s.refs[r] for r in refs]
        except KeyError as e:
            return {"error": f"unknown ref {e}"}
        ready, pending = await self._run(
            ray_tpu.wait, targets, num_returns=num_returns,
            timeout=timeout_s)
        by_obj = {id(s.refs[r]): r for r in refs}
        return {"ready": [by_obj[id(o)] for o in ready],
                "pending": [by_obj[id(o)] for o in pending]}

    async def handle_client_register_fn(self, conn, fn_blob: bytes,
                                        options: dict):
        import cloudpickle

        import ray_tpu

        s = self._session(conn)
        fn = cloudpickle.loads(fn_blob)
        rf = ray_tpu.remote(fn)
        if options:
            rf = rf.options(**options)
        fid = uuid.uuid4().bytes[:8]
        s.fns[fid] = rf
        return {"fn_id": fid}

    def _resolve_args(self, s: _Session, args_blob: bytes):
        import cloudpickle

        args, kwargs = cloudpickle.loads(args_blob)

        def resolve(v):
            if isinstance(v, _ClientRefMarker):
                return s.refs[v.ref_id]
            return v

        return ([resolve(a) for a in args],
                {k: resolve(v) for k, v in kwargs.items()})

    async def handle_client_task(self, conn, fn_id: bytes, args_blob: bytes,
                                 options: Optional[dict] = None):
        s = self._session(conn)
        rf = s.fns.get(fn_id)
        if rf is None:
            return {"error": f"unknown fn {fn_id!r}"}
        args, kwargs = self._resolve_args(s, args_blob)
        target = rf.options(**options) if options else rf
        ref = await self._run(target.remote, *args, **kwargs)
        return {"ref": self._track_ref(s, ref)}

    async def handle_client_actor_create(self, conn, cls_blob: bytes,
                                         args_blob: bytes, options: dict):
        import cloudpickle

        import ray_tpu

        s = self._session(conn)
        cls = cloudpickle.loads(cls_blob)
        ac = ray_tpu.remote(cls)
        if options:
            ac = ac.options(**options)
        args, kwargs = self._resolve_args(s, args_blob)
        handle = await self._run(ac.remote, *args, **kwargs)
        aid = handle._actor_id
        s.actors[aid] = handle
        return {"actor_id": aid}

    async def handle_client_actor_call(self, conn, actor_id: bytes,
                                       method_name: str, args_blob: bytes):
        s = self._session(conn)
        handle = s.actors.get(actor_id)
        if handle is None:
            return {"error": f"unknown actor {actor_id.hex()[:12]}"}
        args, kwargs = self._resolve_args(s, args_blob)
        ref = await self._run(
            getattr(handle, method_name).remote, *args, **kwargs)
        return {"ref": self._track_ref(s, ref)}

    async def handle_client_get_actor(self, conn, name: str,
                                      namespace: Optional[str] = None):
        import ray_tpu

        s = self._session(conn)
        try:
            handle = await self._run(ray_tpu.get_actor, name)
        except Exception as e:
            return {"error": repr(e)}
        s.actors[handle._actor_id] = handle
        return {"actor_id": handle._actor_id}

    async def handle_client_kill_actor(self, conn, actor_id: bytes):
        import ray_tpu

        s = self._session(conn)
        handle = s.actors.pop(actor_id, None)
        if handle is not None:
            await self._run(ray_tpu.kill, handle)
        return {"ok": handle is not None}

    def session_count(self) -> int:
        return len(self._sessions)

    # -- cross-language ops (xlang dialect; see runtime/xlang.py) ----------
    #
    # Non-Python peers (cpp/raytpu_client) reach the cluster through these.
    # Args/results are restricted to the xlang vocabulary; object refs
    # travel as bytes and may appear inside args as {"$ref": <bytes>}.

    @staticmethod
    def _track_ref(s: _Session, ref) -> bytes:
        rid = ref.id.binary() if hasattr(ref, "id") else ref.binary()
        s.refs[rid] = ref
        return rid

    @staticmethod
    def _xresolve_args(s: _Session, args, kwargs):
        def resolve(v):
            if isinstance(v, dict):
                if set(v) == {"$ref"}:
                    rid = v["$ref"]
                    if rid not in s.refs:
                        raise _UnknownRef(rid)
                    return s.refs[rid]
                return {k: resolve(x) for k, x in v.items()}
            if isinstance(v, list):
                return [resolve(x) for x in v]
            return v

        return ([resolve(a) for a in (args or [])],
                {k: resolve(v) for k, v in (kwargs or {}).items()})

    async def handle_xhello(self, conn):
        import ray_tpu

        s = self._session(conn)
        resources = await self._run(ray_tpu.cluster_resources)
        return {"ok": True, "client_id": s.client_id,
                "cluster_resources": resources}

    async def handle_xcall(self, conn, name: str, args=None, kwargs=None,
                           options=None):
        """Invoke a named/importable Python function as a remote task."""
        import ray_tpu
        from ray_tpu.util import cross_language

        s = self._session(conn)
        fn = cross_language.resolve(name)
        rf = ray_tpu.remote(fn)
        if options:
            rf = rf.options(**options)
        try:
            a, kw = self._xresolve_args(s, args, kwargs)
        except _UnknownRef as e:
            return {"error": str(e)}
        ref = await self._run(rf.remote, *a, **kw)
        return {"ref": self._track_ref(s, ref)}

    async def handle_xput(self, conn, value):
        import ray_tpu

        s = self._session(conn)
        ref = await self._run(ray_tpu.put, value)
        return {"ref": self._track_ref(s, ref)}

    async def handle_xget(self, conn, refs, timeout_s=None):
        import ray_tpu

        s = self._session(conn)
        try:
            targets = [s.refs[r] for r in refs]
        except KeyError as e:
            return {"error": f"unknown ref {e}"}
        try:
            values = await self._run(ray_tpu.get, targets, timeout=timeout_s)
        except Exception as e:
            return {"error": f"{type(e).__name__}: {e}"}
        # Representability is enforced once, at the transport encode
        # (ServerConnection.send turns XEncodeError into a structured
        # error reply) — no second serialization pass here.
        return {"values": list(values)}

    async def handle_xwait(self, conn, refs, num_returns: int = 1,
                           timeout_s=None):
        import ray_tpu

        s = self._session(conn)
        try:
            targets = [s.refs[r] for r in refs]
        except KeyError as e:
            return {"error": f"unknown ref {e}"}
        ready, pending = await self._run(
            ray_tpu.wait, targets, num_returns=num_returns,
            timeout=timeout_s)
        by_obj = {id(s.refs[r]): r for r in refs}
        return {"ready": [by_obj[id(o)] for o in ready],
                "pending": [by_obj[id(o)] for o in pending]}

    async def handle_xactor_get(self, conn, name: str):
        import ray_tpu

        s = self._session(conn)
        try:
            handle = await self._run(ray_tpu.get_actor, name)
        except Exception as e:
            return {"error": f"{type(e).__name__}: {e}"}
        s.actors[handle._actor_id] = handle
        return {"actor_id": handle._actor_id}

    async def handle_xactor_call(self, conn, actor_id: bytes, method: str,
                                 args=None, kwargs=None):
        s = self._session(conn)
        handle = s.actors.get(actor_id)
        if handle is None:
            return {"error": f"unknown actor {actor_id.hex()[:12]}"}
        try:
            a, kw = self._xresolve_args(s, args, kwargs)
        except _UnknownRef as e:
            return {"error": str(e)}
        ref = await self._run(getattr(handle, method).remote, *a, **kw)
        return {"ref": self._track_ref(s, ref)}

    async def handle_xkv_get(self, conn, key: str):
        from ray_tpu.core.worker import global_worker

        reply = await global_worker().gcs.call("kv_get", key=key.encode())
        return {"value": reply.get("value")}

    async def handle_xkv_put(self, conn, key: str, value: bytes):
        from ray_tpu.core.worker import global_worker

        reply = await global_worker().gcs.call(
            "kv_put", key=key.encode(), value=value)
        return {"ok": bool(reply.get("ok"))}

    async def handle_xrelease(self, conn, refs):
        s = self._session(conn)
        for r in refs:
            s.refs.pop(r, None)
        return {"ok": True}

    async def handle_client_release(self, conn, refs):
        """Client-side ref went out of scope: drop the proxy's handle."""
        s = self._session(conn)
        for r in refs:
            s.refs.pop(r, None)
        return {"ok": True}

    # -- hosted (foreign-executing) workers --------------------------------
    #
    # The reverse of xcall: a C++ (or other non-Python) process registers
    # functions it EXECUTES, long-polls for tasks, and pushes results.
    # Python drivers submit via cross_language.hosted("name").remote(...).
    # Reference analog: cpp/src/ray/runtime/task/task_executor.cc.

    async def handle_xworker_register(self, conn, name: str, functions):
        from ray_tpu.util import cross_language

        s = self._session(conn)
        worker_id = cross_language.hosted_register(name, list(functions))
        s.hosted_workers.add(worker_id)
        return {"worker_id": worker_id}

    async def handle_xworker_poll(self, conn, worker_id: bytes,
                                  timeout_s: float = 10.0):
        import asyncio

        from ray_tpu.util import cross_language

        loop = asyncio.get_event_loop()
        if not hasattr(self, "_poll_pool"):
            # Dedicated pool: long-polls parked on the DEFAULT executor
            # would occupy its handful of threads (cpu_count+4 — five on
            # the 1-core box) and starve every other handler's _run().
            from concurrent.futures import ThreadPoolExecutor

            self._poll_pool = ThreadPoolExecutor(
                max_workers=16, thread_name_prefix="xworker-poll")
        try:
            task = await loop.run_in_executor(
                self._poll_pool, cross_language.hosted_poll, worker_id,
                float(timeout_s))
        except KeyError as e:
            return {"error": str(e)}
        if task is None:
            return {"idle": True}
        return {"task_id": task["task_id"], "fn": task["fn"],
                "args": task["args"]}

    async def handle_xworker_result(self, conn, worker_id: bytes,
                                    task_id: bytes, status: str,
                                    value=None, error: str = ""):
        from ray_tpu.util import cross_language

        try:
            cross_language.hosted_result(worker_id, task_id, status,
                                         value=value, error=error)
        except KeyError as e:
            return {"error": str(e)}
        return {"ok": True}

    async def handle_xworker_unregister(self, conn, worker_id: bytes):
        from ray_tpu.util import cross_language

        cross_language.hosted_unregister(worker_id)
        s = self._session(conn)
        s.hosted_workers.discard(worker_id)
        return {"ok": True}


def _safe_exc(e: BaseException):
    import cloudpickle

    try:
        cloudpickle.dumps(e)
        return e
    except Exception:
        return None


class _UnknownRef(KeyError):
    """A {"$ref": ...} arg names a ref this session doesn't hold (released
    via xrelease, or stale after reconnect)."""

    def __init__(self, rid: bytes):
        super().__init__(rid)
        self.rid = rid

    def __str__(self):
        return f"unknown ref {self.rid.hex()[:24]}"


class _ClientRefMarker:
    """Placeholder for a client-held ref inside pickled task args."""

    __slots__ = ("ref_id",)

    def __init__(self, ref_id: bytes):
        self.ref_id = ref_id
