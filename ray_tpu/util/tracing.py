"""Task tracing: spans around submit/execute, optional OpenTelemetry export.

Reference analog: python/ray/util/tracing/tracing_helper.py (lazy otel import
:36-57; @_tracing_task_invocation wrapping RemoteFunction._remote at
remote_function.py:302). The TPU build records spans into an in-process ring
buffer always (cheap), and mirrors them to OpenTelemetry when the user has
opentelemetry-sdk installed and tracing enabled; ``ray_tpu.scripts timeline``
dumps the ring as a chrome://tracing JSON file.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Optional

_MAX_SPANS = int(os.environ.get("RAY_TPU_TRACE_BUFFER", "10000"))
_spans = collections.deque(maxlen=_MAX_SPANS)
_lock = threading.Lock()
_enabled = os.environ.get("RAY_TPU_TRACING", "1") != "0"

_otel_tracer = None

# -- cross-process trace context ---------------------------------------------
# W3C-traceparent-shaped propagation (tracing_helper.py:_inject_tracing
# analog, minus the otel hard dependency): every span mints an 8-byte span
# id and joins the thread's current trace (minting a 16-byte trace id at
# the root). submit_task copies the caller's (trace_id, span_id) into the
# TaskSpec wire envelope (TaskSpecMsg fields 17/18); the executing worker
# adopts them via trace_context() so the execute span — and any spans the
# task body opens, including nested submits — carry the same trace id and
# parent-link back to the driver-side submit span. Stitching is by id, not
# wall clock, so it survives process boundaries and clock skew.
_ctx = threading.local()


def request_trace_id(request_id: str) -> bytes:
    """Deterministic 16-byte trace id for one LLM serving request.

    Derived from crc32(request_id) — the same function the engine seeds
    sampling from — so EVERY process that handles the request (router,
    prefill replica, decode replica, migration target, the CLI after the
    fact) computes the identical trace id from the rid alone. Stitching a
    request's spans across failover replays and live migration therefore
    needs no side channel: the rid is the trace identity; the disagg wire
    only carries parent-span linkage."""
    import zlib

    rid = request_id.encode()
    return b"".join(
        zlib.crc32(rid + bytes([i])).to_bytes(4, "big") for i in range(4))


def current_trace_id() -> Optional[bytes]:
    return getattr(_ctx, "trace_id", None)


def current_span_id() -> Optional[bytes]:
    return getattr(_ctx, "span_id", None)


@contextmanager
def trace_context(trace_id: Optional[bytes],
                  parent_span_id: Optional[bytes]):
    """Adopt a propagated (trace_id, parent_span_id) pair — the executor
    side of the TaskSpec trace fields. Spans opened inside parent to the
    propagated span id; the previous thread context is restored on exit."""
    prev = (getattr(_ctx, "trace_id", None), getattr(_ctx, "span_id", None))
    _ctx.trace_id = trace_id
    _ctx.span_id = parent_span_id
    try:
        yield
    finally:
        _ctx.trace_id, _ctx.span_id = prev


def _get_otel():
    """Lazy optional OpenTelemetry tracer (absent in the base image)."""
    global _otel_tracer
    if _otel_tracer is None:
        try:
            from opentelemetry import trace  # type: ignore
            _otel_tracer = trace.get_tracer("ray_tpu")
        except Exception:
            _otel_tracer = False
    return _otel_tracer or None


def enabled() -> bool:
    return _enabled


def set_enabled(value: bool):
    global _enabled
    _enabled = value


@contextmanager
def span(name: str, kind: str, **attrs):
    """Record one span; nests naturally via wall-clock containment."""
    if not _enabled:
        yield
        return
    otel = _get_otel()
    ctx = otel.start_as_current_span(name) if otel else None
    if ctx is not None:
        ctx.__enter__()
    trace_id = getattr(_ctx, "trace_id", None) or os.urandom(16)
    parent = getattr(_ctx, "span_id", None)
    span_id = os.urandom(8)
    prev = (getattr(_ctx, "trace_id", None), getattr(_ctx, "span_id", None))
    _ctx.trace_id, _ctx.span_id = trace_id, span_id
    start = time.time()
    try:
        yield
    finally:
        _ctx.trace_id, _ctx.span_id = prev
        end = time.time()
        ids = {"trace_id": trace_id.hex(), "span_id": span_id.hex()}
        if parent is not None:
            ids["parent_span_id"] = parent.hex()
        with _lock:
            _spans.append({"name": name, "cat": kind, "ts": start * 1e6,
                           "dur": (end - start) * 1e6, "ph": "X",
                           "pid": os.getpid(),
                           "tid": threading.get_ident() % 100000,
                           "args": {**ids, **attrs}})
        if ctx is not None:
            ctx.__exit__(None, None, None)


def record_span(name: str, kind: str, start: float, end: float, **attrs):
    """Append a span retroactively from measured wall-clock bounds.

    For code that times phases itself (e.g. Train closes a step record at
    `session.report()` — the step's extent is only known after the fact).
    The span joins the thread's current trace context exactly like
    `span()` would."""
    if not _enabled:
        return
    trace_id = getattr(_ctx, "trace_id", None) or os.urandom(16)
    parent = getattr(_ctx, "span_id", None)
    ids = {"trace_id": trace_id.hex(), "span_id": os.urandom(8).hex()}
    if parent is not None:
        ids["parent_span_id"] = parent.hex()
    with _lock:
        _spans.append({"name": name, "cat": kind, "ts": start * 1e6,
                       "dur": max(0.0, end - start) * 1e6, "ph": "X",
                       "pid": os.getpid(),
                       "tid": threading.get_ident() % 100000,
                       "args": {**ids, **attrs}})


def get_spans() -> list:
    with _lock:
        return list(_spans)


def dump_chrome_trace(path: str):
    """Write the span ring in chrome://tracing 'traceEvents' format
    (the `ray timeline` CLI analog)."""
    with open(path, "w") as f:
        json.dump({"traceEvents": get_spans()}, f)


def merge_spans(groups) -> list:
    """Merge per-process span rings into one chrome traceEvents list.

    `groups` is an iterable of (label, spans) — one entry per process, as
    returned by the cluster `dump_spans` fan-out. os.getpid() collides
    across hosts, so every (label, original pid) pair is remapped to a
    unique lane and announced with a process_name metadata event; the
    trace/span ids in each span's `args` are untouched — they are what
    stitches submit -> execute -> nested submit across lanes."""
    events, lanes = [], {}
    for label, spans in groups:
        for s in spans:
            key = (label, s.get("pid"))
            lane = lanes.get(key)
            if lane is None:
                lane = lanes[key] = len(lanes) + 1
                events.append({"ph": "M", "name": "process_name",
                               "pid": lane,
                               "args": {"name": f"{label} (pid {s.get('pid')})"}})
            ev = dict(s)
            ev["pid"] = lane
            events.append(ev)
    return events
