"""Proactive object broadcast: replicate a plasma object to every node.

Reference analog: src/ray/object_manager/push_manager.h:30 (chunked pushes)
and the release-benchmark envelope case "1 GiB object broadcast, 50+ nodes"
(release/benchmarks/README.md:18). Ours relays through a fanout tree of
raylets (runtime/raylet handle_fetch_and_relay): depth O(log_f n), and no
node uploads more than f copies — the owner is not a bottleneck. After
broadcast, tasks on any node read the object zero-copy from their local
store instead of pulling on demand.

Each relay hop moves the object over the raw-frame object plane
(raylet._pull_from -> handle_pull_object_raw): chunks ride as framed
payload bytes straight from the store arena into a preallocated receive
buffer, so a 1 GiB broadcast never materializes an intermediate pickle of
the object on any hop (see docs/control_plane.md).
"""

from __future__ import annotations

from typing import List, Optional


def broadcast_object(ref, node_ids: Optional[List[bytes]] = None,
                     timeout: float = 600.0) -> int:
    """Replicate `ref`'s object to `node_ids` (default: every alive node).
    Returns the number of nodes newly covered. Blocking."""
    import ray_tpu
    from ray_tpu.config import cfg
    from ray_tpu.core.worker import global_worker

    core = global_worker()
    oid = ref.binary()
    nodes = {bytes.fromhex(n["node_id"]) if isinstance(n["node_id"], str)
             else n["node_id"]: tuple(n["address"])
             for n in ray_tpu.nodes() if n.get("alive", True)}
    # Root = a node that already holds the object.
    if core.store is not None and core.store.contains(oid):
        root = core.node_id
    else:
        root = core._object_locations.get(oid) or ref.owner
    if root not in nodes:
        raise ValueError(f"object {oid.hex()[:12]} location unknown")
    wanted = node_ids if node_ids is not None else list(nodes)
    targets = [nodes[nid] for nid in wanted
               if nid != root and nid in nodes]
    if not targets:
        return 0

    async def _run():
        client = await core._raylet_for(nodes[root])
        return await client.call(
            "fetch_and_relay", oid=oid, source=nodes[root], targets=targets,
            fanout=cfg().broadcast_fanout, timeout=timeout)

    reply = core.io.run(_run(), timeout=timeout + 10)
    if not reply.get("ok"):
        raise RuntimeError(f"broadcast failed: {reply.get('error')}")
    return len(targets)
