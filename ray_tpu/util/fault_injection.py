"""Fault-injection utilities for chaos testing.

Reference analog: python/ray/_private/test_utils.py:1512 ResourceKillerActor
and :1587 NodeKillerBase (actors that kill raylets/components on an
interval), and the chaos release harness (release/nightly_tests/
setup_chaos.py). Ours are plain threads driving a `Cluster`
(ray_tpu.cluster_utils) — the in-process multi-node utility — because the
killer must outlive the nodes it kills.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Callable, List, Optional

logger = logging.getLogger(__name__)


class NodeKiller:
    """Periodically kills a random non-head worker node in the cluster.

    `respawn=True` adds a replacement node (same resources and labels) after
    each kill, keeping cluster capacity roughly constant while churning node
    ids — the elastic-recovery scenario. Respawn errors are counted in
    `respawn_failures` (the cluster may legitimately be shutting down under
    us) and the killer keeps running."""

    def __init__(self, cluster, interval_s: float = 1.0, *,
                 respawn: bool = True, seed: int = 0,
                 max_kills: Optional[int] = None,
                 node_filter: Optional[Callable] = None):
        self.cluster = cluster
        self.interval_s = interval_s
        self.respawn = respawn
        self.max_kills = max_kills
        self.node_filter = node_filter or (lambda node: True)
        self.kills: List[str] = []
        self.respawn_failures = 0
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="chaos-node-killer")
        self._thread.start()
        return self

    def _pick_victim(self):
        victims = [n for n in self.cluster.nodes
                   if n.proc.poll() is None and self.node_filter(n)]
        return self._rng.choice(victims) if victims else None

    def _kill_one(self, node) -> bool:
        """Kill `node` and optionally respawn a replacement. Returns True if
        the kill happened."""
        node_id = node.node_id.hex()[:12]
        resources = dict(node.resources)
        labels = dict(getattr(node, "labels", {}) or {})
        try:
            self.cluster.remove_node(node, force=True)
        except Exception:
            logger.warning("NodeKiller: failed to kill node %s",
                           node_id, exc_info=True)
            return False
        self.kills.append(node_id)
        logger.info("NodeKiller: killed node %s (kill #%d)",
                    node_id, len(self.kills))
        if self.respawn:
            try:
                num_cpus = resources.pop("CPU", 1.0)
                num_tpus = resources.pop("TPU", 0.0)
                self.cluster.add_node(num_cpus=num_cpus,
                                      num_tpus=num_tpus,
                                      resources=resources or None,
                                      labels=labels or None)
            except Exception:
                self.respawn_failures += 1
                logger.warning(
                    "NodeKiller: failed to respawn replacement for node %s "
                    "(%d respawn failure(s) so far)",
                    node_id, self.respawn_failures, exc_info=True)
        return True

    def _run(self):
        while not self._stop.wait(self.interval_s):
            if self.max_kills is not None and len(self.kills) >= self.max_kills:
                return
            node = self._pick_victim()
            if node is None:
                continue
            self._kill_one(node)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)


class SliceKiller(NodeKiller):
    """Kills ONE host of a multi-host TPU slice (by `tpu-slice-name` label).

    The point of killing a single host: the GCS must fate-share the
    surviving siblings (a slice is one ICI failure domain), and anything
    blocked in a collective over that slice must abort fast. Targets only
    nodes whose slice has >= `min_slice_hosts` live members, so single-host
    slices (which trivially fate-share) are skipped.

    `slice_name=None` picks a random qualifying slice per kill. With
    `respawn=True` the replacement host carries the SAME slice label — the
    "repaired slice rejoins" scenario (note the GCS will have already marked
    the old siblings dead; respawn restores capacity, not the old slice).
    Use `strike()` for a one-shot kill without starting the interval thread.
    """

    def __init__(self, cluster, interval_s: float = 1.0, *,
                 slice_name: Optional[str] = None,
                 min_slice_hosts: int = 2,
                 respawn: bool = False, seed: int = 0,
                 max_kills: Optional[int] = None):
        self.slice_name = slice_name
        self.min_slice_hosts = min_slice_hosts
        super().__init__(cluster, interval_s, respawn=respawn, seed=seed,
                         max_kills=max_kills, node_filter=self._in_target_slice)

    def _live_slice_sizes(self):
        sizes: dict = {}
        for n in self.cluster.nodes:
            name = (getattr(n, "labels", {}) or {}).get("tpu-slice-name")
            if name and n.proc.poll() is None:
                sizes[name] = sizes.get(name, 0) + 1
        return sizes

    def _in_target_slice(self, node) -> bool:
        name = (getattr(node, "labels", {}) or {}).get("tpu-slice-name")
        if name is None:
            return False
        if self.slice_name is not None and name != self.slice_name:
            return False
        return self._live_slice_sizes().get(name, 0) >= self.min_slice_hosts

    def strike(self) -> Optional[str]:
        """Kill one qualifying slice host NOW (no thread). Returns the short
        node id of the victim, or None if no slice qualifies."""
        node = self._pick_victim()
        if node is None:
            logger.warning("SliceKiller: no multi-host slice to strike")
            return None
        node_id = node.node_id.hex()[:12]
        slice_name = (getattr(node, "labels", {}) or {}).get("tpu-slice-name")
        if self._kill_one(node):
            logger.info("SliceKiller: struck host %s of slice %r",
                        node_id, slice_name)
            return node_id
        return None


class PreemptionKiller(NodeKiller):
    """Advance-notice preemption: drain notice now, hard kill at deadline.

    Models a spot/preemptible reclaim end to end: `strike()` picks a
    victim, issues the GCS `drain_node(node_id, reason, deadline_s=
    notice_s)` two-phase drain (scheduler stops leasing onto it, its
    raylet migrates primary object copies, Train/RLHF checkpoint and
    re-form proactively), then a timer thread force-kills the raylet at
    the deadline — whatever didn't migrate in time falls back to the
    reactive paths (fate-sharing, lineage reconstruction, gang restart).

    `notice_s <= 0` is the no-notice shape: immediate drain-as-kill (the
    GCS treats a non-positive deadline as a straight NODE_PREEMPTED
    death), exercising the purely reactive recovery the graceful plane
    falls back to. With `respawn=True` a replacement node (same
    resources/labels) is added AT NOTICE TIME, standing in for the
    autoscaler's replacement launch so re-forming gangs have somewhere
    to go before the deadline."""

    def __init__(self, cluster, notice_s: float = 10.0, *,
                 reason: str = "chaos preemption", respawn: bool = True,
                 seed: int = 0,
                 node_filter: Optional[Callable] = None):
        self.notice_s = notice_s
        self.reason = reason
        self.struck: List[str] = []
        self._timers: List[threading.Timer] = []
        # Respawn is handled here at NOTICE time (see strike), never by
        # the inherited deadline-kill path.
        self._respawn_replacement = respawn
        super().__init__(cluster, interval_s=3600.0, respawn=False,
                         seed=seed, node_filter=node_filter)

    def _drain(self, node_id: bytes) -> bool:
        """Issue the drain RPC from a fresh client (the killer outlives
        any driver worker, so it cannot borrow one's GCS connection)."""
        import asyncio

        from ray_tpu.runtime.rpc import RpcClient

        async def call():
            client = RpcClient(*self.cluster.gcs_address)
            await client.connect(timeout=5)
            try:
                return await client.call(
                    "drain_node", node_id=node_id, reason=self.reason,
                    deadline_s=self.notice_s, timeout=10)
            finally:
                await client.close()

        try:
            return bool(asyncio.run(call()).get("ok"))
        except Exception:
            logger.warning("PreemptionKiller: drain_node failed",
                           exc_info=True)
            return False

    def _respawn_like(self, resources: dict, labels: dict):
        """Replacement capacity, standing in for the autoscaler's
        notice-time replacement launch."""
        if not self._respawn_replacement:
            return
        try:
            res = dict(resources)
            self.cluster.add_node(num_cpus=res.pop("CPU", 1.0),
                                  num_tpus=res.pop("TPU", 0.0),
                                  resources=res or None,
                                  labels=dict(labels) or None)
        except Exception:
            self.respawn_failures += 1
            logger.warning("PreemptionKiller: replacement respawn failed",
                           exc_info=True)

    def strike(self, node=None) -> Optional[str]:
        """Preempt one qualifying node NOW: drain notice + replacement
        capacity immediately, then a timed hard kill `notice_s` later.
        Returns the victim's short node id (before the kill lands), or
        None if no node qualifies.

        `node` pins the victim — a Cluster node object or a node-id hex
        prefix — for scripted chaos scenarios ("drain THIS replica's
        node, outright-kill THAT one") where the seeded random pick
        would make the assertion depend on the draw."""
        if node is not None and isinstance(node, str):
            node = next((n for n in self.cluster.nodes
                         if n.node_id.hex().startswith(node)
                         and n.proc.poll() is None), None)
        if node is None:
            node = self._pick_victim()
        if node is None:
            logger.warning("PreemptionKiller: no node to preempt")
            return None
        short = node.node_id.hex()[:12]
        resources = dict(node.resources)
        labels = dict(getattr(node, "labels", {}) or {})
        if self.notice_s <= 0:
            # No-notice preemption: the GCS marks it dead (reactive path),
            # then the process goes away and the replacement arrives late.
            self._drain(node.node_id)
            self._kill_one(node)
            self._respawn_like(resources, labels)
            self.struck.append(short)
            return short
        self._drain(node.node_id)
        self._respawn_like(resources, labels)
        self.struck.append(short)
        logger.info("PreemptionKiller: drain notice for node %s "
                    "(kill in %.1fs)", short, self.notice_s)
        timer = threading.Timer(self.notice_s, self._deadline_kill, (node,))
        timer.daemon = True
        timer.start()
        self._timers.append(timer)
        return short

    def _deadline_kill(self, node):
        if node.proc.poll() is not None:
            return  # already down (GCS deadline enforcement won the race)
        self._kill_one(node)

    def stop(self):
        for t in self._timers:
            t.cancel()
        super().stop()


class GcsKiller:
    """Kills and restarts the GCS on an interval (GCS fault-tolerance
    churn; the reference exercises this via NotifyGCSRestart paths).

    Transient restart errors (port still in TIME_WAIT, slow exit) are
    counted in `respawn_failures` and logged; the killer keeps looping —
    a chaos run must not silently stop churning halfway through."""

    def __init__(self, cluster, interval_s: float = 2.0,
                 downtime_s: float = 0.5, max_kills: Optional[int] = None):
        self.cluster = cluster
        self.interval_s = interval_s
        self.downtime_s = downtime_s
        self.max_kills = max_kills
        self.kills = 0
        self.respawn_failures = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="chaos-gcs-killer")
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval_s):
            if self.max_kills is not None and self.kills >= self.max_kills:
                return
            try:
                self.cluster.kill_gcs()
                time.sleep(self.downtime_s)
            except Exception:
                logger.warning("GcsKiller: failed to kill GCS", exc_info=True)
                continue
            try:
                self.cluster.restart_gcs()
                self.kills += 1
            except Exception:
                self.respawn_failures += 1
                logger.warning(
                    "GcsKiller: GCS restart failed (%d failure(s) so far); "
                    "retrying next tick", self.respawn_failures,
                    exc_info=True)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)


class FailPoints:
    """Named in-process fail points for deterministic crash injection.

    Library code sprinkles `failpoint("name")` at interesting spots
    (e.g. "ckpt.persist" before the shard write, "ckpt.commit" between
    shard write and manifest commit). Tests arm a point with an
    exception (simulated crash) or a `threading.Event` gate (pause the
    code there until released). Unarmed points cost one dict lookup on
    an (almost always) empty dict.
    """

    def __init__(self):
        self._points = {}
        self._lock = threading.Lock()
        self.hits = {}

    def arm(self, name: str, *, exc: Optional[BaseException] = None,
            block: Optional[threading.Event] = None, after: int = 0):
        """Arm `name`. `exc` raises at the site; `block` makes the site
        wait until the event is set; `after=N` skips the first N hits
        (crash on the N+1-th pass)."""
        with self._lock:
            self._points[name] = {"exc": exc, "block": block,
                                  "after": int(after)}

    def disarm(self, name: str):
        with self._lock:
            self._points.pop(name, None)

    def clear(self):
        with self._lock:
            self._points.clear()
            self.hits.clear()

    def check(self, name: str):
        if not self._points:          # fast path: nothing armed anywhere
            return
        with self._lock:
            point = self._points.get(name)
            if point is None:
                return
            self.hits[name] = self.hits.get(name, 0) + 1
            if point["after"] > 0:
                point["after"] -= 1
                return
        if point["block"] is not None:
            point["block"].wait()
        if point["exc"] is not None:
            raise point["exc"]


FAIL_POINTS = FailPoints()


def failpoint(name: str):
    """Module-level fail-point check — the one-liner library code calls."""
    FAIL_POINTS.check(name)
