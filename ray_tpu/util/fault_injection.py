"""Fault-injection utilities for chaos testing.

Reference analog: python/ray/_private/test_utils.py:1512 ResourceKillerActor
and :1587 NodeKillerBase (actors that kill raylets/components on an
interval), and the chaos release harness (release/nightly_tests/
setup_chaos.py). Ours are plain threads driving a `Cluster`
(ray_tpu.cluster_utils) — the in-process multi-node utility — because the
killer must outlive the nodes it kills.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, List, Optional


class NodeKiller:
    """Periodically kills a random non-head worker node in the cluster.

    `respawn=True` adds a replacement node (same resources) after each kill,
    keeping cluster capacity roughly constant while churning node ids —
    the elastic-recovery scenario."""

    def __init__(self, cluster, interval_s: float = 1.0, *,
                 respawn: bool = True, seed: int = 0,
                 max_kills: Optional[int] = None,
                 node_filter: Optional[Callable] = None):
        self.cluster = cluster
        self.interval_s = interval_s
        self.respawn = respawn
        self.max_kills = max_kills
        self.node_filter = node_filter or (lambda node: True)
        self.kills: List[str] = []
        self._rng = random.Random(seed)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval_s):
            if self.max_kills is not None and len(self.kills) >= self.max_kills:
                return
            victims = [n for n in self.cluster.nodes
                       if n.proc.poll() is None and self.node_filter(n)]
            if not victims:
                continue
            node = self._rng.choice(victims)
            resources = dict(node.resources)
            try:
                self.cluster.remove_node(node, force=True)
            except Exception:
                continue
            self.kills.append(node.node_id.hex()[:12])
            if self.respawn:
                try:
                    num_cpus = resources.pop("CPU", 1.0)
                    num_tpus = resources.pop("TPU", 0.0)
                    self.cluster.add_node(num_cpus=num_cpus,
                                          num_tpus=num_tpus,
                                          resources=resources or None)
                except Exception:
                    pass

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)


class GcsKiller:
    """Kills and restarts the GCS on an interval (GCS fault-tolerance
    churn; the reference exercises this via NotifyGCSRestart paths)."""

    def __init__(self, cluster, interval_s: float = 2.0,
                 downtime_s: float = 0.5, max_kills: Optional[int] = None):
        self.cluster = cluster
        self.interval_s = interval_s
        self.downtime_s = downtime_s
        self.max_kills = max_kills
        self.kills = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval_s):
            if self.max_kills is not None and self.kills >= self.max_kills:
                return
            try:
                self.cluster.kill_gcs()
                time.sleep(self.downtime_s)
                self.cluster.restart_gcs()
                self.kills += 1
            except Exception:
                return

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
