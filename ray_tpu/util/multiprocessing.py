"""Drop-in ``multiprocessing.Pool`` replacement over ray_tpu actors.

Reference analog: python/ray/util/multiprocessing/pool.py (Pool with
map/starmap/apply + async variants, imap/imap_unordered, distributed over
actor processes instead of local fork).
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu
from ray_tpu.util.actor_pool import ActorPool


class _PoolWorker:
    def __init__(self, initializer=None, initargs=()):
        if initializer is not None:
            initializer(*initargs)

    def run_batch(self, fn, batch, star: bool):
        if star:
            return [fn(*item) for item in batch]
        return [fn(item) for item in batch]


class AsyncResult:
    def __init__(self, refs: List[Any]):
        self._refs = refs

    def get(self, timeout: Optional[float] = None) -> List[Any]:
        batches = ray_tpu.get(self._refs, timeout=timeout)
        return list(itertools.chain.from_iterable(batches))

    def wait(self, timeout: Optional[float] = None):
        ray_tpu.wait(self._refs, num_returns=len(self._refs), timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs), timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        try:
            self.get(timeout=0)
            return True
        except Exception:
            return False


class Pool:
    """Process pool over cluster actors; chunks work like multiprocessing."""

    def __init__(self, processes: Optional[int] = None, initializer=None,
                 initargs=(), ray_remote_args: Optional[dict] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        if processes is None:
            processes = max(int(ray_tpu.cluster_resources().get("CPU", 1)), 1)
        if processes < 1:
            raise ValueError("processes must be >= 1")
        self._processes = processes
        opts = dict(ray_remote_args or {})
        opts.setdefault("num_cpus", 1)
        cls = ray_tpu.remote(_PoolWorker)
        self._actors = [cls.options(**opts).remote(initializer, initargs)
                        for _ in range(processes)]
        self._closed = False
        self._next_apply = 0

    def _check(self):
        if self._closed:
            raise ValueError("Pool is closed")

    @staticmethod
    def _chunks(items: List[Any], chunksize: int) -> List[List[Any]]:
        return [items[i:i + chunksize] for i in range(0, len(items), chunksize)]

    def _default_chunksize(self, n: int) -> int:
        chunks_per_worker = 4
        return max(1, n // (self._processes * chunks_per_worker))

    def _run(self, fn: Callable, items: List[Any], chunksize: Optional[int],
             star: bool) -> AsyncResult:
        self._check()
        chunksize = chunksize or self._default_chunksize(len(items))
        refs = []
        for i, batch in enumerate(self._chunks(items, chunksize)):
            actor = self._actors[i % self._processes]
            refs.append(actor.run_batch.remote(fn, batch, star))
        return AsyncResult(refs)

    def apply(self, fn: Callable, args=(), kwds=None) -> Any:
        return self.apply_async(fn, args, kwds).get()[0]

    def apply_async(self, fn: Callable, args=(), kwds=None) -> AsyncResult:
        self._check()
        kwds = kwds or {}
        actor = self._actors[self._next_apply % self._processes]
        self._next_apply += 1
        wrapped = _bind(fn, kwds)
        return AsyncResult([actor.run_batch.remote(wrapped, [tuple(args)], True)])

    def map(self, fn: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return self._run(fn, list(iterable), chunksize, star=False).get()

    def map_async(self, fn, iterable, chunksize=None) -> AsyncResult:
        return self._run(fn, list(iterable), chunksize, star=False)

    def starmap(self, fn: Callable, iterable: Iterable,
                chunksize: Optional[int] = None) -> List[Any]:
        return self._run(fn, list(iterable), chunksize, star=True).get()

    def starmap_async(self, fn, iterable, chunksize=None) -> AsyncResult:
        return self._run(fn, list(iterable), chunksize, star=True)

    def imap(self, fn: Callable, iterable: Iterable, chunksize: int = 1):
        self._check()
        pool = ActorPool(self._actors)
        batches = self._chunks(list(iterable), chunksize)
        for out in pool.map(
                lambda a, b: a.run_batch.remote(fn, b, False), batches):
            yield from out

    def imap_unordered(self, fn: Callable, iterable: Iterable, chunksize: int = 1):
        self._check()
        pool = ActorPool(self._actors)
        batches = self._chunks(list(iterable), chunksize)
        for out in pool.map_unordered(
                lambda a, b: a.run_batch.remote(fn, b, False), batches):
            yield from out

    def close(self):
        self._closed = True

    def terminate(self):
        self.close()
        for a in self._actors:
            ray_tpu.kill(a)
        self._actors = []

    def join(self):
        if not self._closed:
            raise ValueError("Pool.join() before close()")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()


def _bind(fn, kwds):
    def wrapped(*args):
        return fn(*args, **kwds)
    return wrapped
