"""Process-local blocked-on registry: what is each thread waiting for?

Reference analog: Ray's core worker tracks the task it is blocked on so
`ray stack` can say "waiting on ObjectRef(...) owned by ..." instead of
printing a bare `fut.result()` frame (core_worker.cc task-state bookkeeping
+ python/ray/util/check_open_ports-style stack annotation). Here the
registry is deliberately tiny: blocking call sites wrap themselves in
`blocked_on(...)`, which records a {kind, detail, since} entry keyed by
thread ident in a plain dict under a lock. Two consumers read it:

  * `dump_stacks` (utils/debug.render_stacks) — annotates each rendered
    thread with its live blocked-on record, so a cluster-wide stack dump
    explains *why* a frame is parked, not just where;
  * the wait-edge reporter (core/worker.py task-event flush loop) — turns
    `object_get` / `collective_op` records into graph edges the GCS
    assembles into the cluster wait-graph for stall/deadlock detection.

Kinds (closed set, mirrors the detector's edge schema):
  * "object_get"    — blocked in get()/wait(); detail: oid (hex), owner
                      (node hex or addr), target_task / target_actor /
                      target_name when the object is a known task return
  * "collective_op" — blocked inside a collective op or Work.wait();
                      detail: group, rank, world_size, op_id
  * "channel_read"  — blocked on a compiled-DAG channel read; detail:
                      channel (hex), version

Everything is best-effort and allocation-light: registering is one dict
store, deregistering one pop. Never raises into the blocking path.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Dict, List, Optional

OBJECT_GET = "object_get"
COLLECTIVE_OP = "collective_op"
CHANNEL_READ = "channel_read"
KINDS = (OBJECT_GET, COLLECTIVE_OP, CHANNEL_READ)

_lock = threading.Lock()
# thread ident -> list of records (a stack: get() inside a collective
# callback etc. nests; the innermost record is the live one).
_blocked: Dict[int, List[dict]] = {}

# thread ident -> task context dict ({task_id, name, actor_id}) set by the
# worker executor so blocked-on records (and stack dumps) can attribute a
# thread to the task/actor it is running. Drivers have no entry.
_task_ctx: Dict[int, dict] = {}


def set_task_context(thread_ident: int, ctx: Optional[dict]) -> None:
    """Associate (or with ctx=None, clear) the task running on a thread."""
    with _lock:
        if ctx is None:
            _task_ctx.pop(thread_ident, None)
        else:
            _task_ctx[thread_ident] = ctx


def task_context(thread_ident: Optional[int] = None) -> Optional[dict]:
    ident = thread_ident if thread_ident is not None \
        else threading.get_ident()
    with _lock:
        ctx = _task_ctx.get(ident)
        return dict(ctx) if ctx else None


@contextlib.contextmanager
def blocked_on(kind: str, **detail: Any):
    """Mark the current thread blocked on `kind` for the `with` body.

    The record is visible to concurrent `snapshot()` / `current_edges()`
    callers the moment the body starts blocking. Exceptions propagate
    unchanged; the record is always removed.
    """
    ident = threading.get_ident()
    rec = {"kind": kind, "since": time.time(), "detail": detail}
    with _lock:
        _blocked.setdefault(ident, []).append(rec)
    try:
        yield rec
    finally:
        with _lock:
            stack = _blocked.get(ident)
            if stack is not None:
                try:
                    stack.remove(rec)
                except ValueError:
                    pass
                if not stack:
                    _blocked.pop(ident, None)


def snapshot() -> Dict[int, dict]:
    """thread ident -> innermost live blocked-on record (copies)."""
    with _lock:
        return {ident: dict(stack[-1])
                for ident, stack in _blocked.items() if stack}


def current_edges() -> List[dict]:
    """Flatten live records into wait-graph edges for the GCS.

    Each edge carries the waiter's task context (when known) so the
    detector can build task->task cycles, plus the raw detail so events
    can name object ids, owners, and collective groups.
    """
    edges = []
    with _lock:
        items = [(ident, dict(rec))
                 for ident, stack in _blocked.items()
                 for rec in stack]
        ctxs = {ident: dict(ctx) for ident, ctx in _task_ctx.items()}
    for ident, rec in items:
        edge = {
            "kind": rec["kind"],
            "since": rec["since"],
            "thread": ident,
        }
        edge.update(rec["detail"])
        ctx = ctxs.get(ident)
        if ctx:
            edge["waiter_task"] = ctx.get("task_id")
            edge["waiter_name"] = ctx.get("name")
            if ctx.get("actor_id"):
                edge["waiter_actor"] = ctx.get("actor_id")
        edges.append(edge)
    return edges
