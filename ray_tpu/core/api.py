"""Public API: init/shutdown/remote/get/put/wait/kill.

Reference analog: python/ray/_private/worker.py (init:1285, shutdown:1894,
get:2645, put:2813, wait:2878, remote:3266).
"""

from __future__ import annotations

import asyncio
import atexit
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from ray_tpu.core import worker as worker_mod
from ray_tpu.core.actor import ActorClass, get_actor  # noqa: F401
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.remote_function import RemoteFunction
from ray_tpu.core.worker import CoreWorker
from ray_tpu.runtime import node as node_mod
from ray_tpu.runtime import resources as resources_mod

_head: Optional[node_mod.NodeProcesses] = None


def init(address: Optional[str] = None, *, num_cpus: Optional[float] = None,
         num_tpus: Optional[float] = None,
         resources: Optional[Dict[str, float]] = None,
         object_store_memory: Optional[int] = None,
         labels: Optional[Dict[str, str]] = None,
         worker_env: Optional[Dict[str, str]] = None,
         runtime_env: Optional[dict] = None,
         include_dashboard: Optional[bool] = None,
         dashboard_port: int = 0,
         ignore_reinit_error: bool = False,
         remote_client: bool = False,
         _system_config: Optional[Dict[str, Any]] = None) -> "RuntimeContext":
    """Start a local cluster (default) or connect to an existing one
    (address="host:port" of its GCS, or the RAY_TPU_ADDRESS env var set by
    the job-submission entrypoint runner). `_system_config` overrides entries
    of the central config table (ray_tpu/config.py, the ray_config_def.h
    analog); worker processes inherit them via RAY_TPU_* env vars."""
    global _head
    if worker_mod.is_initialized():
        if ignore_reinit_error:
            return RuntimeContext()
        raise RuntimeError("ray_tpu.init() already called (use ignore_reinit_error)")
    from ray_tpu.config import cfg

    if _system_config:
        cfg().apply_overrides(_system_config)
        # Propagate to node/worker subprocesses.
        for k, v in _system_config.items():
            os.environ[f"RAY_TPU_{k.upper()}"] = str(v)
    if object_store_memory is None:
        object_store_memory = cfg().object_store_memory_default

    if address is None:
        address = os.environ.get("RAY_TPU_ADDRESS") or None
    if address == "auto":
        address = os.environ.get("RAY_TPU_ADDRESS") or None
        if address is None:
            raise RuntimeError(
                'init(address="auto") but RAY_TPU_ADDRESS is not set')
    if address is None:
        session_dir = node_mod.new_session_dir()
        processes = node_mod.NodeProcesses(session_dir)
        processes.gcs_proc, processes.gcs_address = node_mod.start_gcs(session_dir)
        # Workers must resolve by-reference pickles (module-level functions/
        # classes) against the driver's import paths (runtime_env working_dir
        # equivalent for the local-cluster case).
        import sys as _sys
        driver_path = ":".join(p for p in _sys.path if p)
        worker_env = dict(worker_env or {})
        worker_env.setdefault(
            "PYTHONPATH",
            driver_path + ":" + os.environ.get("PYTHONPATH", ""))
        res = resources_mod.node_resources(num_cpus, num_tpus, None, resources)
        node_labels = dict(resources_mod.tpu_slice_labels())
        node_labels.update(labels or {})
        processes.raylet_proc, info = node_mod.start_raylet(
            session_dir, processes.gcs_address, res, node_labels,
            object_store_memory, is_head=True, worker_env=worker_env)
        processes.node_id = bytes.fromhex(info["node_id"])
        processes.raylet_address = tuple(info["address"])
        processes.store_path = info["store_path"]
        _head = processes
        core = CoreWorker(
            mode="driver", gcs_address=processes.gcs_address,
            raylet_address=processes.raylet_address,
            store_path=processes.store_path, session_dir=session_dir,
            node_id=processes.node_id)
    else:
        host, port = address.rsplit(":", 1)
        gcs_address = (host, int(port))
        # Connect-only mode: pick the head (or first) node's raylet as local.
        import asyncio

        from ray_tpu.runtime import rpc as rpc_mod
        from ray_tpu.runtime.rpc import RpcClient

        # Resolve the auth token by the address being attached to (NOT
        # session_latest, which mis-resolves with two clusters on one host).
        rpc_mod.load_token_for_address(host, int(port))

        async def _discover():
            client = RpcClient(*gcs_address)
            await client.connect(timeout=30)
            nodes = await client.call("get_nodes")
            await client.close()
            return nodes

        loop = asyncio.new_event_loop()
        try:
            nodes = loop.run_until_complete(_discover())
        finally:
            loop.close()
        if not nodes:
            raise RuntimeError(f"no nodes registered at GCS {address}")
        head = next((n for n in nodes if n["is_head"]), nodes[0])
        # Ray-Client analog (util/client/): a remote driver attaches with NO
        # local store — put() streams into the head node's store over RPC,
        # get() pulls chunks back. Auto-detected when the store path isn't
        # visible (different machine), or forced with remote_client=True.
        store_path = head["object_store_path"]
        if remote_client or not os.path.exists(store_path):
            store_path = None
        core = CoreWorker(
            mode="driver", gcs_address=gcs_address,
            raylet_address=tuple(head["address"]),
            store_path=store_path,
            session_dir=os.path.dirname(head["object_store_path"]),
            node_id=head["node_id"])
    # An auto-started cluster (_head set above) dies with this driver: the
    # GCS tears everything down when the owning connection drops, so a
    # SIGKILLed driver can't leak GCS/raylet/worker processes. The token
    # makes registration idempotent under auto_reconnect retries; the
    # keepalive loop re-claims the job after transparent reconnects even
    # when the driver is otherwise idle (no other GCS traffic would redial).
    import uuid as _uuid

    owns_cluster = _head is not None
    job_token = _uuid.uuid4().hex
    core.job_id = core.io.run(core.gcs.call(
        "register_job", owns_cluster=owns_cluster, token=job_token))["job_id"]

    async def _reclaim_job(client):
        await client.call("claim_job", job_id=core.job_id,
                          owns_cluster=owns_cluster)

    core.gcs.on_reconnect = _reclaim_job

    async def _job_keepalive():
        from ray_tpu.config import cfg as _cfg

        while True:
            await asyncio.sleep(_cfg().job_keepalive_interval_s)
            try:
                await core.gcs.call("claim_job", job_id=core.job_id,
                                    owns_cluster=owns_cluster, timeout=10)
            except Exception:
                pass  # reconnect path retries on the next tick

    if owns_cluster:
        core._job_keepalive_task = core.io.spawn(_job_keepalive())
    if runtime_env:
        from ray_tpu.runtime_env import prepare_runtime_env

        core.job_runtime_env = prepare_runtime_env(core, dict(runtime_env))
    worker_mod.set_global_worker(core)
    if include_dashboard is None:
        include_dashboard = (os.environ.get("RAY_TPU_INCLUDE_DASHBOARD") == "1"
                             and _head is not None)
    if include_dashboard and _head is not None:
        try:
            _head.dashboard_proc, _head.dashboard_url = node_mod.start_dashboard(
                _head.session_dir, _head.gcs_address, port=dashboard_port)
            core.io.run(core.gcs.call(
                "kv_put", key=b"dashboard_url",
                value=_head.dashboard_url.encode()))
        except Exception as e:
            import logging

            logging.getLogger(__name__).warning("dashboard failed to start: %s", e)
    from ray_tpu.runtime.log_monitor import attach_driver_log_stream
    from ray_tpu.util import usage_stats

    attach_driver_log_stream(core)
    usage_stats.write_report(core.session_dir)
    atexit.register(_atexit_shutdown)
    return RuntimeContext()


def _atexit_shutdown():
    try:
        if worker_mod.is_initialized():
            shutdown()
    except Exception:
        pass


def shutdown():
    global _head
    if worker_mod.is_initialized():
        core = worker_mod.global_worker()
        core.shutdown(kill_cluster=_head is not None)
        worker_mod.set_global_worker(None)
    if _head is not None:
        if _head.dashboard_proc is not None:
            try:
                _head.dashboard_proc.kill()
            except Exception:
                pass
        for proc in (_head.raylet_proc, _head.gcs_proc):
            if proc is not None:
                try:
                    proc.wait(timeout=5)
                except Exception:
                    try:
                        proc.kill()
                    except Exception:
                        pass
        _head = None


def is_initialized() -> bool:
    return worker_mod.is_initialized()


def remote(*args, **kwargs):
    """@remote decorator for functions and classes, with or without options."""
    if len(args) == 1 and not kwargs and (callable(args[0]) or isinstance(args[0], type)):
        target = args[0]
        if isinstance(target, type):
            return ActorClass(target)
        return RemoteFunction(target)

    def decorator(target):
        if isinstance(target, type):
            allowed = {"num_cpus", "num_tpus", "resources", "max_restarts",
                       "max_task_retries", "max_concurrency", "name", "namespace",
                       "lifetime", "scheduling_strategy", "runtime_env"}
            opts = {k: v for k, v in kwargs.items() if k in allowed}
            return ActorClass(target, **opts)
        allowed = {"num_returns", "num_cpus", "num_tpus", "resources",
                   "max_retries", "scheduling_strategy", "runtime_env"}
        opts = {k: v for k, v in kwargs.items() if k in allowed}
        return RemoteFunction(target, **opts)

    return decorator


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None) -> Any:
    core = worker_mod.global_worker()
    if isinstance(refs, ObjectRef):
        return core.get_one(refs, timeout)
    return core.get(list(refs), timeout)


def put(value: Any) -> ObjectRef:
    return worker_mod.global_worker().put(value)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None
         ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
    return worker_mod.global_worker().wait(refs, num_returns, timeout)


def kill(actor_handle, *, no_restart: bool = True):
    worker_mod.global_worker().kill_actor(actor_handle._actor_id, no_restart)


def free(refs):
    """Eagerly delete the objects' data everywhere (ray.internal.free
    analog). The refs become unreadable; lineage is dropped too."""
    worker_mod.global_worker().free(refs)


def cancel(ref, *, force: bool = False, recursive: bool = False) -> bool:
    """Cancel the task producing `ref` (ray.cancel analog). Queued tasks
    fail immediately; running tasks get a best-effort interrupt
    (force=True kills the worker process). get() on the ref raises
    TaskCancelledError. Returns False if the task already finished."""
    return worker_mod.global_worker().cancel(ref, force=force,
                                             recursive=recursive)


class RuntimeContext:
    @property
    def gcs_address(self) -> Optional[str]:
        core = worker_mod.global_worker()
        return f"{core.gcs.host}:{core.gcs.port}"

    @property
    def node_id(self):
        return worker_mod.global_worker().node_id

    @property
    def session_dir(self):
        return worker_mod.global_worker().session_dir

    @property
    def current_actor_id(self):
        return worker_mod.global_worker().current_actor_id

    @property
    def dashboard_url(self):
        core = worker_mod.global_worker()
        reply = core.io.run(core.gcs.call("kv_get", key=b"dashboard_url"))
        blob = reply.get("value")
        return blob.decode() if blob else None


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext()


def nodes() -> List[dict]:
    core = worker_mod.global_worker()
    return core.io.run(core.gcs.call("get_nodes"))


def cluster_resources() -> Dict[str, float]:
    total: Dict[str, float] = {}
    for n in nodes():
        for k, v in n["resources"].items():
            total[k] = total.get(k, 0.0) + v
    return total


def available_resources() -> Dict[str, float]:
    total: Dict[str, float] = {}
    for n in nodes():
        for k, v in n["available"].items():
            total[k] = total.get(k, 0.0) + v
    return total
