"""Task and actor specifications passed over the wire.

Reference analog: src/ray/common/task/task_spec.h:257 TaskSpecification (ours
is a plain dataclass pickled by the RPC layer rather than a protobuf).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# An argument is ("v", payload_bytes) for pass-by-value or
# ("r", object_id_bytes) for a shared-memory store reference.
Arg = Tuple[str, bytes]


@dataclass
class TaskSpec:
    task_id: bytes
    fn_id: bytes              # key of pickled function in GCS KV
    name: str                 # human-readable, for errors/metrics
    args: List[Arg] = field(default_factory=list)
    kwarg_names: List[Optional[str]] = field(default_factory=list)  # parallel to args; None = positional
    num_returns: int = 1
    resources: Dict[str, float] = field(default_factory=dict)
    max_retries: int = 3
    # Actor fields (None for normal tasks)
    actor_id: Optional[bytes] = None
    method_name: Optional[str] = None
    seq_no: int = 0
    # Scheduling
    scheduling_strategy: Any = None
    placement_group_id: Optional[bytes] = None
    placement_group_bundle_index: int = -1
    runtime_env: Optional[dict] = None
    # Submitter-side bookkeeping: object ids pinned until this task
    # completes (args must survive the submit->execute window even if the
    # caller drops its refs; reference: task_manager.h holds arg refs).
    pinned_oids: Optional[List[bytes]] = None
    # Trace propagation: the caller's trace id and the span it submitted
    # from (util.tracing). The executing worker adopts these so its
    # execute span parents under the driver's submit span.
    trace_id: Optional[bytes] = None
    parent_span_id: Optional[bytes] = None

    def to_wire(self) -> bytes:
        """Encode the envelope as a wire.TaskSpecMsg (core_worker.proto:441
        PushTaskRequest analog): fields evolve per-number across versions
        instead of all-or-nothing pickled dataclasses."""
        from ray_tpu.runtime import wire

        return wire.TaskSpecMsg(
            task_id=self.task_id, fn_id=self.fn_id, name=self.name,
            payload=(self.args, self.kwarg_names,
                     self.scheduling_strategy, self.runtime_env,
                     self.pinned_oids),
            num_returns=self.num_returns, resources=self.resources,
            max_retries=self.max_retries, actor_id=self.actor_id or b"",
            method_name=self.method_name or "", seq_no=self.seq_no,
            placement_group_id=self.placement_group_id or b"",
            placement_group_bundle_index=self.placement_group_bundle_index,
            trace_id=self.trace_id or b"",
            parent_span_id=self.parent_span_id or b"",
            ).encode()

    @classmethod
    def from_wire(cls, data: bytes) -> "TaskSpec":
        from ray_tpu.runtime import wire

        m = wire.TaskSpecMsg.decode(data)
        p = m.payload
        if isinstance(p, tuple) and len(p) == 5:
            args, kwarg_names, strategy, runtime_env, pinned = p
        else:
            # First-cut writer: field 4 carried the args list alone and
            # the rest rode the retired 5/12/15/16 fields.
            args = p or []
            kwarg_names = m.kwarg_names_v1 or []
            strategy = m.scheduling_strategy_v1
            runtime_env = m.runtime_env_v1
            pinned = list(m.pinned_oids_v1) or None
        return cls(
            task_id=m.task_id, fn_id=m.fn_id, name=m.name,
            args=args or [], kwarg_names=kwarg_names or [],
            num_returns=m.num_returns, resources=m.resources,
            max_retries=m.max_retries, actor_id=m.actor_id or None,
            method_name=m.method_name or None, seq_no=m.seq_no,
            scheduling_strategy=strategy,
            placement_group_id=m.placement_group_id or None,
            placement_group_bundle_index=m.placement_group_bundle_index,
            runtime_env=runtime_env,
            pinned_oids=list(pinned) if pinned else None,
            trace_id=m.trace_id or None,
            parent_span_id=m.parent_span_id or None)


@dataclass
class ActorSpec:
    actor_id: bytes
    class_id: bytes           # key of pickled class in GCS KV
    name: Optional[str]       # named actor (GCS registry) or None
    class_name: str
    args: List[Arg] = field(default_factory=list)
    kwarg_names: List[Optional[str]] = field(default_factory=list)
    resources: Dict[str, float] = field(default_factory=dict)
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    scheduling_strategy: Any = None
    placement_group_id: Optional[bytes] = None
    placement_group_bundle_index: int = -1
    namespace: str = "default"
    runtime_env: Optional[dict] = None
