"""Task and actor specifications passed over the wire.

Reference analog: src/ray/common/task/task_spec.h:257 TaskSpecification (ours
is a plain dataclass pickled by the RPC layer rather than a protobuf).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# An argument is ("v", payload_bytes) for pass-by-value or
# ("r", object_id_bytes) for a shared-memory store reference.
Arg = Tuple[str, bytes]


@dataclass
class TaskSpec:
    task_id: bytes
    fn_id: bytes              # key of pickled function in GCS KV
    name: str                 # human-readable, for errors/metrics
    args: List[Arg] = field(default_factory=list)
    kwarg_names: List[Optional[str]] = field(default_factory=list)  # parallel to args; None = positional
    num_returns: int = 1
    resources: Dict[str, float] = field(default_factory=dict)
    max_retries: int = 3
    # Actor fields (None for normal tasks)
    actor_id: Optional[bytes] = None
    method_name: Optional[str] = None
    seq_no: int = 0
    # Scheduling
    scheduling_strategy: Any = None
    placement_group_id: Optional[bytes] = None
    placement_group_bundle_index: int = -1
    runtime_env: Optional[dict] = None
    # Submitter-side bookkeeping: object ids pinned until this task
    # completes (args must survive the submit->execute window even if the
    # caller drops its refs; reference: task_manager.h holds arg refs).
    pinned_oids: Optional[List[bytes]] = None


@dataclass
class ActorSpec:
    actor_id: bytes
    class_id: bytes           # key of pickled class in GCS KV
    name: Optional[str]       # named actor (GCS registry) or None
    class_name: str
    args: List[Arg] = field(default_factory=list)
    kwarg_names: List[Optional[str]] = field(default_factory=list)
    resources: Dict[str, float] = field(default_factory=dict)
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    scheduling_strategy: Any = None
    placement_group_id: Optional[bytes] = None
    placement_group_bundle_index: int = -1
    namespace: str = "default"
    runtime_env: Optional[dict] = None
