"""Streaming generator returns (num_returns="streaming").

Reference analog: python/ray/_raylet.pyx:289 ObjectRefGenerator and the
ReportGeneratorItemReturns RPC (src/ray/protobuf/core_worker.proto:462).
Ours: the executing worker pushes each yielded item back over the same
connection the task was pushed on (small values inline, large values sealed
to the executor's plasma store with only the location pushed); the final
reply carries the item count. The caller-side CoreWorker records each item
and wakes this iterator.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ray_tpu.core.object_ref import ObjectRef


class _GeneratorState:
    """Caller-side state for one streaming task; written by the IO loop
    (item pushes + completion reply), read by user threads via next()."""

    def __init__(self):
        self.cond = threading.Condition()
        self.items: Dict[int, ObjectRef] = {}   # index -> ref, not yet consumed
        self.next_read = 0
        self.total: Optional[int] = None        # set on completion
        self.error: Optional[BaseException] = None

    def push(self, index: int, ref: ObjectRef):
        with self.cond:
            self.items[index] = ref
            self.cond.notify_all()

    def finish(self, total: int):
        with self.cond:
            self.total = total
            self.cond.notify_all()

    def fail(self, error: BaseException, streamed: Optional[int] = None):
        """Deliver buffered items through `streamed` (if known), then raise."""
        with self.cond:
            self.error = error
            if streamed is not None:
                self.total = streamed
            self.cond.notify_all()

    def next_blocking(self, timeout: Optional[float]) -> ObjectRef:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self.cond:
            while True:
                ref = self.items.pop(self.next_read, None)
                if ref is not None:
                    self.next_read += 1
                    return ref
                if self.total is not None and self.next_read >= self.total:
                    if self.error is not None:
                        raise self.error
                    raise StopIteration
                if self.error is not None and not self.items:
                    # Error with unknown item count: buffered items drained.
                    raise self.error
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise TimeoutError("timed out waiting for generator item")
                self.cond.wait(remaining)


class ObjectRefGenerator:
    """Iterator over the ObjectRefs of a streaming task's yielded items.

    Each next() blocks until the executor reports the next item (possibly
    before the task finishes), then returns an ObjectRef whose value is
    already local (inline) or pullable (plasma on the executor's node).
    """

    def __init__(self, task_id: bytes, state: _GeneratorState):
        self._task_id = task_id
        self._state = state

    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        return self._state.next_blocking(None)

    def next(self, timeout: Optional[float] = None) -> ObjectRef:
        return self._state.next_blocking(timeout)

    def completed(self) -> bool:
        s = self._state
        with s.cond:
            return (s.total is not None or s.error is not None)

    def __repr__(self):
        return f"ObjectRefGenerator({self._task_id.hex()[:12]})"
