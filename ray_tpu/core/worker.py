"""The per-process core worker: connections, object access, task submission.

Reference analog: src/ray/core_worker/core_worker.h CoreWorker (Put
core_worker.cc:1522, Get :1823, SubmitTask via
transport/normal_task_submitter.cc:23 with per-SchedulingKey lease caching,
SubmitActorTask :2803 via actor_task_submitter.h:75) plus the in-process
memory store for inlined results (store_provider/memory_store/).

One instance per process (driver or worker), created by ray_tpu.init() /
worker bootstrap. Synchronous public methods; all I/O on a dedicated asyncio
thread (the instrumented_io_context analog).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import hashlib
import logging
import os
import threading
import time
from concurrent.futures import Future as SyncFuture
from typing import Any, Dict, List, Optional, Sequence, Tuple

import cloudpickle

from ray_tpu.core import serialization
from ray_tpu.runtime import metric_defs
from ray_tpu.core.exceptions import (
    ActorDiedError, GetTimeoutError, ObjectLostError, RayTpuError, TaskError,
    WorkerCrashedError, actor_death_error)
from ray_tpu.core.generator import ObjectRefGenerator, _GeneratorState
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.task_spec import ActorSpec, TaskSpec
from ray_tpu.runtime.object_store import ObjectNotFoundError, ObjectStore
from ray_tpu.runtime.object_store.spill import SpillManager
from ray_tpu.runtime.object_store.store import StoreFullError
from ray_tpu.runtime.rpc import (ConnectionLost, EventLoopThread, RpcClient,
                                 RpcError, RpcServer)
from ray_tpu.util import tracing
from ray_tpu.utils.ids import ObjectID, TaskID

logger = logging.getLogger(__name__)

from ray_tpu.config import cfg

_MISSING = object()


class _LeasedWorker:
    def __init__(self, lease_id, worker_id, address, node_id, raylet):
        self.lease_id = lease_id
        self.worker_id = worker_id
        self.address = address
        self.node_id = node_id
        self.raylet = raylet  # the raylet client that granted this lease
        self.client: Optional[RpcClient] = None
        self.busy = False
        self.return_timer: Optional[asyncio.TimerHandle] = None


class _KeyState:
    """Per-SchedulingKey submission state (normal_task_submitter.h:52)."""

    def __init__(self):
        self.queue: List[TaskSpec] = []
        self.leases: List[_LeasedWorker] = []
        self.inflight_reqs: set = set()  # outstanding lease request ids


class CoreWorker:
    def __init__(self, mode: str, gcs_address: Tuple[str, int],
                 raylet_address: Optional[Tuple[str, int]],
                 store_path: Optional[str], session_dir: str,
                 node_id: Optional[bytes] = None):
        self.mode = mode
        self.session_dir = session_dir
        self.node_id = node_id
        self.io = EventLoopThread()
        self.gcs = self.io.run(self._connect(gcs_address, auto_reconnect=True))
        # Lease-batch plumbing must exist before any raylet client is up:
        # a lease_grant push can arrive as soon as the socket connects.
        self._lease_grant_waiters: Dict[bytes, "asyncio.Future"] = {}
        self._lease_batch_buf: Dict[Any, list] = {}  # raylet client -> queue
        self.raylet = (self.io.run(self._connect(
            raylet_address, on_push=self._on_raylet_push))
            if raylet_address else None)
        self.store = ObjectStore(store_path, create=False) if store_path else None
        self.spill = (SpillManager(self.store, os.path.join(session_dir, "spill"))
                      if self.store is not None else None)
        self._node_addrs: Dict[bytes, Tuple[str, int]] = {}  # node_id -> raylet addr
        self.memory_store: Dict[bytes, Any] = {}      # oid -> deserialized value
        self._object_locations: Dict[bytes, bytes] = {}  # oid -> node_id (plasma results)
        self.result_futures: Dict[bytes, SyncFuture] = {}
        # Pending return oid -> {task_id, name, actor_id}: lets a blocked
        # get() name the task/actor it is waiting for (wait-graph edges,
        # `scripts stack` annotations). Popped with result_futures.
        self._result_meta: Dict[bytes, dict] = {}
        self._mem_lock = threading.Lock()
        self._registered_fns: set = set()
        self._keys: Dict[Tuple, _KeyState] = {}
        # Methods whose peers speak the typed wire schema (runtime/wire.py).
        # Optimistic: flip OFF per method on the first "no handler" from an
        # older peer and stay on the legacy pickled envelope (the rolling-
        # upgrade case the schema exists for).
        self._typed_methods = {"lease_worker", "lease_batch",
                               "cancel_lease_batch", "push_task",
                               "push_actor_task", "pull_object",
                               "put_object", "report_task_events"}
        self._raylet_clients: Dict[Tuple[str, int], RpcClient] = {}
        self._actor_clients: Dict[bytes, "_ActorClient"] = {}
        self._put_refs: set = set()                   # plasma ids this process created
        self._lineage: Dict[bytes, dict] = {}         # return oid -> lineage record
        self._generators: Dict[bytes, _GeneratorState] = {}  # task_id -> state
        # Cancellation (ray.cancel analog, task_manager.h MarkTaskCanceled):
        # cancelled ids suppress every retry/reconstruction path; in-flight
        # maps a dispatched task to the lease whose worker is running it.
        self._cancelled_tasks: set = set()
        self._inflight_tasks: Dict[bytes, "_LeasedWorker"] = {}
        # ---- ownership / distributed refcount (reference_count.h analog) --
        # Owner-side: oid -> {"locations": set[node_id], "borrowers": set[id],
        #   "containers": set[container_oid], "children": [(oid, addr)],
        #   "inline": bool}
        self._owned: Dict[bytes, dict] = {}
        self._local_refs: Dict[bytes, int] = {}       # live ObjectRef pyobjects
        self._borrowed: Dict[bytes, Tuple] = {}       # oid -> owner addr
        self._arg_pins: Dict[bytes, int] = {}         # oid -> in-flight task uses
        # GC-safe drop queue: ObjectRef.__del__ appends here (lock-free);
        # drained outside GC context (see ref_dropped).
        import collections

        self._dropped_refs: "collections.deque" = collections.deque()
        self._deferred_unborrow: set = set()
        self._pending_borrows: list = []              # in-flight borrow RPCs
        self._owner_clients: Dict[Tuple, RpcClient] = {}
        self._owner_locks: Dict[Tuple, "asyncio.Lock"] = {}
        self._death_sub_client: Optional[RpcClient] = None
        # node_id -> True/False: was the node's death an ANNOUNCED
        # drain/preemption? Filled lazily from the GCS node table on the
        # (rare) death paths that decide whether to consume retry budget.
        self._node_death_cause: Dict[bytes, bool] = {}
        self.worker_ident = (os.environ.get("RAY_TPU_WORKER_ID")
                             or "drv" + os.urandom(6).hex())
        # Every process (driver AND worker) serves the ownership protocol:
        # borrow/unborrow, containment pins, owner-side object fetch.
        self.core_server = RpcServer("127.0.0.1", 0)
        self.core_server.register("borrow", self._h_borrow)
        self.core_server.register("unborrow", self._h_unborrow)
        self.core_server.register("pin_container", self._h_pin_container)
        self.core_server.register("unpin_container", self._h_unpin_container)
        self.core_server.register("get_object", self._h_get_object)
        self.core_server.register("force_free", self._h_force_free)
        self.io.run(self.core_server.start())
        self.owner_addr = self.core_server.address
        self.current_actor_id: Optional[bytes] = None
        self.current_task_name: Optional[str] = None
        self.job_id = None
        self.job_runtime_env: Optional[dict] = None   # init(runtime_env=...)
        # Task-event + wait-edge reporter: started unconditionally so even
        # a process that never submits a task (e.g. a driver parked in
        # get()) reports what it is blocked on.
        with self._mem_lock:
            self._task_events: list = []
            self._task_events_flusher_started = True
            self._task_events_dropped = 0             # lifetime (summary)
            self._task_events_dropped_unreported = 0  # ships in next frame
        self._had_wait_edges = False
        self.io.spawn(self._flush_task_events_loop())

    @staticmethod
    async def _connect(addr, auto_reconnect: bool = False, on_push=None):
        client = RpcClient(addr[0], addr[1], auto_reconnect=auto_reconnect,
                           on_push=on_push)
        await client.connect(timeout=60)
        return client

    # ------------------------------------------------------------------ put/get

    def _require_store(self) -> ObjectStore:
        if self.store is None:
            raise RayTpuError(
                "this process is not colocated with a node object store "
                "(remote-attached driver); put/get of plasma objects is unavailable")
        return self.store

    def put(self, value: Any) -> ObjectRef:
        self._drain_dropped_refs()
        if isinstance(value, ObjectRef):
            raise TypeError("put() does not accept ObjectRefs")
        oid = ObjectID.generate().binary()
        segments, total, contained = serialization.serialize_with_refs(value)
        if self.store is not None:
            self._write_segments_to_plasma(oid, segments, total)
        else:
            # Remote-client driver (Ray Client analog): no colocated store —
            # materialize into the attached node's store over chunked RPC.
            self._remote_put(oid, serialization.join_segments(segments))
        self._put_refs.add(oid)
        children = self._pin_children(oid, contained)
        self._new_owned(oid, location=self.node_id, children=children)
        ref = ObjectRef(oid, owner=self.node_id, owner_addr=self.owner_addr)
        self.register_ref(ref)
        return ref

    def _remote_put(self, oid: bytes, payload: bytes):
        if self.raylet is None:
            raise RayTpuError("no attached raylet for remote put")
        chunk_size = cfg().pull_chunk_bytes

        async def _send_raw():
            # Zero-pickle: each chunk ships as the raw-frame payload (a
            # memoryview slice straight onto the socket), only the small
            # ObjPutMsg header is encoded.
            from ray_tpu.runtime import wire

            total = len(payload)
            view = memoryview(payload)
            off = 0
            while True:
                end = min(off + chunk_size, total)
                m, _ = await self.raylet.call_raw(
                    "put_object_raw",
                    m=wire.ObjPutMsg(oid=oid, offset=off, total=total,
                                     seal=(end >= total)).encode(),
                    payload=view[off:end])
                ack = wire.AckMsg.decode(m)
                if not ack.ok:
                    raise RayTpuError(f"remote put failed: {ack.error}")
                off = end
                if off >= total:
                    return

        async def _send():
            if "put_object" in self._typed_methods:
                try:
                    return await _send_raw()
                except RpcError as e:
                    if (isinstance(e, ConnectionLost)
                            or "no handler" not in str(e)):
                        raise
                    self._typed_methods.discard("put_object")
            total = len(payload)
            off = 0
            while True:
                end = min(off + chunk_size, total)
                r = await self.raylet.call(
                    "put_object", oid=oid, chunk=payload[off:end], offset=off,
                    total=total, seal=(end >= total))
                if not r.get("ok"):
                    raise RayTpuError(f"remote put failed: {r.get('error')}")
                off = end
                if off >= total:
                    return

        self.io.run(_send(), timeout=600)
        self._object_locations[oid] = self.node_id

    def spill_create(self, oid: bytes, size: int, metadata: bytes = b"") -> memoryview:
        """store.create with spill-before-evict when a spill dir is available."""
        if self.spill is not None:
            return self.spill.create_with_spill(oid, size, metadata)
        return self._require_store().create(oid, size, metadata)

    def _write_segments_to_plasma(self, oid: bytes, segments, total: int):
        store = self._require_store()
        buf = self.spill_create(oid, total)
        try:
            serialization.write_segments(buf, segments)
        except BaseException:
            buf.release()
            store.abort(oid)
            raise
        buf.release()
        store.seal(oid)

    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float] = None) -> List[Any]:
        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        for ref in refs:
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            out.append(self.get_one(ref, remaining))
        return out

    def get_one(self, ref: ObjectRef, timeout: Optional[float]) -> Any:
        self._drain_dropped_refs()
        oid = ref.binary()
        with self._mem_lock:
            if oid in self.memory_store:
                return self._raise_if_error(self.memory_store[oid])
            fut = self.result_futures.get(oid)
        # Everything below may block: register what we are blocked on so
        # stack dumps and the cluster wait-graph can explain the stall.
        with self._blocked_get_ctx(oid, ref):
            if fut is not None:
                try:
                    fut.result(timeout)
                # On 3.10 concurrent.futures.TimeoutError is NOT the builtin
                # TimeoutError (they merge in 3.11) — catch both.
                except (TimeoutError, concurrent.futures.TimeoutError):
                    raise GetTimeoutError(f"get() timed out waiting for {ref}")
                with self._mem_lock:
                    if oid in self.memory_store:
                        return self._raise_if_error(self.memory_store[oid])
                # fell through: result is in plasma
            start = time.monotonic()
            # A BORROWED ref (we never submitted the producing task and
            # another process owns it) may resolve ONLY at its owner: an
            # inline result never lands in plasma, even on this node. Probe
            # plasma briefly, then spend the budget on the owner fetch —
            # otherwise a same-node borrow waits the full timeout for a
            # local appearance that can never happen.
            borrowed = (fut is None and ref.owner_addr is not None
                        and tuple(ref.owner_addr) != tuple(self.owner_addr))
            plasma_timeout = timeout
            if borrowed:
                plasma_timeout = 0.05 if timeout is None else min(timeout, 0.05)
            try:
                value = self._get_plasma_value(oid, ref.owner, plasma_timeout)
            except ObjectNotFoundError:
                # The plasma wait may have consumed the whole budget: the
                # owner fallback only gets what remains (never doubles the
                # timeout).
                remaining = (None if timeout is None else
                             timeout - (time.monotonic() - start))
                if remaining is not None and remaining <= 0:
                    raise GetTimeoutError(f"get() timed out waiting for {ref}")
                value = self._fetch_from_owner(ref, remaining)
            except ObjectLostError:
                # Drain relocation first: a draining node migrates its
                # primary copies to live peers and records the new homes in
                # the GCS relocation table — a moved object is readable
                # WITHOUT lineage re-execution.
                value = self._get_relocated_value(oid, timeout)
                if value is not _MISSING:
                    return self._raise_if_error(value)
                # Lineage reconstruction: re-execute the producing task, then
                # re-enter the full read path (the new result may be inline).
                # An announced preemption does not consume the
                # reconstruction budget (the loss was planned, not a bug).
                preempted = self._node_was_preempted(
                    self._object_locations.get(oid))
                if not self._reconstruct(oid, timeout, preempted=preempted):
                    raise
                return self.get_one(ref, timeout)
        return self._raise_if_error(value)

    def _get_relocated_value(self, oid: bytes, timeout: Optional[float]):
        """Ask the GCS where a drain migration put `oid`; on a hit, retry
        the plasma read from the new home. Returns _MISSING when there is
        no (new) relocation or the read fails anyway."""
        try:
            reply = self.io.run(
                self.gcs.call("locate_object", oid=oid), timeout=10)
        except Exception:
            return _MISSING
        if not reply or not reply.get("found"):
            return _MISSING
        node_id = reply["node_id"]
        if node_id == self._object_locations.get(oid):
            return _MISSING  # that's where we just failed to read from
        self._object_locations[oid] = node_id
        addr = reply.get("address")
        if addr:
            self._node_addrs[node_id] = tuple(addr)
        try:
            return self._get_plasma_value(oid, node_id, timeout)
        except (ObjectNotFoundError, ObjectLostError):
            return _MISSING

    def _preemption_verdict(self, node_id: bytes, nodes) -> bool:
        """Classify `node_id` against a GCS node-table snapshot; caches
        only FINAL (dead-node) verdicts — a live, non-draining node may
        still receive a drain notice later."""
        from ray_tpu.core.exceptions import CAUSE_PREEMPTION, death_cause

        verdict = False
        for n in nodes:
            nid = n["node_id"]
            if isinstance(nid, str):
                nid = bytes.fromhex(nid)
            if nid != node_id:
                continue
            verdict = bool(n.get("draining")) or death_cause(
                n.get("death_reason")) == CAUSE_PREEMPTION
            if not n.get("alive", True):
                self._node_death_cause[node_id] = verdict
            break
        return verdict

    def _node_was_preempted(self, node_id: Optional[bytes]) -> bool:
        """True when `node_id` died (or is dying) from an ANNOUNCED
        drain/preemption — such deaths never consume retry budgets
        (max_retries / reconstruction_attempts). Lazily resolved from the
        GCS node table; only called on (rare) death paths. Sync — must not
        be called from the IO loop (use _node_was_preempted_async there)."""
        if node_id is None:
            return False
        cached = self._node_death_cause.get(node_id)
        if cached is not None:
            return cached
        try:
            nodes = self.io.run(
                self.gcs.call("get_nodes", only_alive=False), timeout=10)
        except Exception:
            return False
        return self._preemption_verdict(node_id, nodes)

    async def _node_was_preempted_async(self, node_id: Optional[bytes]) -> bool:
        """IO-loop twin of _node_was_preempted."""
        if node_id is None:
            return False
        cached = self._node_death_cause.get(node_id)
        if cached is not None:
            return cached
        try:
            nodes = await self.gcs.call("get_nodes", only_alive=False,
                                        timeout=10)
        except Exception:
            return False
        return self._preemption_verdict(node_id, nodes)

    def _blocked_get_ctx(self, oid: bytes, ref: ObjectRef, **extra):
        """blocked_on("object_get") context for a (possibly) blocking read
        of `ref`, annotated with everything this process knows about the
        object: its owner and — when we submitted the producing task
        ourselves — the target task/actor (the wait-graph edge)."""
        from ray_tpu.core import blocked as blocked_mod

        detail = {"oid": oid.hex()}
        owner = ref.owner_addr or ref.owner
        if owner is not None:
            detail["owner"] = (owner.hex()
                               if isinstance(owner, (bytes, bytearray))
                               else f"{owner[0]}:{owner[1]}")
        meta = self._result_meta.get(oid)
        if meta:
            detail["target_task"] = meta.get("task_id")
            detail["target_name"] = meta.get("name")
            if meta.get("actor_id"):
                detail["target_actor"] = meta["actor_id"]
        detail.update(extra)
        return blocked_mod.blocked_on(blocked_mod.OBJECT_GET, **detail)


    def _get_plasma_value(self, oid: bytes, owner: Optional[bytes],
                          timeout: Optional[float]) -> Any:
        """Plasma read path: local shm store -> local spill dir -> remote pull
        from the object's location (ObjectManager pull protocol analog,
        object_manager.proto:60; ours is chunked raylet RPC over the control
        plane since tensors ride XLA collectives, not the object plane)."""
        location = self._object_locations.get(oid) or owner
        remote = (location is not None and self.node_id is not None
                  and location != self.node_id)
        store = self.store
        if store is not None:
            # With a remote fallback available, don't burn the whole timeout
            # waiting for a local appearance that will never happen.
            local_timeout = 0.05 if remote else timeout
            try:
                buf = store.get(oid, timeout=local_timeout)
                # `pin=buf` keeps the store read reference alive for as long
                # as any zero-copy array deserialized out of this payload is.
                return serialization.deserialize(buf.data, pin=buf)
            except ObjectNotFoundError:
                pass
            if self.spill is not None and self.spill.restore(oid):
                buf = store.get(oid, timeout=5)
                return serialization.deserialize(buf.data, pin=buf)
        if (remote or store is None) and location is not None:
            data = self._pull_remote(oid, location)
            if store is not None:
                # Cache locally so repeated gets are zero-copy shm reads.
                try:
                    view = self.spill_create(oid, len(data))
                    view[:] = data
                    view.release()
                    store.seal(oid)
                    buf = store.get(oid, timeout=5)
                    return serialization.deserialize(buf.data, pin=buf)
                except (ValueError, StoreFullError, ObjectNotFoundError):
                    pass  # concurrent create/restore or no room: use the copy
            return serialization.deserialize(memoryview(data))
        raise ObjectNotFoundError(oid.hex())

    def _fetch_from_owner(self, ref: ObjectRef, timeout: Optional[float]):
        """Last-resort read path: ask the object's OWNER process (nested refs
        whose value lives only in the owner's memory store, or whose plasma
        location we never learned). GetObjectStatus analog
        (core_worker.proto: the owner resolves inlined values/locations)."""
        addr = ref.owner_addr
        oid = ref.binary()
        if addr is None or tuple(addr) == tuple(self.owner_addr):
            raise GetTimeoutError(f"get() timed out waiting for {ref}")
        budget = 30.0 if timeout is None else max(0.1, min(timeout, 30.0))

        async def _ask():
            try:
                return await asyncio.wait_for(
                    self._owner_call(tuple(addr), "get_object", oid=oid),
                    budget)
            except asyncio.TimeoutError:
                return None

        reply = self.io.run(_ask(), timeout=budget + 5)
        if not reply or not reply.get("found"):
            raise GetTimeoutError(f"get() timed out waiting for {ref}")
        if "payload" in reply:
            return serialization.deserialize(memoryview(reply["payload"]))
        location = reply.get("location")
        if location is not None:
            self._object_locations[oid] = location
            return self._get_plasma_value(oid, location, timeout)
        raise GetTimeoutError(f"get() timed out waiting for {ref}")

    def _node_address(self, node_id: bytes) -> Optional[Tuple[str, int]]:
        addr = self._node_addrs.get(node_id)
        if addr is not None:
            return addr
        for n in self.io.run(self.gcs.call("get_nodes")):
            nid = n["node_id"]
            if isinstance(nid, str):
                nid = bytes.fromhex(nid)
            self._node_addrs[nid] = tuple(n["address"])
        return self._node_addrs.get(node_id)

    def _pull_remote(self, oid: bytes, node_id: bytes) -> bytes:
        """Chunked pull of a sealed object from another node's raylet:
        raw-frame fast path (zero-pickle — chunk bytes come off the socket
        as views over the receive buffer and land in ONE preallocated
        bytearray, no intermediate pickle buffer ever materializes),
        legacy pickled chunks against an old raylet."""
        pull_start = time.monotonic()
        addr = self._node_address(node_id)
        if addr is None:
            raise ObjectLostError(
                f"object {oid.hex()[:12]} lives on unknown/dead node "
                f"{node_id.hex()[:12]}", oid=oid)

        async def _pull_raw(client):
            from ray_tpu.runtime import wire

            buf, off, total = None, 0, 0
            while True:
                m, payload = await client.call_raw(
                    "pull_object_raw",
                    m=wire.ObjChunkRequestMsg(
                        oid=oid, offset=off,
                        length=cfg().pull_chunk_bytes).encode())
                rep = wire.ObjChunkReplyMsg.decode(m)
                if not rep.found:
                    raise ObjectLostError(
                        f"object {oid.hex()[:12]} not found on node "
                        f"{node_id.hex()[:12]} (evicted or node restarted)",
                        oid=oid)
                if buf is None:
                    total = rep.total
                    buf = bytearray(total)
                n = len(payload)
                buf[off:off + n] = payload
                off += n
                if off >= total:
                    return buf
                if n == 0:
                    raise ObjectLostError(
                        f"truncated pull of {oid.hex()[:12]}", oid=oid)

        async def _pull():
            client = await self._raylet_for(addr)
            if "pull_object" in self._typed_methods:
                try:
                    return await _pull_raw(client)
                except RpcError as e:
                    if (isinstance(e, ConnectionLost)
                            or "no handler" not in str(e)):
                        raise
                    self._typed_methods.discard("pull_object")
            chunks, off = [], 0
            while True:
                reply = await client.call(
                    "pull_object", oid=oid, offset=off,
                    length=cfg().pull_chunk_bytes)
                if not reply.get("found"):
                    raise ObjectLostError(
                        f"object {oid.hex()[:12]} not found on node "
                        f"{node_id.hex()[:12]} (evicted or node restarted)",
                        oid=oid)
                chunk = reply["chunk"]
                chunks.append(chunk)
                off += len(chunk)
                if off >= reply["total"]:
                    return b"".join(chunks)
                if not chunk:
                    raise ObjectLostError(
                        f"truncated pull of {oid.hex()[:12]}", oid=oid)

        try:
            data = self.io.run(_pull())
        except (ConnectionLost, OSError):
            raise ObjectLostError(
                f"node {node_id.hex()[:12]} unreachable while pulling "
                f"{oid.hex()[:12]}", oid=oid)
        metric_defs.PULL_LATENCY.observe(time.monotonic() - pull_start)
        return data

    @staticmethod
    def _raise_if_error(value):
        if isinstance(value, RayTpuError):
            raise value
        return value

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        self._drain_dropped_refs()
        assert num_returns <= len(refs)
        deadline = None if timeout is None else time.monotonic() + timeout
        pending = list(refs)
        ready: List[ObjectRef] = []
        while len(ready) < num_returns:
            still, futs = [], []
            for ref in pending:
                oid = ref.binary()
                with self._mem_lock:
                    # A completed task pops its result future, so a plasma
                    # result's only completion evidence is its recorded
                    # location — without this check wait() never reports a
                    # remote plasma result ready even though get() works.
                    in_mem = (oid in self.memory_store
                              or oid in self._object_locations)
                    fut = self.result_futures.get(oid)
                if in_mem or (fut is not None and fut.done()) or \
                        (self.store is not None and self.store.contains(oid)):
                    ready.append(ref)
                else:
                    still.append(ref)
                    if fut is not None:
                        futs.append(fut)
            pending = still
            if len(ready) >= num_returns:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            if len(futs) == len(pending):
                # Every pending ref has a local result future: block until
                # ANY completes (event-driven, no busy-poll).
                block = remaining if remaining is not None else 60.0
            else:
                # Some refs can only appear by being sealed into plasma by
                # another process (no completion signal): re-check coarsely.
                block = 0.02 if remaining is None else min(0.02, remaining)
            first = pending[0]
            with self._blocked_get_ctx(first.binary(), first,
                                       num_pending=len(pending)):
                if futs:
                    concurrent.futures.wait(
                        futs, timeout=block,
                        return_when=concurrent.futures.FIRST_COMPLETED)
                else:
                    time.sleep(block)
        return ready, pending

    # ------------------------------------------------------------- functions

    def register_function(self, fn) -> bytes:
        pickled = cloudpickle.dumps(fn)
        fn_id = hashlib.sha1(pickled).digest()
        if fn_id not in self._registered_fns:
            self.io.run(self.gcs.call("kv_put", key=b"fn:" + fn_id, value=pickled,
                                      overwrite=False))
            self._registered_fns.add(fn_id)
        return fn_id

    def register_class(self, cls) -> bytes:
        pickled = cloudpickle.dumps(cls)
        class_id = hashlib.sha1(pickled).digest()
        if class_id not in self._registered_fns:
            self.io.run(self.gcs.call("kv_put", key=b"cls:" + class_id, value=pickled,
                                      overwrite=False))
            self._registered_fns.add(class_id)
        return class_id

    # ------------------------------------------------------------ serialization

    def serialize_args(self, args, kwargs) -> Tuple[List, List, List]:
        """Build TaskSpec args: small values inline; ObjectRefs stay refs;
        large values spill to plasma (DependencyResolver analog). Also
        returns the oids to pin for the task's lifetime (ref args + refs
        nested inside inline values)."""
        out, names, pins = [], [], []
        for name, value in [(None, a) for a in args] + list(kwargs.items()):
            if isinstance(value, ObjectRef):
                oid = value.binary()
                # Prefer the tracked result location over the ref's recorded
                # owner: task returns live on the node that executed the task.
                owner = self._object_locations.get(oid) or value.owner or self.node_id
                out.append(("r", oid, owner))
                pins.append(oid)
            else:
                segments, total, contained = serialization.serialize_with_refs(
                    value)
                pins.extend(r.binary() for r in contained)
                if total > cfg().inline_result_max and self.store is not None:
                    oid = ObjectID.generate().binary()
                    self._write_segments_to_plasma(oid, segments, total)
                    self._put_refs.add(oid)
                    self._new_owned(oid, location=self.node_id)
                    out.append(("r", oid, self.node_id))
                else:
                    out.append(("v", serialization.join_segments(segments)))
            names.append(name)
        return out, names, pins

    def resolve_args(self, spec: TaskSpec) -> Tuple[list, dict]:
        """Worker-side: materialize TaskSpec args."""
        args, kwargs = [], {}
        for arg, name in zip(spec.args, spec.kwarg_names):
            kind, payload = arg[0], arg[1]
            if kind == "v":
                value = serialization.deserialize(payload)
            else:
                owner = arg[2] if len(arg) > 2 else None
                value = self._get_plasma_value(payload, owner, timeout=60)
            if name is None:
                args.append(value)
            else:
                kwargs[name] = value
        return args, kwargs

    # ------------------------------------------------------- streaming items

    async def _on_worker_push(self, method: str, data: dict):
        """Pushes from executor workers back to this (submitting) process.
        Currently: streaming-generator item reports (the
        ReportGeneratorItemReturns analog, core_worker.proto:462)."""
        if method != "gen_item":
            logger.warning("unexpected worker push %r", method)
            return
        task_id = data["task_id"]
        index = data["index"]
        oid = ObjectID.for_task_return(TaskID(task_id), index).binary()
        node_id = data.get("node_id")
        if "payload" in data:
            # Deserialize outside the lock (nested refs re-enter it); also
            # destroy any displaced value outside it (see _maybe_free).
            value = serialization.deserialize(data["payload"])
            with self._mem_lock:
                displaced = self.memory_store.pop(oid, None)
                self.memory_store[oid] = value
            del displaced
        elif node_id is not None:
            self._object_locations[oid] = node_id
        gen = self._generators.get(task_id)
        if gen is not None:
            gen.push(index, ObjectRef(oid, owner=node_id))

    def _make_generator(self, task_id: bytes) -> ObjectRefGenerator:
        state = _GeneratorState()
        self._generators[task_id] = state
        return ObjectRefGenerator(task_id, state)

    async def _on_raylet_push(self, method: str, data: dict):
        """Pushes from raylets: deferred lease-batch resolutions. A
        `lease_grant` carries the encoded LeaseReplyMsg for a req_id whose
        batch entry came back pending=True (see handle_lease_batch2)."""
        if method != "lease_grant":
            logger.warning("unexpected raylet push %r", method)
            return
        fut = self._lease_grant_waiters.pop(data.get("req_id"), None)
        if fut is not None and not fut.done():
            from ray_tpu.runtime import wire

            fut.set_result(wire.LeaseReplyMsg.decode(data["m"]).to_reply())

    # ------------------------------------------------------- task events

    def _record_task_event(self, spec: TaskSpec, state: str,
                           error: Optional[str] = None):
        """Buffer a task state transition; batches flush to the GCS
        (task_event_buffer.h:224 -> GcsTaskManager analog). Best-effort —
        observability must never block or fail the hot path."""
        with self._mem_lock:
            buf = getattr(self, "_task_events", None)
            if buf is None:
                buf = self._task_events = []
                self._task_events_flusher_started = False
            buf.append({
                "task_id": spec.task_id.hex(),
                "name": spec.name,
                "state": state,
                "actor_id": spec.actor_id.hex() if spec.actor_id else None,
                "worker": self.worker_ident,  # timeline lane key
                "time": time.time(),
                "error": error,
            })
            # Bounded buffer: observability never OOMs the submitter. Drops
            # are COUNTED, not silent — the count ships with the next flush
            # frame, feeds ray_tpu_task_events_dropped_total, and surfaces
            # in state.summary().
            overflow = len(buf) - cfg().task_events_max
            if overflow > 0:
                del buf[:overflow]
                self._task_events_dropped = getattr(
                    self, "_task_events_dropped", 0) + overflow
                self._task_events_dropped_unreported = getattr(
                    self, "_task_events_dropped_unreported", 0) + overflow
                metric_defs.TASK_EVENTS_DROPPED.inc(overflow)
            start = not self._task_events_flusher_started
            self._task_events_flusher_started = True
        if start:
            self.io.spawn(self._flush_task_events_loop())

    def _collect_wait_edges(self) -> list:
        """Snapshot this process's blocked-on records as wait-graph edges,
        each with a short captured stack so detector events can show WHERE
        the waiter is parked, not just what it waits for."""
        import sys as _sys
        import traceback as _tb

        from ray_tpu.core import blocked as blocked_mod

        try:
            edges = blocked_mod.current_edges()
        except Exception:
            return []
        if not edges:
            return []
        # The frames snapshot must not outlive this call: the dict contains
        # our own frame (a cycle only the generational GC would break), and
        # any frame whose function returns meanwhile stays alive with its
        # locals — a pinned channel buffer held that way wedges the ring's
        # writer. clear() breaks the cycle and drops dead frames now.
        frames = _sys._current_frames()
        try:
            for e in edges:
                f = frames.get(e.get("thread"))
                if f is not None:
                    try:
                        e["stack"] = [ln.rstrip("\n") for ln in
                                      _tb.format_stack(f, limit=4)]
                    except Exception:
                        pass
                    f = None
                e.pop("thread", None)
                if self.node_id is not None:
                    e["node_id"] = self.node_id.hex()
                if self.current_actor_id and "waiter_actor" not in e:
                    e["waiter_actor"] = self.current_actor_id.hex()
        finally:
            frames.clear()
        return edges

    async def _flush_task_events_loop(self):
        while True:
            await asyncio.sleep(cfg().task_events_flush_interval_s)
            self._drain_dropped_refs()   # idle-driver drop processing
            # Piggyback wait-graph edges on the same flush tick/frame: an
            # edge list (possibly empty, to clear a previous report) rides
            # the FIRST report of the tick.
            edges = self._collect_wait_edges()
            send_edges = (edges if (edges or self._had_wait_edges)
                          else None)
            self._had_wait_edges = bool(edges)
            first = True
            while True:
                batch_max = cfg().event_flush_batch_max
                with self._mem_lock:
                    buf = getattr(self, "_task_events", None)
                    batch = buf[:batch_max] if buf else []
                    if batch:
                        del buf[:batch_max]  # in-place: appends race-free
                    dropped = getattr(self,
                                      "_task_events_dropped_unreported", 0)
                    self._task_events_dropped_unreported = 0
                if not batch and not (first and (send_edges is not None
                                                 or dropped)):
                    break
                try:
                    await self._report_task_events(
                        batch, send_edges if first else None, dropped)
                except Exception:
                    # GCS down: drop the events quietly (status quo) but
                    # keep the drop COUNT for the next successful frame.
                    with self._mem_lock:
                        self._task_events_dropped_unreported += dropped
                    break
                first = False

    async def _report_task_events(self, batch, send_edges, dropped):
        """One flush frame: a typed TaskEventBatchMsg (one encode per tick
        instead of N dict-pickles) carrying events + wait edges + the drop
        count; legacy pickled envelope against an old GCS."""
        from ray_tpu.runtime import wire

        if "report_task_events" in self._typed_methods:
            msg = wire.TaskEventBatchMsg(
                events=[wire.TaskEventMsg.from_event(e) for e in batch],
                reporter=self.worker_ident,
                node_id=self.node_id or b"",
                dropped=dropped)
            if send_edges is not None:
                msg.has_wait_edges = True
                msg.wait_edges = send_edges
            try:
                await self.gcs.call("report_task_events2", m=msg.encode())
                return
            except RpcError as e:
                if (isinstance(e, ConnectionLost)
                        or "no handler" not in str(e)):
                    raise
                self._typed_methods.discard("report_task_events")
        await self.gcs.call(
            "report_task_events", events=batch, wait_edges=send_edges,
            reporter=self.worker_ident, node_id=self.node_id)

    # --------------------------------------------- ownership & refcounting
    #
    # Reference analog: src/ray/core_worker/reference_count.h:418-615. The
    # process that creates an object (put / task submission) OWNS it: it
    # tracks where copies live, which processes borrow it, and which stored
    # objects contain it. Data is freed everywhere on zero (delete-on-zero);
    # pins keep in-flight task args alive across the submit/execute window.

    def _new_owned(self, oid: bytes, location: Optional[bytes] = None,
                   inline: bool = False, children=None) -> dict:
        rec = self._owned.get(oid)
        if rec is None:
            rec = self._owned[oid] = {
                "locations": set(), "borrowers": set(), "containers": set(),
                "children": [], "inline": inline}
        if location is not None:
            rec["locations"].add(location)
        if children:
            rec["children"].extend(children)
        metric_defs.OBJECTS_OWNED.set(len(self._owned))
        return rec

    def register_ref(self, ref: ObjectRef, arrived: bool = False):
        """Count a live ObjectRef pyobject; on first arrival from another
        process, register this process as a borrower with the owner. The
        borrow RPC is async; executors drain pending borrows BEFORE replying
        to a task (take_pending_borrows), closing the window where the
        submitter unpins args while our borrow is still in flight."""
        self._drain_dropped_refs()
        oid = ref.binary()
        ref._registered = True
        with self._mem_lock:
            self._local_refs[oid] = self._local_refs.get(oid, 0) + 1
            needs_borrow = (arrived and oid not in self._owned
                            and oid not in self._borrowed
                            and ref.owner_addr is not None
                            and tuple(ref.owner_addr) != tuple(self.owner_addr))
            if needs_borrow:
                self._borrowed[oid] = tuple(ref.owner_addr)
        if needs_borrow:
            fut = self.io.spawn(self._owner_call(
                tuple(ref.owner_addr), "borrow", oid=oid,
                borrower=self.worker_ident))
            with self._mem_lock:
                # Prune completed futures so drivers (which never drain via
                # take_pending_borrows) don't leak one entry per borrow.
                self._pending_borrows = [
                    f for f in self._pending_borrows if not f.done()]
                self._pending_borrows.append(fut)

    def take_pending_borrows(self) -> list:
        with self._mem_lock:
            futs, self._pending_borrows = self._pending_borrows, []
        return futs

    def ref_dropped(self, oid: bytes):
        """Called from ObjectRef.__del__ — possibly by the CYCLIC GC at an
        arbitrary allocation point, including inside a _mem_lock-held
        section of THIS thread. Taking _mem_lock here could self-deadlock,
        so __del__ only enqueues (deque.append is atomic and allocation-
        free) and pokes the io loop; the drop is processed by
        _drain_dropped_refs on the io thread (plus opportunistically from
        normal call sites), always outside GC context."""
        self._dropped_refs.append(oid)
        try:
            self.io.loop.call_soon_threadsafe(self._drain_dropped_refs)
        except RuntimeError:
            pass  # loop already closed (shutdown): nothing left to free

    def _drain_dropped_refs(self):
        while True:
            try:
                oid = self._dropped_refs.popleft()
            except IndexError:
                return
            self._ref_dropped_now(oid)

    def _ref_dropped_now(self, oid: bytes):
        with self._mem_lock:
            n = self._local_refs.get(oid, 0) - 1
            if n > 0:
                self._local_refs[oid] = n
                return
            self._local_refs.pop(oid, None)
            owner_addr = self._borrowed.get(oid)
            if owner_addr is not None and self._arg_pins.get(oid):
                # Still pinned by an in-flight task: unborrow when unpinned.
                self._deferred_unborrow.add(oid)
                return
            if owner_addr is not None:
                self._borrowed.pop(oid, None)
        if owner_addr is not None:
            self.io.spawn(self._owner_call(
                owner_addr, "unborrow", oid=oid, borrower=self.worker_ident))
        elif oid in self._owned:
            self._maybe_free(oid)

    def pin_args(self, oids):
        with self._mem_lock:
            for oid in oids:
                self._arg_pins[oid] = self._arg_pins.get(oid, 0) + 1

    def unpin_args(self, oids):
        to_unborrow, to_free = [], []
        with self._mem_lock:
            for oid in oids:
                n = self._arg_pins.get(oid, 0) - 1
                if n > 0:
                    self._arg_pins[oid] = n
                    continue
                self._arg_pins.pop(oid, None)
                if oid in self._deferred_unborrow:
                    self._deferred_unborrow.discard(oid)
                    addr = self._borrowed.pop(oid, None)
                    if addr is not None:
                        to_unborrow.append((addr, oid))
                elif oid in self._owned and not self._local_refs.get(oid):
                    to_free.append(oid)
        for addr, oid in to_unborrow:
            self.io.spawn(self._owner_call(
                addr, "unborrow", oid=oid, borrower=self.worker_ident))
        for oid in to_free:
            self._maybe_free(oid)

    def _maybe_free(self, oid: bytes):
        """Owner-side delete-on-zero: free the object's data everywhere once
        nothing holds it (local refs, borrowers, containing objects, pins)."""
        # Values popped under the lock are destroyed AFTER it is released:
        # a value containing registered ObjectRefs runs ref_dropped from its
        # __del__, which re-acquires this (non-reentrant) lock.
        displaced = []
        with self._mem_lock:
            rec = self._owned.get(oid)
            if rec is None:
                return
            if (self._local_refs.get(oid) or rec["borrowers"]
                    or rec["containers"] or self._arg_pins.get(oid)):
                return
            del self._owned[oid]
            displaced.append(self.memory_store.pop(oid, None))
            self._lineage.pop(oid, None)
            children = rec["children"]
            locations = set(rec["locations"])
            metric_defs.OBJECTS_OWNED.set(len(self._owned))
        del displaced
        self._put_refs.discard(oid)
        self._object_locations.pop(oid, None)
        # Drop the data copies.
        if self.store is not None and self.store.contains(oid):
            try:
                self.store.delete(oid)
            except Exception:
                pass
            if self.spill is not None:
                self.spill.delete(oid)
            locations.discard(self.node_id)
        for node in locations:
            self.io.spawn(self._free_on_node(node, oid))
        # Release our containment pins on nested refs.
        for child_oid, child_addr in children:
            self._unpin_child(child_oid, child_addr, oid)

    def _unpin_child(self, child_oid: bytes, child_addr, container_oid: bytes):
        if child_addr is None or tuple(child_addr) == tuple(self.owner_addr):
            with self._mem_lock:
                rec = self._owned.get(child_oid)
                if rec is not None:
                    rec["containers"].discard(container_oid)
            if rec is not None:
                self._maybe_free(child_oid)
        else:
            self.io.spawn(self._owner_call(
                tuple(child_addr), "unpin_container", oid=child_oid,
                container=container_oid))

    def _pin_children(self, container_oid: bytes, refs) -> list:
        """Record that `container_oid`'s serialized bytes contain `refs`;
        pin each inner object with its owner so it outlives the container.
        Returns the children list for the container's owner record."""
        children = []
        for ref in refs:
            child = ref.binary()
            addr = ref.owner_addr
            children.append((child, addr))
            if addr is None or tuple(addr) == tuple(self.owner_addr):
                with self._mem_lock:
                    rec = self._owned.get(child)
                    if rec is not None:
                        rec["containers"].add(container_oid)
            else:
                self.io.spawn(self._owner_call(
                    tuple(addr), "pin_container", oid=child,
                    container=container_oid))
        return children

    def free(self, refs, force: bool = True):
        """Eagerly delete objects' data (ray.internal.free analog)."""
        for ref in refs if isinstance(refs, (list, tuple)) else [refs]:
            oid = ref.binary()
            if oid in self._owned:
                with self._mem_lock:
                    rec = self._owned.get(oid)
                    if rec is not None:
                        rec["borrowers"].clear()
                        rec["containers"].clear()
                        self._local_refs.pop(oid, None)
                self._maybe_free(oid)
            elif ref.owner_addr is not None:
                self.io.spawn(self._owner_call(
                    tuple(ref.owner_addr), "force_free", oid=oid))

    async def _free_on_node(self, node_id: bytes, oid: bytes):
        addr = self._node_address(node_id)
        if addr is None:
            return
        try:
            client = await self._raylet_for(addr)
            await client.call("free_object", oid=oid)
        except Exception:
            pass

    async def _owner_call(self, addr: Tuple, method: str, **kw):
        """Ordered, best-effort RPC to an object owner (per-address lock so
        borrow/unborrow sequences never reorder)."""
        try:
            lock = self._owner_locks.setdefault(addr, asyncio.Lock())
            async with lock:
                client = self._owner_clients.get(addr)
                if client is None or client._dead:
                    client = RpcClient(*addr)
                    await client.connect(timeout=10)
                    self._owner_clients[addr] = client
                return await client.call(method, timeout=30, **kw)
        except Exception:
            return None  # owner gone: object is orphaned, nothing to do

    # -- owner-side protocol handlers (served by core_server) --------------

    async def _h_borrow(self, conn, oid: bytes, borrower: str):
        with self._mem_lock:
            rec = self._owned.get(oid)
            if rec is None:
                return {"found": False}
            rec["borrowers"].add(borrower)
        self._ensure_death_subscription()
        return {"found": True}

    async def _h_unborrow(self, conn, oid: bytes, borrower: str):
        with self._mem_lock:
            rec = self._owned.get(oid)
            if rec is not None:
                rec["borrowers"].discard(borrower)
        if rec is not None:
            self._maybe_free(oid)
        return {"ok": True}

    async def _h_pin_container(self, conn, oid: bytes, container: bytes):
        with self._mem_lock:
            rec = self._owned.get(oid)
            if rec is None:
                return {"found": False}
            rec["containers"].add(container)
        return {"found": True}

    async def _h_unpin_container(self, conn, oid: bytes, container: bytes):
        with self._mem_lock:
            rec = self._owned.get(oid)
            if rec is not None:
                rec["containers"].discard(container)
        if rec is not None:
            self._maybe_free(oid)
        return {"ok": True}

    async def _h_get_object(self, conn, oid: bytes):
        """Owner-side fetch: lets borrowers resolve refs whose value lives
        only in this process's memory store (nested refs, small results)."""
        with self._mem_lock:
            value = self.memory_store.get(oid, _MISSING)
        if value is not _MISSING and not isinstance(value, RayTpuError):
            segments, _ = serialization.serialize(value)
            return {"found": True, "payload": serialization.join_segments(segments)}
        rec = self._owned.get(oid)
        if rec is not None and rec["locations"]:
            return {"found": True, "location": next(iter(rec["locations"]))}
        if self.store is not None and self.store.contains(oid):
            return {"found": True, "location": self.node_id}
        return {"found": False}

    async def _h_force_free(self, conn, oid: bytes):
        with self._mem_lock:
            rec = self._owned.get(oid)
            if rec is not None:
                rec["borrowers"].clear()
                rec["containers"].clear()
                self._local_refs.pop(oid, None)
        self._maybe_free(oid)
        return {"ok": True}

    def _ensure_death_subscription(self):
        """Prune borrowers when their worker process dies (borrower-crash
        leg of the borrower protocol). Raylets report worker deaths to the
        GCS, which republishes on the 'worker_death' channel."""
        if self._death_sub_client is not None:
            return
        self._death_sub_client = True  # claim before the async connect

        async def on_push(method, data):
            if method != "pubsub" or data.get("channel") != "worker_death":
                return
            dead = data["message"].get("worker_id")
            if not dead:
                return
            affected = []
            with self._mem_lock:
                for oid, rec in list(self._owned.items()):
                    if dead in rec["borrowers"]:
                        rec["borrowers"].discard(dead)
                        affected.append(oid)
            for oid in affected:
                self._maybe_free(oid)

        async def _resub(client):
            await client._call_once("subscribe", 30,
                                    dict(channels=["worker_death"]))

        async def _connect():
            client = RpcClient(self.gcs.host, self.gcs.port, on_push=on_push,
                               auto_reconnect=True, on_reconnect=_resub)
            await client.connect(timeout=30)
            await client.call("subscribe", channels=["worker_death"])
            self._death_sub_client = client

        self.io.spawn(_connect())

    STREAMING = -1  # num_returns sentinel on the wire

    @classmethod
    def _normalize_num_returns(cls, num_returns) -> int:
        if num_returns == "streaming":
            return cls.STREAMING
        n = int(num_returns)
        if n < 0 and n != cls.STREAMING:
            raise ValueError(f"invalid num_returns {num_returns!r}")
        return n

    # ------------------------------------------------------------ normal tasks

    def submit_task(self, fn, args, kwargs, *, name: str, num_returns: int,
                    resources: Dict[str, float], max_retries: int,
                    scheduling_strategy=None, placement_group_id=None,
                    bundle_index=-1, runtime_env=None) -> List[ObjectRef]:
        from ray_tpu import runtime_env as renv_mod

        self._drain_dropped_refs()
        metric_defs.TASKS_SUBMITTED.inc()
        fn_id = self.register_function(fn)
        num_returns = self._normalize_num_returns(num_returns)
        ser_args, names, pins = self.serialize_args(args, kwargs)
        task_id = TaskID.generate().binary()
        runtime_env = renv_mod.prepare_runtime_env(
            self, self.merge_job_env(runtime_env))
        spec = TaskSpec(
            task_id=task_id, fn_id=fn_id, name=name, args=ser_args,
            kwarg_names=names, num_returns=num_returns, resources=resources,
            max_retries=max_retries, scheduling_strategy=scheduling_strategy,
            placement_group_id=placement_group_id,
            placement_group_bundle_index=bundle_index,
            runtime_env=runtime_env, pinned_oids=pins,
            # Propagate the caller's trace context (if any): the executing
            # worker adopts it so its execute span parents under ours.
            trace_id=tracing.current_trace_id(),
            parent_span_id=tracing.current_span_id())
        self.pin_args(pins)
        self._record_task_event(spec, "SUBMITTED")
        if num_returns == self.STREAMING:
            gen = self._make_generator(task_id)
            self.io.spawn(self._submit_async(spec))
            return [gen]
        refs = []
        with self._mem_lock:
            for i in range(num_returns):
                oid = ObjectID.for_task_return(TaskID(task_id), i).binary()
                self.result_futures[oid] = SyncFuture()
                self._result_meta[oid] = {"task_id": task_id.hex(),
                                          "name": name}
                refs.append(ObjectRef(oid, owner=self.node_id,
                                      owner_addr=self.owner_addr))
        for ref in refs:
            self._new_owned(ref.binary(), inline=True)
            self.register_ref(ref)
        self._record_lineage(spec, [r.binary() for r in refs])
        self.io.spawn(self._submit_async(spec))
        return refs

    def cancel(self, ref: ObjectRef, force: bool = False,
               recursive: bool = False) -> bool:
        """Cancel the task producing `ref` (ray.cancel analog).

        Queued tasks are dequeued and fail immediately with
        TaskCancelledError. Running tasks get a best-effort interrupt
        injected into the executing thread (async tasks are cancelled on
        the loop); `force=True` additionally kills the worker process.
        Returns True if a cancellation was delivered, False if the task
        already finished (or is unknown — e.g. an actor method, which the
        reference also refuses to cancel this way). `recursive` is
        accepted for signature parity; child-task cancellation is not
        propagated.
        """
        from ray_tpu.core.exceptions import TaskCancelledError

        if recursive:
            # Accepted for signature parity only — don't let callers rely
            # on child cancellation that never happens.
            logger.warning(
                "cancel(recursive=True): child-task cancellation is not "
                "propagated; only the task producing this ref is cancelled")
        oid = ref.binary() if hasattr(ref, "binary") else ref.id.binary()
        with self._mem_lock:
            rec = self._lineage.get(oid)
            fut = self.result_futures.get(oid)
            # Completed tasks pop their result future; the VALUE is the
            # evidence of completion. Returning False here must leave no
            # trace, or a no-op cancel would poison later reconstruction.
            finished = (fut.done() if fut is not None
                        else (oid in self.memory_store
                              or oid in self._object_locations))
        if rec is None or finished:
            return False
        spec = rec["spec"]
        task_id = spec.task_id
        self._cancelled_tasks.add(task_id)

        async def _do_cancel() -> bool:
            # 1. still queued? dequeue + fail (never reaches a worker).
            for state in self._keys.values():
                for queued in list(state.queue):
                    if queued.task_id == task_id:
                        state.queue.remove(queued)
                        self._complete_error(queued, TaskCancelledError(
                            f"task {queued.name} was cancelled"))
                        return True
            # 2. dispatched: interrupt the executing worker.
            lease = self._inflight_tasks.get(task_id)
            if lease is not None and lease.client is not None:
                try:
                    reply = await lease.client.call(
                        "cancel_task", task_id=task_id, force=force)
                    return bool(reply.get("ok"))
                except (ConnectionLost, OSError):
                    return True  # worker died with the cancel: cancelled
            # 3. neither queued nor on a worker but still pending: it is
            # awaiting dependency resolution — the post-resolve
            # _cancelled_tasks check in _run_on_lease will fail it before
            # it ever reaches a worker.
            with self._mem_lock:
                pending = (fut is not None and not fut.done())
            return pending
        try:
            delivered = bool(self.io.run(_do_cancel(), timeout=30))
        except Exception:
            logger.exception("cancel of %s failed", spec.name)
            delivered = False
        if not delivered:
            # No cancellation happened: leave no trace (the flag would
            # otherwise suppress legitimate retries/reconstruction).
            self._cancelled_tasks.discard(task_id)
        return delivered

    def merge_job_env(self, env: Optional[dict]) -> Optional[dict]:
        """Per-task/actor env overrides the job-level env; env_vars merge
        key-wise (reference runtime_env inheritance semantics)."""
        base = self.job_runtime_env
        if not base:
            return env
        if not env:
            return dict(base)
        merged = dict(base)
        merged.update(env)
        env_vars = dict(base.get("env_vars") or {})
        env_vars.update(env.get("env_vars") or {})
        if env_vars:
            merged["env_vars"] = env_vars
        return merged

    # ------------------------------------------------------------ lineage


    def _record_lineage(self, spec: TaskSpec, return_oids: List[bytes]):
        """Owner-side lineage for plasma-result reconstruction
        (TaskManager lineage analog, task_manager.h:219,577; recovery
        object_recovery_manager.h:38). Stateless tasks only — actor method
        results are never re-executed out of band."""
        if spec.actor_id is not None:
            return
        import copy

        pristine = copy.deepcopy(spec)
        pristine.pinned_oids = None  # pins belong to the original attempt
        rec = {"spec": pristine, "oids": list(return_oids),
               "attempts": cfg().reconstruction_attempts}
        with self._mem_lock:
            for oid in return_oids:
                self._lineage[oid] = rec
            # Bound lineage memory: drop oldest entries beyond the cap
            # (lineage bytes cap analog).
            while len(self._lineage) > cfg().lineage_max_entries:
                self._lineage.pop(next(iter(self._lineage)))

    def _reconstruct_start(self, oid: bytes,
                           preempted: bool = False) -> Optional[SyncFuture]:
        """Kick off re-execution of the task whose lineage produced `oid`;
        returns the result future (None if no lineage/attempts remain).
        If a (re-)execution producing `oid` is already in flight, piggyback
        on its future instead of double-executing the producer.
        `preempted=True` (the copy was lost to an announced node
        retirement) re-executes WITHOUT consuming the attempt budget."""
        with self._mem_lock:
            existing = self.result_futures.get(oid)
            if existing is not None and not existing.done():
                return existing
            rec = self._lineage.get(oid)
            if rec is None or rec["attempts"] <= 0:
                return None
            if rec["spec"].task_id in self._cancelled_tasks:
                return None  # cancelled tasks never re-execute
            if not preempted:
                rec["attempts"] -= 1
            import copy

            spec = copy.deepcopy(rec["spec"])
            out = None
            for roid in rec["oids"]:
                self.memory_store.pop(roid, None)
                self._object_locations.pop(roid, None)
                fut = SyncFuture()
                self.result_futures[roid] = fut
                if roid == oid:
                    out = fut
        metric_defs.RECONSTRUCTIONS.inc()
        logger.warning("reconstructing lost object %s by re-executing %s",
                       oid.hex()[:12], spec.name)
        self.io.spawn(self._submit_async(spec))
        return out

    def _reconstruct(self, oid: bytes, timeout: Optional[float],
                     preempted: bool = False) -> bool:
        """Re-execute the task whose lineage produced `oid` (the object's
        primary copy was lost with its node). Returns True if a new attempt
        was submitted and completed."""
        fut = self._reconstruct_start(oid, preempted=preempted)
        if fut is None:
            return False
        try:
            fut.result(timeout if timeout is not None else 600)
        except Exception:
            return False
        return True

    @staticmethod
    def _env_key(runtime_env: Optional[dict]) -> Optional[str]:
        """Stable runtime_env fingerprint: leases (and therefore pooled
        workers) are only shared between tasks with the SAME env
        (worker_pool.h runtime-env-keyed pool)."""
        if not runtime_env:
            return None
        import hashlib
        import json as json_mod

        return hashlib.sha1(json_mod.dumps(
            runtime_env, sort_keys=True, default=repr).encode()
        ).hexdigest()[:16]

    def _scheduling_key(self, spec: TaskSpec) -> Tuple:
        res = tuple(sorted(spec.resources.items()))
        pg = (spec.placement_group_id, spec.placement_group_bundle_index)
        return (spec.fn_id, res, pg, self._env_key(spec.runtime_env))

    async def _submit_async(self, spec: TaskSpec):
        # Resolve dependencies BEFORE the task can enter a queue or occupy a
        # lease (the reference's DependencyResolver runs before
        # RequestNewWorkerLease, normal_task_submitter.cc:117): a queued task
        # is always runnable. Resolving after lease assignment deadlocks the
        # pool — downstream tasks hold every worker awaiting upstream outputs
        # while the upstream tasks sit queued with no worker to run on.
        try:
            dep_err = await self._resolve_dependencies(spec)
        except Exception as e:
            # A failed resolve must surface on the result future, not kill
            # this (unobserved) coroutine — else get() hangs with no error.
            dep_err = e if isinstance(e, RayTpuError) else RayTpuError(
                f"dependency resolution for {spec.name} failed: {e!r}")
        if dep_err is not None:
            self._complete_error(spec, dep_err)
            return
        if spec.task_id in self._cancelled_tasks:
            # Cancelled while parked on a pending dependency: fail it here
            # rather than requesting (possibly forking) a worker just so
            # _run_on_lease can fail it.
            from ray_tpu.core.exceptions import TaskCancelledError

            self._complete_error(
                spec, TaskCancelledError(f"task {spec.name} was cancelled"))
            return
        key = self._scheduling_key(spec)
        state = self._keys.setdefault(key, _KeyState())
        state.queue.append(spec)
        await self._pump(key, state)

    async def _pump(self, key, state: _KeyState):
        # Assign queued tasks to idle leases.
        for lease in state.leases:
            if not state.queue:
                break
            if not lease.busy:
                spec = state.queue.pop(0)
                self._cancel_return(lease)
                lease.busy = True
                asyncio.ensure_future(self._run_on_lease(key, state, lease, spec))
        # Transfer idle leases from compatible keys (same resources/pg/env,
        # different function): workers are function-agnostic — they load any
        # function from the GCS table — so a warm worker leased for f can run
        # g without a raylet round-trip. The reference keys leases strictly
        # per-SchedulingKey (normal_task_submitter.h:52) and pays only a
        # PopWorker on a miss; here a pool miss forks a ~1s Python process,
        # so cross-key reuse is this build's warm-dispatch path.
        while state.queue:
            stolen = self._steal_idle_lease(key)
            if stolen is None:
                break
            spec = state.queue.pop(0)
            state.leases.append(stolen)
            stolen.busy = True
            asyncio.ensure_future(self._run_on_lease(key, state, stolen, spec))
        # Match outstanding lease requests to unassigned work: request more if
        # short, cancel extras if the queue drained (the raylet would otherwise
        # grant stale speculative leases and starve other scheduling keys).
        want = min(len(state.queue), cfg().lease_max_inflight_requests)
        if want > len(state.inflight_reqs):
            for _ in range(want - len(state.inflight_reqs)):
                req_id = os.urandom(8)
                state.inflight_reqs.add(req_id)
                asyncio.ensure_future(self._request_lease(key, state, req_id))
        elif want < len(state.inflight_reqs):
            extra = len(state.inflight_reqs) - want
            extras = list(state.inflight_reqs)[:extra]
            # The requests may have spilled; cancel everywhere we talk to,
            # one batched frame per raylet instead of reqs x raylets calls.
            for target in [self.raylet, *self._raylet_clients.values()]:
                asyncio.ensure_future(self._cancel_lease_reqs(target, extras))

    def _steal_idle_lease(self, key) -> Optional[_LeasedWorker]:
        """Pop an idle leased worker from a scheduling key that differs only
        in fn_id (identical resources / placement-group slot / runtime_env —
        any worker satisfying those can execute this key's tasks too).
        Fully-drained key states are pruned on the way so the scan stays
        bounded by LIVE keys, not every function ever submitted."""
        dead_keys = []
        found = None
        for other_key, other in self._keys.items():
            if other_key == key:
                continue
            if not other.leases and not other.queue and not other.inflight_reqs:
                dead_keys.append(other_key)
                continue
            if found is not None or other_key[1:] != key[1:]:
                continue
            if other.queue:
                continue  # its own work would just re-fork; don't starve it
            for lease in other.leases:
                if not lease.busy:
                    self._cancel_return(lease)
                    other.leases.remove(lease)
                    found = lease
                    break
        for dk in dead_keys:
            del self._keys[dk]
        return found

    async def _lease_idle(self, key, state: _KeyState, lease: _LeasedWorker):
        """A lease just went idle: feed its own queue first, else hand the
        warm worker to a compatible key with waiting work (the push half of
        cross-key reuse — without it, work queued while this lease was busy
        would wait out lease_idle_timeout_s and then fork anyway), else arm
        the idle-return timer."""
        lease.busy = False
        if state.queue:
            await self._pump(key, state)
            return
        for t_key, t_state in self._keys.items():
            if t_key == key or t_key[1:] != key[1:] or not t_state.queue:
                continue
            state.leases.remove(lease)
            t_state.leases.append(lease)
            lease.busy = True
            spec = t_state.queue.pop(0)
            asyncio.ensure_future(self._run_on_lease(t_key, t_state, lease, spec))
            return
        self._schedule_return(key, state, lease)

    async def _cancel_lease_reqs(self, target, req_ids):
        """Cancel a set of lease requests on one raylet: one
        cancel_lease_batch call, per-id fallback against an old raylet;
        a dead raylet has nothing left to cancel."""
        try:
            if "cancel_lease_batch" in self._typed_methods:
                try:
                    await target.call("cancel_lease_batch",
                                      req_ids=list(req_ids))
                    return
                except RpcError as e:
                    if (isinstance(e, ConnectionLost)
                            or "no handler" not in str(e)):
                        raise
                    self._typed_methods.discard("cancel_lease_batch")
            for req_id in req_ids:
                await target.call("cancel_lease_request", req_id=req_id)
        except Exception:
            pass

    async def _raylet_for(self, address: Tuple[str, int]) -> RpcClient:
        client = self._raylet_clients.get(address)
        if client is None or client._dead:
            client = RpcClient(*address, on_push=self._on_raylet_push)
            await client.connect(timeout=15)
            self._raylet_clients[address] = client
        return client

    async def _lease_call(self, target, resources, req_id, pg_id,
                          bundle_index, env_key) -> dict:
        """One lease RPC: coalesced into a LeaseBatchRequestMsg frame when
        the raylet speaks lease_batch2 (one scheduling pass grants the
        whole batch), else a typed LeaseRequestMsg/LeaseReplyMsg envelope,
        else legacy pickled kwargs against an older raylet."""
        from ray_tpu.runtime import wire

        if "lease_batch" in self._typed_methods:
            msg = wire.LeaseRequestMsg(
                resources=resources, for_actor=False,
                placement_group_id=pg_id or b"", bundle_index=bundle_index,
                env_key=env_key or "", req_id=req_id or os.urandom(8),
                holder=self.worker_ident)
            return await self._lease_call_batched(target, msg)
        if "lease_worker" in self._typed_methods:
            msg = wire.LeaseRequestMsg(
                resources=resources, for_actor=False,
                placement_group_id=pg_id or b"", bundle_index=bundle_index,
                env_key=env_key or "", req_id=req_id or b"",
                holder=self.worker_ident)
            try:
                encoded = await target.call("lease_worker2", m=msg.encode())
                return wire.LeaseReplyMsg.decode(encoded).to_reply()
            except RpcError as e:
                if "no handler" not in str(e):
                    raise
                self._typed_methods.discard("lease_worker")
        return await target.call(
            "lease_worker", resources=resources, req_id=req_id,
            placement_group_id=pg_id, bundle_index=bundle_index,
            env_key=env_key)

    async def _lease_call_batched(self, target, msg) -> dict:
        """Enqueue one lease request on the per-raylet micro-batch buffer
        and await its resolution. Requests landing on the same event-loop
        tick coalesce into one LeaseBatchRequestMsg (the buffer flushes on
        the next tick, or eagerly at lease_batch_max); replies arrive
        either inline in the LeaseBatchReplyMsg or later via a
        `lease_grant` push (see raylet.handle_lease_batch2)."""
        fut = asyncio.get_event_loop().create_future()
        buf = self._lease_batch_buf.setdefault(target, [])
        buf.append((msg, fut))
        if len(buf) >= cfg().lease_batch_max:
            self._lease_batch_buf.pop(target, None)
            asyncio.ensure_future(self._send_lease_batch(target, buf))
        elif len(buf) == 1:
            asyncio.ensure_future(self._flush_lease_batch(target))
        try:
            # The reply for a pending entry rides a push on the raylet
            # connection; if that connection dies the push never comes, so
            # poll connection liveness rather than waiting forever.
            while True:
                try:
                    return await asyncio.wait_for(asyncio.shield(fut), 1.0)
                except asyncio.TimeoutError:
                    if target._dead or target._closed:
                        raise ConnectionLost(
                            "raylet connection lost awaiting lease grant")
        finally:
            self._lease_grant_waiters.pop(msg.req_id, None)

    async def _flush_lease_batch(self, target):
        await asyncio.sleep(0)  # let same-tick requests pile on
        buf = self._lease_batch_buf.pop(target, None)
        if buf:
            await self._send_lease_batch(target, buf)

    async def _send_lease_batch(self, target, buf):
        from ray_tpu.runtime import wire

        by_id = {msg.req_id: fut for msg, fut in buf}
        # Register waiters BEFORE the call: a pending entry's lease_grant
        # push can arrive while we're still decoding the batch reply.
        self._lease_grant_waiters.update(by_id)
        try:
            encoded = await target.call(
                "lease_batch2",
                m=wire.LeaseBatchRequestMsg(
                    entries=[msg for msg, _ in buf]).encode())
            reply = wire.LeaseBatchReplyMsg.decode(encoded)
        except Exception as e:
            for msg, _ in buf:
                self._lease_grant_waiters.pop(msg.req_id, None)
            if (isinstance(e, RpcError) and not isinstance(e, ConnectionLost)
                    and "no handler" in str(e)):
                # Old raylet: fall back to per-request leasing for this and
                # every future request.
                self._typed_methods.discard("lease_batch")
                for msg, fut in buf:
                    asyncio.ensure_future(
                        self._lease_single_fallback(target, msg, fut))
                return
            for _, fut in buf:
                if not fut.done():
                    fut.set_exception(
                        e if isinstance(e, Exception) else RpcError(repr(e)))
            return
        for entry in reply.entries:
            fut = by_id.get(entry.req_id)
            if fut is not None and not fut.done():
                self._lease_grant_waiters.pop(entry.req_id, None)
                fut.set_result(entry.to_reply())
        # Entries in reply.pending resolve later via the lease_grant push
        # (_on_raylet_push); their waiters stay registered.

    async def _lease_single_fallback(self, target, msg, fut):
        try:
            reply = await self._lease_call(
                target, dict(msg.resources), msg.req_id,
                msg.placement_group_id or None, msg.bundle_index,
                msg.env_key or None)
        except Exception as e:
            if not fut.done():
                fut.set_exception(e)
            return
        if not fut.done():
            fut.set_result(reply)

    async def _request_lease(self, key, state: _KeyState, req_id: bytes):
        spec_resources = dict(key[1])
        pg_id, bundle_index = key[2]
        reply = None
        last_err = None
        # A spillback target can die between the routing decision (possibly
        # made from a stale gossip view) and our connect: restart the chain
        # from the local raylet, whose view self-corrects within a heartbeat.
        for attempt in range(4):
            target = self.raylet
            try:
                for _hop in range(4):  # bounded spillback chain
                    reply = await self._lease_call(
                        target, spec_resources, req_id, pg_id, bundle_index,
                        key[3] if len(key) > 3 else None)
                    if reply.get("spillback"):
                        target = await self._raylet_for(tuple(reply["spillback"]))
                        continue
                    break
                break
            except Exception as e:
                last_err = e
                reply = None
                await asyncio.sleep(0.5 * (attempt + 1))
        if reply is None:
            state.inflight_reqs.discard(req_id)
            self._fail_queued(
                state, RayTpuError(f"lease request failed: {last_err!r}"))
            return
        state.inflight_reqs.discard(req_id)
        if not reply.get("ok"):
            if reply.get("canceled"):
                # The cancel raced new work: the queue may have refilled
                # while this request was dying, and with it gone nothing
                # else would re-pump this key — a silent stall.
                if state.queue:
                    await self._pump(key, state)
                return
            if state.queue:
                self._fail_queued(state, RayTpuError(reply.get("error", "lease refused")))
            return
        lease = _LeasedWorker(reply["lease_id"], reply["worker_id"],
                              tuple(reply["worker_address"]), reply["node_id"],
                              target)
        try:
            lease.client = RpcClient(*lease.address,
                                     on_push=self._on_worker_push)
            await lease.client.connect(timeout=15)
        except Exception:
            await self._return_lease(state, lease, dead=True)
            return
        state.leases.append(lease)
        await self._pump(key, state)
        if not lease.busy:
            # Granted after the queue drained (speculative grant): give the
            # worker back promptly so it doesn't pin resources.
            self._schedule_return(key, state, lease)

    def _fail_queued(self, state: _KeyState, err: RayTpuError):
        while state.queue:
            spec = state.queue.pop(0)
            self._complete_error(spec, err)

    async def _resolve_dependencies(self, spec: TaskSpec) -> Optional[RayTpuError]:
        """DependencyResolver analog (normal_task_submitter.cc): before a
        spec may enter a key queue, wait for pending ObjectRef args; inline
        values that live only in this process's memory store (workers can't
        see it), keep plasma refs as-is. Returns an error to propagate if a
        dependency failed."""
        for i, arg in enumerate(spec.args):
            kind, payload = arg[0], arg[1]
            if kind != "r":
                continue
            oid = payload
            with self._mem_lock:
                fut = self.result_futures.get(oid)
            if fut is not None:
                try:
                    await asyncio.wrap_future(fut)
                except Exception:
                    pass
            with self._mem_lock:
                value = self.memory_store.get(oid, _MISSING)
            if value is not _MISSING:
                if isinstance(value, RayTpuError):
                    return value
                segments, _ = serialization.serialize(value)
                spec.args[i] = ("v", serialization.join_segments(segments))
            else:
                # Plasma-resident dependency: the owner recorded at
                # serialize_args time predates task completion — refresh it
                # now that the location of the result is known.
                location = self._object_locations.get(oid)
                if location is not None:
                    spec.args[i] = ("r", oid, location)
        return None

    async def _run_on_lease(self, key, state: _KeyState, lease: _LeasedWorker,
                            spec: TaskSpec):
        from ray_tpu.core.exceptions import TaskCancelledError

        if spec.task_id in self._cancelled_tasks:
            # Cancelled while queued but popped before the cancel scan saw
            # it: fail it here instead of dispatching.
            self._complete_error(
                spec, TaskCancelledError(f"task {spec.name} was cancelled"))
            await self._lease_idle(key, state, lease)
            return
        # Dependencies were resolved BEFORE the spec entered the queue
        # (_submit_async) — a queued task is always runnable, so nothing may
        # await here while holding the lease.
        self._inflight_tasks[spec.task_id] = lease
        try:
            reply = await self._push_call(lease.client, "push_task", spec)
        except (ConnectionLost, OSError):
            self._inflight_tasks.pop(spec.task_id, None)
            state.leases.remove(lease)
            await self._return_lease(state, lease, dead=True)
            if spec.task_id in self._cancelled_tasks:
                # force-cancel kills the worker mid-push: that death is
                # the cancellation, never a retryable crash.
                self._complete_error(spec, TaskCancelledError(
                    f"task {spec.name} was cancelled (force)"))
                return
            # Streaming tasks never retry transparently: items already
            # consumed by the caller cannot be un-yielded, so a re-execution
            # would duplicate them (the reference checkpoints the consumed
            # index; we surface the failure instead).
            if spec.max_retries > 0 and spec.num_returns != self.STREAMING:
                # A death caused by an announced drain/preemption does not
                # consume the retry budget (the node was retired on
                # schedule — retrying is the designed recovery, not a
                # symptom worth rationing).
                if not await self._node_was_preempted_async(lease.node_id):
                    spec.max_retries -= 1
                logger.warning("task %s worker died; retrying", spec.name)
                # Through _submit_async, not the queue directly: the resolve
                # pass refreshes plasma arg locations that may have died
                # with the worker's node (near-instant — deps are done).
                await self._submit_async(spec)
            else:
                self._complete_error(spec, WorkerCrashedError(
                    f"worker running {spec.name} died"))
            return
        except Exception as e:
            # Non-connection failure (e.g. worker couldn't load the function):
            # surface it on the result futures and free the lease.
            self._inflight_tasks.pop(spec.task_id, None)
            self._complete_error(spec, e if isinstance(e, RayTpuError)
                                 else RayTpuError(f"task push failed: {e!r}"))
            await self._lease_idle(key, state, lease)
            return
        self._inflight_tasks.pop(spec.task_id, None)
        lost_oid = self._lost_arg_oid(spec, reply)
        if lost_oid is not None:
            # Recursive object recovery (object_recovery_manager.h:38):
            # the task failed because one of its ARGS was lost. Release the
            # lease FIRST — the reconstruction may need the very resources
            # this lease holds (holding it while awaiting would deadlock a
            # fully-subscribed cluster) — then recover + resubmit aside.
            await self._lease_idle(key, state, lease)
            asyncio.ensure_future(
                self._recover_and_resubmit(spec, reply, lost_oid))
            return
        self._complete_task(spec, reply)
        await self._lease_idle(key, state, lease)

    async def _push_call(self, client, method: str, spec: TaskSpec) -> dict:
        """One task/actor push: typed TaskSpecMsg/TaskReplyMsg envelope when
        the worker speaks it, legacy pickled spec against an older one."""
        from ray_tpu.runtime import wire

        if method in self._typed_methods:
            try:
                encoded = await client.call(method + "2", m=spec.to_wire())
                return wire.TaskReplyMsg.decode(encoded).to_reply()
            except RpcError as e:
                if "no handler" not in str(e):
                    raise
                self._typed_methods.discard(method)
        return await client.call(method, spec=spec)

    def _lost_arg_oid(self, spec: TaskSpec, reply: dict) -> Optional[bytes]:
        """The oid of a reconstructible lost dependency, or None."""
        if reply.get("status") != "error" or spec.num_returns == self.STREAMING:
            return None
        cause = getattr(reply.get("error"), "cause", None)
        oid = getattr(cause, "oid", None)
        if oid is None:
            return None
        # Only an ARG-resolution loss is safe to recover by re-running: the
        # body never executed. An ObjectLostError raised from inside the
        # body (a get() on some unrelated ref) means the body DID run —
        # re-executing would duplicate side effects against max_retries.
        if oid not in {a[1] for a in spec.args if a[0] == "r"}:
            return None
        if getattr(spec, "_recon_retries", 0) >= \
                cfg().max_dependency_reconstructions:
            return None
        with self._mem_lock:
            rec = self._lineage.get(oid)
            if rec is None or rec["attempts"] <= 0:
                return None
        return oid

    async def _recover_and_resubmit(self, spec: TaskSpec, reply: dict,
                                    oid: bytes):
        """Reconstruct a lost arg, then resubmit the failed task (user
        retries are NOT consumed; bounded by max_dependency_reconstructions
        and the arg's own lineage attempts)."""
        try:
            spec._recon_retries = getattr(spec, "_recon_retries", 0) + 1
            fut = self._reconstruct_start(oid)
            if fut is not None:
                await asyncio.wait_for(asyncio.wrap_future(fut), 600)
                with self._mem_lock:
                    err = self.memory_store.get(oid)
                if not isinstance(err, RayTpuError):
                    # (_resolve_dependencies refreshes the arg's embedded
                    # location from _object_locations on resubmit.)
                    logger.warning("recovered lost dependency %s; re-running "
                                   "%s", oid.hex()[:12], spec.name)
                    await self._submit_async(spec)
                    return
        except Exception:
            logger.exception("lost-arg recovery for %s failed", spec.name)
        self._complete_task(spec, reply)

    def _schedule_return(self, key, state: _KeyState, lease: _LeasedWorker):
        loop = asyncio.get_event_loop()
        self._cancel_return(lease)
        lease.return_timer = loop.call_later(
            cfg().lease_idle_timeout_s,
            lambda: asyncio.ensure_future(self._maybe_return(key, state, lease)))

    def _cancel_return(self, lease: _LeasedWorker):
        if lease.return_timer is not None:
            lease.return_timer.cancel()
            lease.return_timer = None

    async def _maybe_return(self, key, state: _KeyState, lease: _LeasedWorker):
        if lease.busy or state.queue:
            return
        if lease in state.leases:
            state.leases.remove(lease)
        await self._return_lease(state, lease, dead=False)

    async def _return_lease(self, state, lease: _LeasedWorker, dead: bool):
        try:
            await lease.raylet.call("return_worker", lease_id=lease.lease_id,
                                    worker_dead=dead)
        except Exception:
            pass
        if lease.client is not None:
            await lease.client.close()

    def _complete_task(self, spec: TaskSpec, reply: dict):
        metric_defs.TASKS_FINISHED.inc(tags={"outcome": "ok"})
        # A successfully-completed task is beyond cancellation: drop the
        # flag so the set stays bounded and future reconstruction of this
        # task's objects is never suppressed by a raced/no-op cancel.
        self._cancelled_tasks.discard(spec.task_id)
        if spec.pinned_oids:
            self.unpin_args(spec.pinned_oids)
            spec.pinned_oids = None
        if reply.get("status") == "ok":
            self._record_task_event(spec, "FINISHED")
        if spec.num_returns == self.STREAMING:
            gen = self._generators.pop(spec.task_id, None)
            if gen is None:
                return
            if reply["status"] == "ok":
                gen.finish(reply["streamed"])
            else:
                gen.fail(reply["error"], reply.get("streamed"))
            return
        if reply["status"] == "ok":
            returns = reply["returns"]
            node_id = reply.get("node_id")
            # Deserialize OUTSIDE the lock: payloads may contain ObjectRefs
            # whose unpickling re-enters register_ref (same lock).
            values = {}
            for i, ret in enumerate(returns):
                if ret[0] == "v":
                    values[i] = serialization.deserialize(ret[1])
            displaced = []  # destroy evicted values outside the lock
            with self._mem_lock:
                for i, ret in enumerate(returns):
                    kind = ret[0]
                    children = ret[2] if len(ret) > 2 else None
                    oid = ObjectID.for_task_return(TaskID(spec.task_id), i).binary()
                    if kind == "v":
                        displaced.append(self.memory_store.pop(oid, None))
                        self.memory_store[oid] = values[i]
                    elif node_id is not None:
                        # Sealed in the executing node's plasma store.
                        self._object_locations[oid] = node_id
                    rec = self._owned.get(oid)
                    if rec is not None:
                        if kind != "v" and node_id is not None:
                            rec["locations"].add(node_id)
                            rec["inline"] = False
                        # The executor already pinned these children with
                        # their owners; we unpin when this return is freed.
                        if children:
                            rec["children"].extend(children)
                    fut = self.result_futures.pop(oid, None)
                    self._result_meta.pop(oid, None)
                    if fut is not None and not fut.done():
                        fut.set_result(True)
            del displaced
        else:
            err = reply["error"]
            self._complete_error(spec, err)

    def _complete_error(self, spec: TaskSpec, err: RayTpuError):
        metric_defs.TASKS_FINISHED.inc(tags={"outcome": "error"})
        if spec.pinned_oids:
            self.unpin_args(spec.pinned_oids)
            spec.pinned_oids = None
        self._record_task_event(spec, "FAILED", error=repr(err)[:500])
        if spec.num_returns == self.STREAMING:
            gen = self._generators.pop(spec.task_id, None)
            if gen is not None:
                gen.fail(err)
            return
        displaced = []  # destroy evicted values outside the lock (see
        with self._mem_lock:  # _maybe_free for why)
            for i in range(spec.num_returns):
                oid = ObjectID.for_task_return(TaskID(spec.task_id), i).binary()
                displaced.append(self.memory_store.pop(oid, None))
                self.memory_store[oid] = err
                fut = self.result_futures.pop(oid, None)
                self._result_meta.pop(oid, None)
                if fut is not None and not fut.done():
                    fut.set_result(True)
        del displaced

    # ------------------------------------------------------------ actor tasks

    def create_actor(self, spec: ActorSpec, timeout: float = 300.0) -> dict:
        return self.io.run(self.gcs.call("create_actor", spec=spec, timeout=timeout))

    def submit_actor_task(self, actor_id: bytes, method_name: str, args, kwargs,
                          *, num_returns: int, name: str,
                          max_task_retries: int = 0) -> List[ObjectRef]:
        metric_defs.ACTOR_CALLS.inc()
        num_returns = self._normalize_num_returns(num_returns)
        ser_args, names, pins = self.serialize_args(args, kwargs)
        task_id = TaskID.generate().binary()
        spec = TaskSpec(task_id=task_id, fn_id=b"", name=name, args=ser_args,
                        kwarg_names=names, num_returns=num_returns,
                        max_retries=max_task_retries, actor_id=actor_id,
                        method_name=method_name, pinned_oids=pins,
                        trace_id=tracing.current_trace_id(),
                        parent_span_id=tracing.current_span_id())
        self.pin_args(pins)
        self._record_task_event(spec, "SUBMITTED")
        client = self._actor_clients.get(actor_id)
        if client is None:
            client = self._actor_clients.setdefault(actor_id, _ActorClient(self, actor_id))
        if num_returns == self.STREAMING:
            gen = self._make_generator(task_id)
            self.io.spawn(client.enqueue(spec))
            return [gen]
        refs = []
        with self._mem_lock:
            for i in range(num_returns):
                oid = ObjectID.for_task_return(TaskID(task_id), i).binary()
                self.result_futures[oid] = SyncFuture()
                self._result_meta[oid] = {"task_id": task_id.hex(),
                                          "name": name,
                                          "actor_id": actor_id.hex()}
                refs.append(ObjectRef(oid, owner_addr=self.owner_addr))
        for ref in refs:
            self._new_owned(ref.binary(), inline=True)
            self.register_ref(ref)
        self.io.spawn(client.enqueue(spec))
        return refs

    def object_table(self, limit: int = 1000) -> List[dict]:
        """Owner-side object table of THIS process: refcounts, locations,
        pin state, plus spill state and size where cheaply determinable.
        Serves `state.list_objects()` locally and the `list_objects` worker
        RPC that `state.summarize_objects()` fans out cluster-wide."""
        with self._mem_lock:
            rows = [(oid, dict(local_refs=self._local_refs.get(oid, 0),
                               borrowers=len(rec["borrowers"]),
                               containers=len(rec["containers"]),
                               locations=[loc.hex()
                                          for loc in rec["locations"]],
                               pinned=self._arg_pins.get(oid, 0),
                               in_memory=oid in self.memory_store))
                    for oid, rec in list(self._owned.items())[:limit]]
        out = []
        for oid, row in rows:
            row["object_id"] = oid.hex()
            row["owner"] = self.worker_ident
            spilled = (self.spill is not None
                       and self.spill.contains(oid))
            row["spilled"] = spilled
            size = None
            if spilled:
                try:
                    size = os.path.getsize(self.spill._path(oid))
                except OSError:
                    pass
            elif self.store is not None and self.store.contains(oid):
                try:
                    buf = self.store.get(oid, timeout=0)
                    size = len(buf)
                    buf.release()
                except Exception:
                    pass
            row["size"] = size
            out.append(row)
        return out

    def actor_stats(self, actor_id: bytes, timeout: float = 5.0) -> dict:
        """Query an actor worker's execution stats (queued + ongoing actor
        tasks) over a direct RPC served on the worker's IO loop — never
        queued behind user code (used by serve autoscaling)."""
        return self.actor_stats_many([actor_id], timeout=timeout)[0]

    def actor_stats_many(self, actor_ids: Sequence[bytes],
                         timeout: float = 5.0) -> List[Optional[dict]]:
        """Concurrent actor_stats over many actors; one wall-clock timeout
        budget for the whole batch. Unreachable actors yield None (their
        query coroutine is cancelled, not leaked)."""
        clients = []
        for actor_id in actor_ids:
            client = self._actor_clients.get(actor_id)
            if client is None:
                client = self._actor_clients.setdefault(
                    actor_id, _ActorClient(self, actor_id))
            clients.append(client)

        async def _one(client):
            try:
                await client._ensure_connected()
                return await client.client.call("actor_stats", timeout=timeout)
            except Exception:
                return None

        async def _all():
            return await asyncio.gather(
                *(asyncio.wait_for(_one(c), timeout) for c in clients),
                return_exceptions=True)

        results = self.io.run(_all(), timeout=timeout + 5)
        return [r if isinstance(r, dict) else None for r in results]

    def kill_actor(self, actor_id: bytes, no_restart: bool = True):
        self.io.run(self.gcs.call("kill_actor", actor_id=actor_id,
                                  no_restart=no_restart))

    def get_actor_info(self, actor_id=None, name=None, namespace="default") -> dict:
        return self.io.run(self.gcs.call("get_actor", actor_id=actor_id, name=name,
                                         namespace=namespace))

    # ------------------------------------------------------------ shutdown

    def shutdown(self, kill_cluster: bool):
        try:
            if kill_cluster:
                self.io.run(self.gcs.call("shutdown_cluster", timeout=5), timeout=10)
        except Exception:
            pass
        try:
            for client in self._actor_clients.values():
                if client.client is not None:
                    self.io.run(client.client.close(), timeout=2)
            for client in self._owner_clients.values():
                self.io.run(client.close(), timeout=2)
            if self._death_sub_client not in (None, True):
                self.io.run(self._death_sub_client.close(), timeout=2)
            self.io.run(self.core_server.close(), timeout=2)
            self.io.run(self.gcs.close(), timeout=2)
            if self.raylet is not None:
                self.io.run(self.raylet.close(), timeout=2)
        except Exception:
            pass
        self.io.stop()
        if self.store is not None:
            self.store.close()


class _ActorClient:
    """Direct submission channel to one actor (actor_task_submitter.h:75):
    sequence numbers, ordered delivery, reconnect-on-restart.

    Submission is PIPELINED: up to MAX_INFLIGHT calls are outstanding at
    once, so a concurrent actor (max_concurrency > 1, or async methods)
    actually executes concurrently. Wire order is GUARANTEED to be seq_no
    order because the pump itself performs every send (call_send) before
    spawning the reply-waiter task — task-per-call sending would let late
    calls overtake early ones parked on the connection-setup lock. After a
    reconnect (actor restart), retried calls may re-arrive out of order
    relative to each other — matching the reference's at-most-once,
    retry-opt-in semantics."""

    def __init__(self, core: CoreWorker, actor_id: bytes):
        self.core = core
        self.actor_id = actor_id
        self.client: Optional[RpcClient] = None
        self.seq_no = 0
        self.connect_lock = asyncio.Lock()
        self._queue: asyncio.Queue = asyncio.Queue()
        self._pump_task: Optional[asyncio.Task] = None
        self._sem = asyncio.Semaphore(cfg().actor_max_inflight_calls)

    async def enqueue(self, spec: TaskSpec):
        """Per-caller FIFO: one pump drains the queue so wire order ==
        submission order (ActorSchedulingQueue sequencing analog)."""
        await self._queue.put(spec)
        if self._pump_task is None or self._pump_task.done():
            self._pump_task = asyncio.ensure_future(self._pump())

    async def _pump(self):
        while not self._queue.empty():
            spec = self._queue.get_nowait()
            try:
                dep_err = await self.core._resolve_dependencies(spec)
            except Exception as e:
                # A failed dependency resolve must not kill the pump (that
                # would strand every queued spec with hung result futures).
                self.core._complete_error(spec, ActorDiedError(
                    self.actor_id.hex(), f"dependency resolution failed: {e!r}"))
                continue
            if dep_err is not None:
                self.core._complete_error(spec, dep_err)
                continue
            spec.seq_no = self.seq_no
            self.seq_no += 1
            await self._sem.acquire()
            # SEND from the pump itself (strictly ordered), then hand the
            # reply future to a concurrent waiter task. Spawning whole
            # call coroutines instead would let late specs overtake early
            # ones still parked on the connection-setup lock: tasks wake
            # from a lock one loop-iteration at a time while fresh tasks
            # run straight through the connected fast path — observed as
            # a contiguous run of early calls executing AFTER later ones
            # (the test_actor_ordering flake).
            fut = client = None
            try:
                await self._ensure_connected()
                client = self.client
                if "push_actor_task" in self.core._typed_methods:
                    fut = await client.call_send("push_actor_task2",
                                                 m=spec.to_wire())
                else:
                    fut = await client.call_send("push_actor_task", spec=spec)
            except ActorDiedError as e:
                self.core._complete_error(spec, e)
                self._sem.release()
                continue
            except Exception:
                # Transient send/connect failure: _call_one's retry loop
                # redials and re-sends (documented: retried calls may
                # re-arrive out of order, matching reference at-most-once
                # + opt-in-retry semantics).
                fut = None
            asyncio.ensure_future(self._call_one(spec, client, fut))

    async def _ensure_connected(self):
        if self.client is not None:
            return
        async with self.connect_lock:
            if self.client is not None:
                return
            deadline = time.monotonic() + 120
            while True:
                info = await self.core.gcs.call("get_actor", actor_id=self.actor_id)
                if not info.get("found"):
                    raise ActorDiedError(self.actor_id.hex(), "unknown actor")
                state = info["state"]
                if state == "ALIVE":
                    client = RpcClient(*info["address"],
                                       on_push=self.core._on_worker_push)
                    await client.connect(timeout=15)
                    self.client = client
                    return
                if state == "DEAD":
                    # Slice-lost deaths surface as TpuSliceLostError so
                    # callers (e.g. Train's controller) can gang-restart
                    # instead of treating it as a lone-actor failure.
                    raise actor_death_error(self.actor_id.hex(),
                                            info.get("death_reason", ""))
                if time.monotonic() > deadline:
                    raise ActorDiedError(self.actor_id.hex(),
                                         f"stuck in state {state}")
                await asyncio.sleep(0.1)

    async def _drop_client(self, client: Optional[RpcClient]):
        """Close-once under concurrent failures: only the task whose client
        reference is still current tears it down."""
        if client is not None and self.client is client:
            self.client = None
            await client.close()

    async def _call_one(self, spec: TaskSpec,
                        sent_client: Optional[RpcClient] = None,
                        sent_fut: Optional[asyncio.Future] = None):
        """Await the pump-sent reply (sent_fut); on connection loss, retry
        the full call per spec.max_retries (re-sends happen here, out of
        the ordered pump — acceptable: retry reordering is documented)."""
        try:
            # Streaming methods never retry transparently (items already
            # consumed cannot be un-yielded; see _run_on_lease).
            attempts = (1 if spec.num_returns == CoreWorker.STREAMING
                        else spec.max_retries + 1)
            last_err: Optional[BaseException] = None
            client = sent_client
            while attempts > 0:
                attempts -= 1
                try:
                    if sent_fut is not None:
                        fut, sent_fut = sent_fut, None
                        reply = await fut
                        if isinstance(reply, (bytes, bytearray, memoryview)):
                            from ray_tpu.runtime import wire

                            reply = wire.TaskReplyMsg.decode(reply).to_reply()
                    else:
                        await self._ensure_connected()
                        client = self.client
                        reply = await self.core._push_call(
                            client, "push_actor_task", spec)
                    self.core._complete_task(spec, reply)
                    return
                except (ConnectionLost, OSError) as e:
                    # Connection died: drop the client; next attempt
                    # re-resolves the address (actor may be restarting).
                    await self._drop_client(client)
                    last_err = e
                except RpcError as e:
                    if "no handler" in str(e):
                        # Older worker predates the typed envelope: flip to
                        # the legacy pickled spec and re-send. The probe
                        # must not consume retry budget (streaming methods
                        # have exactly one attempt).
                        self.core._typed_methods.discard("push_actor_task")
                        attempts += 1
                        last_err = e
                        continue
                    raise
                except ActorDiedError as e:
                    self.core._complete_error(spec, e)
                    return
            # Retry budget exhausted on connection loss. Ask the GCS whether
            # the actor is in fact dead — its death_reason carries failure-
            # domain typing (TpuSliceLost) that a bare socket error loses.
            try:
                info = await self.core.gcs.call("get_actor",
                                                actor_id=self.actor_id)
                if info.get("found") and info.get("state") == "DEAD":
                    self.core._complete_error(spec, actor_death_error(
                        self.actor_id.hex(), info.get("death_reason", "")))
                    return
            except Exception:
                pass
            self.core._complete_error(spec, ActorDiedError(
                self.actor_id.hex(), f"connection lost: {last_err!r}"))
        except Exception as e:
            self.core._complete_error(spec, ActorDiedError(
                self.actor_id.hex(), f"submit failed: {e!r}"))
        finally:
            self._sem.release()


# ---------------------------------------------------------------- globals

_global_worker: Optional[CoreWorker] = None
_global_lock = threading.Lock()


def global_worker() -> CoreWorker:
    if _global_worker is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return _global_worker


def set_global_worker(worker: Optional[CoreWorker]):
    global _global_worker
    with _global_lock:
        _global_worker = worker


def is_initialized() -> bool:
    return _global_worker is not None
