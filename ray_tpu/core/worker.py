"""The per-process core worker: connections, object access, task submission.

Reference analog: src/ray/core_worker/core_worker.h CoreWorker (Put
core_worker.cc:1522, Get :1823, SubmitTask via
transport/normal_task_submitter.cc:23 with per-SchedulingKey lease caching,
SubmitActorTask :2803 via actor_task_submitter.h:75) plus the in-process
memory store for inlined results (store_provider/memory_store/).

One instance per process (driver or worker), created by ray_tpu.init() /
worker bootstrap. Synchronous public methods; all I/O on a dedicated asyncio
thread (the instrumented_io_context analog).
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import os
import threading
import time
from concurrent.futures import Future as SyncFuture
from typing import Any, Dict, List, Optional, Sequence, Tuple

import cloudpickle

from ray_tpu.core import serialization
from ray_tpu.core.exceptions import (
    ActorDiedError, GetTimeoutError, ObjectLostError, RayTpuError, TaskError,
    WorkerCrashedError)
from ray_tpu.core.generator import ObjectRefGenerator, _GeneratorState
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.task_spec import ActorSpec, TaskSpec
from ray_tpu.runtime.object_store import ObjectNotFoundError, ObjectStore
from ray_tpu.runtime.object_store.spill import SpillManager
from ray_tpu.runtime.object_store.store import StoreFullError
from ray_tpu.runtime.rpc import ConnectionLost, EventLoopThread, RpcClient
from ray_tpu.utils.ids import ObjectID, TaskID

logger = logging.getLogger(__name__)

INLINE_RESULT_MAX = 100 * 1024
LEASE_IDLE_TIMEOUT_S = 1.0
_MISSING = object()


class _LeasedWorker:
    def __init__(self, lease_id, worker_id, address, node_id, raylet):
        self.lease_id = lease_id
        self.worker_id = worker_id
        self.address = address
        self.node_id = node_id
        self.raylet = raylet  # the raylet client that granted this lease
        self.client: Optional[RpcClient] = None
        self.busy = False
        self.return_timer: Optional[asyncio.TimerHandle] = None


class _KeyState:
    """Per-SchedulingKey submission state (normal_task_submitter.h:52)."""

    def __init__(self):
        self.queue: List[TaskSpec] = []
        self.leases: List[_LeasedWorker] = []
        self.inflight_reqs: set = set()  # outstanding lease request ids


class CoreWorker:
    def __init__(self, mode: str, gcs_address: Tuple[str, int],
                 raylet_address: Optional[Tuple[str, int]],
                 store_path: Optional[str], session_dir: str,
                 node_id: Optional[bytes] = None):
        self.mode = mode
        self.session_dir = session_dir
        self.node_id = node_id
        self.io = EventLoopThread()
        self.gcs = self.io.run(self._connect(gcs_address, auto_reconnect=True))
        self.raylet = (self.io.run(self._connect(raylet_address))
                       if raylet_address else None)
        self.store = ObjectStore(store_path, create=False) if store_path else None
        self.spill = (SpillManager(self.store, os.path.join(session_dir, "spill"))
                      if self.store is not None else None)
        self._node_addrs: Dict[bytes, Tuple[str, int]] = {}  # node_id -> raylet addr
        self.memory_store: Dict[bytes, Any] = {}      # oid -> deserialized value
        self._object_locations: Dict[bytes, bytes] = {}  # oid -> node_id (plasma results)
        self.result_futures: Dict[bytes, SyncFuture] = {}
        self._mem_lock = threading.Lock()
        self._registered_fns: set = set()
        self._keys: Dict[Tuple, _KeyState] = {}
        self._raylet_clients: Dict[Tuple[str, int], RpcClient] = {}
        self._actor_clients: Dict[bytes, "_ActorClient"] = {}
        self._put_refs: set = set()                   # plasma ids this process created
        self._lineage: Dict[bytes, dict] = {}         # return oid -> lineage record
        self._generators: Dict[bytes, _GeneratorState] = {}  # task_id -> state
        self.current_actor_id: Optional[bytes] = None
        self.current_task_name: Optional[str] = None
        self.job_id = None
        self.job_runtime_env: Optional[dict] = None   # init(runtime_env=...)

    @staticmethod
    async def _connect(addr, auto_reconnect: bool = False):
        client = RpcClient(addr[0], addr[1], auto_reconnect=auto_reconnect)
        await client.connect(timeout=60)
        return client

    # ------------------------------------------------------------------ put/get

    def _require_store(self) -> ObjectStore:
        if self.store is None:
            raise RayTpuError(
                "this process is not colocated with a node object store "
                "(remote-attached driver); put/get of plasma objects is unavailable")
        return self.store

    def put(self, value: Any) -> ObjectRef:
        if isinstance(value, ObjectRef):
            raise TypeError("put() does not accept ObjectRefs")
        oid = ObjectID.generate().binary()
        segments, total = serialization.serialize(value)
        self._write_segments_to_plasma(oid, segments, total)
        self._put_refs.add(oid)
        return ObjectRef(oid, owner=self.node_id)

    def spill_create(self, oid: bytes, size: int, metadata: bytes = b"") -> memoryview:
        """store.create with spill-before-evict when a spill dir is available."""
        if self.spill is not None:
            return self.spill.create_with_spill(oid, size, metadata)
        return self._require_store().create(oid, size, metadata)

    def _write_segments_to_plasma(self, oid: bytes, segments, total: int):
        store = self._require_store()
        buf = self.spill_create(oid, total)
        try:
            serialization.write_segments(buf, segments)
        except BaseException:
            buf.release()
            store.abort(oid)
            raise
        buf.release()
        store.seal(oid)

    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float] = None) -> List[Any]:
        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        for ref in refs:
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            out.append(self.get_one(ref, remaining))
        return out

    def get_one(self, ref: ObjectRef, timeout: Optional[float]) -> Any:
        oid = ref.binary()
        with self._mem_lock:
            if oid in self.memory_store:
                return self._raise_if_error(self.memory_store[oid])
            fut = self.result_futures.get(oid)
        if fut is not None:
            try:
                fut.result(timeout)
            except TimeoutError:
                raise GetTimeoutError(f"get() timed out waiting for {ref}")
            with self._mem_lock:
                if oid in self.memory_store:
                    return self._raise_if_error(self.memory_store[oid])
            # fell through: result is in plasma
        try:
            value = self._get_plasma_value(oid, ref.owner, timeout)
        except ObjectNotFoundError:
            raise GetTimeoutError(f"get() timed out waiting for {ref}")
        except ObjectLostError:
            # Lineage reconstruction: re-execute the producing task, then
            # re-enter the full read path (the new result may be inline).
            if not self._reconstruct(oid, timeout):
                raise
            return self.get_one(ref, timeout)
        return self._raise_if_error(value)

    PULL_CHUNK = 4 << 20

    def _get_plasma_value(self, oid: bytes, owner: Optional[bytes],
                          timeout: Optional[float]) -> Any:
        """Plasma read path: local shm store -> local spill dir -> remote pull
        from the object's location (ObjectManager pull protocol analog,
        object_manager.proto:60; ours is chunked raylet RPC over the control
        plane since tensors ride XLA collectives, not the object plane)."""
        location = self._object_locations.get(oid) or owner
        remote = (location is not None and self.node_id is not None
                  and location != self.node_id)
        store = self.store
        if store is not None:
            # With a remote fallback available, don't burn the whole timeout
            # waiting for a local appearance that will never happen.
            local_timeout = 0.05 if remote else timeout
            try:
                buf = store.get(oid, timeout=local_timeout)
                # `pin=buf` keeps the store read reference alive for as long
                # as any zero-copy array deserialized out of this payload is.
                return serialization.deserialize(buf.data, pin=buf)
            except ObjectNotFoundError:
                pass
            if self.spill is not None and self.spill.restore(oid):
                buf = store.get(oid, timeout=5)
                return serialization.deserialize(buf.data, pin=buf)
        if (remote or store is None) and location is not None:
            data = self._pull_remote(oid, location)
            if store is not None:
                # Cache locally so repeated gets are zero-copy shm reads.
                try:
                    view = self.spill_create(oid, len(data))
                    view[:] = data
                    view.release()
                    store.seal(oid)
                    buf = store.get(oid, timeout=5)
                    return serialization.deserialize(buf.data, pin=buf)
                except (ValueError, StoreFullError, ObjectNotFoundError):
                    pass  # concurrent create/restore or no room: use the copy
            return serialization.deserialize(memoryview(data))
        raise ObjectNotFoundError(oid.hex())

    def _node_address(self, node_id: bytes) -> Optional[Tuple[str, int]]:
        addr = self._node_addrs.get(node_id)
        if addr is not None:
            return addr
        for n in self.io.run(self.gcs.call("get_nodes")):
            nid = n["node_id"]
            if isinstance(nid, str):
                nid = bytes.fromhex(nid)
            self._node_addrs[nid] = tuple(n["address"])
        return self._node_addrs.get(node_id)

    def _pull_remote(self, oid: bytes, node_id: bytes) -> bytes:
        """Chunked pull of a sealed object from another node's raylet."""
        addr = self._node_address(node_id)
        if addr is None:
            raise ObjectLostError(
                f"object {oid.hex()[:12]} lives on unknown/dead node "
                f"{node_id.hex()[:12]}")

        async def _pull():
            client = await self._raylet_for(addr)
            chunks, off = [], 0
            while True:
                reply = await client.call(
                    "pull_object", oid=oid, offset=off, length=self.PULL_CHUNK)
                if not reply.get("found"):
                    raise ObjectLostError(
                        f"object {oid.hex()[:12]} not found on node "
                        f"{node_id.hex()[:12]} (evicted or node restarted)")
                chunk = reply["chunk"]
                chunks.append(chunk)
                off += len(chunk)
                if off >= reply["total"]:
                    return b"".join(chunks)
                if not chunk:
                    raise ObjectLostError(
                        f"truncated pull of {oid.hex()[:12]}")

        try:
            return self.io.run(_pull())
        except (ConnectionLost, OSError):
            raise ObjectLostError(
                f"node {node_id.hex()[:12]} unreachable while pulling "
                f"{oid.hex()[:12]}")

    @staticmethod
    def _raise_if_error(value):
        if isinstance(value, RayTpuError):
            raise value
        return value

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        assert num_returns <= len(refs)
        deadline = None if timeout is None else time.monotonic() + timeout
        pending = list(refs)
        ready: List[ObjectRef] = []
        sleep = 0.0005
        while len(ready) < num_returns:
            still = []
            for ref in pending:
                oid = ref.binary()
                with self._mem_lock:
                    in_mem = oid in self.memory_store
                    fut = self.result_futures.get(oid)
                if in_mem or (fut is not None and fut.done()) or \
                        (self.store is not None and self.store.contains(oid)):
                    ready.append(ref)
                else:
                    still.append(ref)
            pending = still
            if len(ready) >= num_returns:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            time.sleep(sleep)
            sleep = min(sleep * 1.5, 0.02)
        return ready, pending

    # ------------------------------------------------------------- functions

    def register_function(self, fn) -> bytes:
        pickled = cloudpickle.dumps(fn)
        fn_id = hashlib.sha1(pickled).digest()
        if fn_id not in self._registered_fns:
            self.io.run(self.gcs.call("kv_put", key=b"fn:" + fn_id, value=pickled,
                                      overwrite=False))
            self._registered_fns.add(fn_id)
        return fn_id

    def register_class(self, cls) -> bytes:
        pickled = cloudpickle.dumps(cls)
        class_id = hashlib.sha1(pickled).digest()
        if class_id not in self._registered_fns:
            self.io.run(self.gcs.call("kv_put", key=b"cls:" + class_id, value=pickled,
                                      overwrite=False))
            self._registered_fns.add(class_id)
        return class_id

    # ------------------------------------------------------------ serialization

    def serialize_args(self, args, kwargs) -> Tuple[List, List]:
        """Build TaskSpec args: small values inline; ObjectRefs stay refs;
        large values spill to plasma (DependencyResolver analog)."""
        out, names = [], []
        for name, value in [(None, a) for a in args] + list(kwargs.items()):
            if isinstance(value, ObjectRef):
                oid = value.binary()
                # Prefer the tracked result location over the ref's recorded
                # owner: task returns live on the node that executed the task.
                owner = self._object_locations.get(oid) or value.owner or self.node_id
                out.append(("r", oid, owner))
            else:
                segments, total = serialization.serialize(value)
                if total > INLINE_RESULT_MAX and self.store is not None:
                    oid = ObjectID.generate().binary()
                    self._write_segments_to_plasma(oid, segments, total)
                    self._put_refs.add(oid)
                    out.append(("r", oid, self.node_id))
                else:
                    out.append(("v", serialization.join_segments(segments)))
            names.append(name)
        return out, names

    def resolve_args(self, spec: TaskSpec) -> Tuple[list, dict]:
        """Worker-side: materialize TaskSpec args."""
        args, kwargs = [], {}
        for arg, name in zip(spec.args, spec.kwarg_names):
            kind, payload = arg[0], arg[1]
            if kind == "v":
                value = serialization.deserialize(payload)
            else:
                owner = arg[2] if len(arg) > 2 else None
                value = self._get_plasma_value(payload, owner, timeout=60)
            if name is None:
                args.append(value)
            else:
                kwargs[name] = value
        return args, kwargs

    # ------------------------------------------------------- streaming items

    async def _on_worker_push(self, method: str, data: dict):
        """Pushes from executor workers back to this (submitting) process.
        Currently: streaming-generator item reports (the
        ReportGeneratorItemReturns analog, core_worker.proto:462)."""
        if method != "gen_item":
            logger.warning("unexpected worker push %r", method)
            return
        task_id = data["task_id"]
        index = data["index"]
        oid = ObjectID.for_task_return(TaskID(task_id), index).binary()
        node_id = data.get("node_id")
        if "payload" in data:
            with self._mem_lock:
                self.memory_store[oid] = serialization.deserialize(
                    data["payload"])
        elif node_id is not None:
            self._object_locations[oid] = node_id
        gen = self._generators.get(task_id)
        if gen is not None:
            gen.push(index, ObjectRef(oid, owner=node_id))

    def _make_generator(self, task_id: bytes) -> ObjectRefGenerator:
        state = _GeneratorState()
        self._generators[task_id] = state
        return ObjectRefGenerator(task_id, state)

    STREAMING = -1  # num_returns sentinel on the wire

    @classmethod
    def _normalize_num_returns(cls, num_returns) -> int:
        if num_returns == "streaming":
            return cls.STREAMING
        n = int(num_returns)
        if n < 0 and n != cls.STREAMING:
            raise ValueError(f"invalid num_returns {num_returns!r}")
        return n

    # ------------------------------------------------------------ normal tasks

    def submit_task(self, fn, args, kwargs, *, name: str, num_returns: int,
                    resources: Dict[str, float], max_retries: int,
                    scheduling_strategy=None, placement_group_id=None,
                    bundle_index=-1, runtime_env=None) -> List[ObjectRef]:
        from ray_tpu import runtime_env as renv_mod

        fn_id = self.register_function(fn)
        num_returns = self._normalize_num_returns(num_returns)
        ser_args, names = self.serialize_args(args, kwargs)
        task_id = TaskID.generate().binary()
        runtime_env = renv_mod.prepare_runtime_env(
            self, self.merge_job_env(runtime_env))
        spec = TaskSpec(
            task_id=task_id, fn_id=fn_id, name=name, args=ser_args,
            kwarg_names=names, num_returns=num_returns, resources=resources,
            max_retries=max_retries, scheduling_strategy=scheduling_strategy,
            placement_group_id=placement_group_id,
            placement_group_bundle_index=bundle_index,
            runtime_env=runtime_env)
        if num_returns == self.STREAMING:
            gen = self._make_generator(task_id)
            self.io.spawn(self._submit_async(spec))
            return [gen]
        refs = [ObjectRef(ObjectID.for_task_return(TaskID(task_id), i).binary(),
                          owner=self.node_id)
                for i in range(num_returns)]
        with self._mem_lock:
            for ref in refs:
                self.result_futures[ref.binary()] = SyncFuture()
        self._record_lineage(spec, [r.binary() for r in refs])
        self.io.spawn(self._submit_async(spec))
        return refs

    def merge_job_env(self, env: Optional[dict]) -> Optional[dict]:
        """Per-task/actor env overrides the job-level env; env_vars merge
        key-wise (reference runtime_env inheritance semantics)."""
        base = self.job_runtime_env
        if not base:
            return env
        if not env:
            return dict(base)
        merged = dict(base)
        merged.update(env)
        env_vars = dict(base.get("env_vars") or {})
        env_vars.update(env.get("env_vars") or {})
        if env_vars:
            merged["env_vars"] = env_vars
        return merged

    # ------------------------------------------------------------ lineage

    LINEAGE_MAX_ENTRIES = 100_000
    RECONSTRUCTION_ATTEMPTS = 3

    def _record_lineage(self, spec: TaskSpec, return_oids: List[bytes]):
        """Owner-side lineage for plasma-result reconstruction
        (TaskManager lineage analog, task_manager.h:219,577; recovery
        object_recovery_manager.h:38). Stateless tasks only — actor method
        results are never re-executed out of band."""
        if spec.actor_id is not None:
            return
        import copy

        pristine = copy.deepcopy(spec)
        rec = {"spec": pristine, "oids": list(return_oids),
               "attempts": self.RECONSTRUCTION_ATTEMPTS}
        with self._mem_lock:
            for oid in return_oids:
                self._lineage[oid] = rec
            # Bound lineage memory: drop oldest entries beyond the cap
            # (lineage bytes cap analog).
            while len(self._lineage) > self.LINEAGE_MAX_ENTRIES:
                self._lineage.pop(next(iter(self._lineage)))

    def _reconstruct(self, oid: bytes, timeout: Optional[float]) -> bool:
        """Re-execute the task whose lineage produced `oid` (the object's
        primary copy was lost with its node). Returns True if a new attempt
        was submitted and completed."""
        with self._mem_lock:
            rec = self._lineage.get(oid)
            if rec is None or rec["attempts"] <= 0:
                return False
            rec["attempts"] -= 1
            import copy

            spec = copy.deepcopy(rec["spec"])
            futs = []
            for roid in rec["oids"]:
                self.memory_store.pop(roid, None)
                self._object_locations.pop(roid, None)
                fut = SyncFuture()
                self.result_futures[roid] = fut
                if roid == oid:
                    futs.append(fut)
        logger.warning("reconstructing lost object %s by re-executing %s",
                       oid.hex()[:12], spec.name)
        self.io.spawn(self._submit_async(spec))
        try:
            futs[0].result(timeout if timeout is not None else 600)
        except Exception:
            return False
        return True

    def _scheduling_key(self, spec: TaskSpec) -> Tuple:
        res = tuple(sorted(spec.resources.items()))
        pg = (spec.placement_group_id, spec.placement_group_bundle_index)
        return (spec.fn_id, res, pg)

    async def _submit_async(self, spec: TaskSpec):
        key = self._scheduling_key(spec)
        state = self._keys.setdefault(key, _KeyState())
        state.queue.append(spec)
        await self._pump(key, state)

    async def _pump(self, key, state: _KeyState):
        # Assign queued tasks to idle leases.
        for lease in state.leases:
            if not state.queue:
                break
            if not lease.busy:
                spec = state.queue.pop(0)
                self._cancel_return(lease)
                lease.busy = True
                asyncio.ensure_future(self._run_on_lease(key, state, lease, spec))
        # Match outstanding lease requests to unassigned work: request more if
        # short, cancel extras if the queue drained (the raylet would otherwise
        # grant stale speculative leases and starve other scheduling keys).
        want = min(len(state.queue), 64)
        if want > len(state.inflight_reqs):
            for _ in range(want - len(state.inflight_reqs)):
                req_id = os.urandom(8)
                state.inflight_reqs.add(req_id)
                asyncio.ensure_future(self._request_lease(key, state, req_id))
        elif want < len(state.inflight_reqs):
            extra = len(state.inflight_reqs) - want
            for req_id in list(state.inflight_reqs)[:extra]:
                # The request may have spilled; cancel everywhere we talk to.
                for target in [self.raylet, *self._raylet_clients.values()]:
                    asyncio.ensure_future(
                        target.call("cancel_lease_request", req_id=req_id))

    async def _raylet_for(self, address: Tuple[str, int]) -> RpcClient:
        client = self._raylet_clients.get(address)
        if client is None or client._dead:
            client = RpcClient(*address)
            await client.connect(timeout=15)
            self._raylet_clients[address] = client
        return client

    async def _request_lease(self, key, state: _KeyState, req_id: bytes):
        spec_resources = dict(key[1])
        pg_id, bundle_index = key[2]
        reply = None
        last_err = None
        # A spillback target can die between the routing decision (possibly
        # made from a stale gossip view) and our connect: restart the chain
        # from the local raylet, whose view self-corrects within a heartbeat.
        for attempt in range(4):
            target = self.raylet
            try:
                for _hop in range(4):  # bounded spillback chain
                    reply = await target.call(
                        "lease_worker", resources=spec_resources, req_id=req_id,
                        placement_group_id=pg_id, bundle_index=bundle_index)
                    if reply.get("spillback"):
                        target = await self._raylet_for(tuple(reply["spillback"]))
                        continue
                    break
                break
            except Exception as e:
                last_err = e
                reply = None
                await asyncio.sleep(0.5 * (attempt + 1))
        if reply is None:
            state.inflight_reqs.discard(req_id)
            self._fail_queued(
                state, RayTpuError(f"lease request failed: {last_err!r}"))
            return
        state.inflight_reqs.discard(req_id)
        if not reply.get("ok"):
            if reply.get("canceled"):
                return
            if state.queue:
                self._fail_queued(state, RayTpuError(reply.get("error", "lease refused")))
            return
        lease = _LeasedWorker(reply["lease_id"], reply["worker_id"],
                              tuple(reply["worker_address"]), reply["node_id"],
                              target)
        try:
            lease.client = RpcClient(*lease.address,
                                     on_push=self._on_worker_push)
            await lease.client.connect(timeout=15)
        except Exception:
            await self._return_lease(state, lease, dead=True)
            return
        state.leases.append(lease)
        await self._pump(key, state)
        if not lease.busy:
            # Granted after the queue drained (speculative grant): give the
            # worker back promptly so it doesn't pin resources.
            self._schedule_return(key, state, lease)

    def _fail_queued(self, state: _KeyState, err: RayTpuError):
        while state.queue:
            spec = state.queue.pop(0)
            self._complete_error(spec, err)

    async def _resolve_dependencies(self, spec: TaskSpec) -> Optional[RayTpuError]:
        """DependencyResolver analog (normal_task_submitter.cc): before pushing,
        wait for pending ObjectRef args; inline values that live only in this
        process's memory store (workers can't see it), keep plasma refs as-is.
        Returns an error to propagate if a dependency failed."""
        for i, arg in enumerate(spec.args):
            kind, payload = arg[0], arg[1]
            if kind != "r":
                continue
            oid = payload
            with self._mem_lock:
                fut = self.result_futures.get(oid)
            if fut is not None:
                try:
                    await asyncio.wrap_future(fut)
                except Exception:
                    pass
            with self._mem_lock:
                value = self.memory_store.get(oid, _MISSING)
            if value is not _MISSING:
                if isinstance(value, RayTpuError):
                    return value
                segments, _ = serialization.serialize(value)
                spec.args[i] = ("v", serialization.join_segments(segments))
            else:
                # Plasma-resident dependency: the owner recorded at
                # serialize_args time predates task completion — refresh it
                # now that the location of the result is known.
                location = self._object_locations.get(oid)
                if location is not None:
                    spec.args[i] = ("r", oid, location)
        return None

    async def _run_on_lease(self, key, state: _KeyState, lease: _LeasedWorker,
                            spec: TaskSpec):
        dep_err = await self._resolve_dependencies(spec)
        if dep_err is not None:
            self._complete_error(spec, dep_err)
            lease.busy = False
            if state.queue:
                await self._pump(key, state)
            else:
                self._schedule_return(key, state, lease)
            return
        try:
            reply = await lease.client.call("push_task", spec=spec)
        except (ConnectionLost, OSError):
            state.leases.remove(lease)
            await self._return_lease(state, lease, dead=True)
            # Streaming tasks never retry transparently: items already
            # consumed by the caller cannot be un-yielded, so a re-execution
            # would duplicate them (the reference checkpoints the consumed
            # index; we surface the failure instead).
            if spec.max_retries > 0 and spec.num_returns != self.STREAMING:
                spec.max_retries -= 1
                logger.warning("task %s worker died; retrying", spec.name)
                state.queue.append(spec)
                await self._pump(key, state)
            else:
                self._complete_error(spec, WorkerCrashedError(
                    f"worker running {spec.name} died"))
            return
        except Exception as e:
            # Non-connection failure (e.g. worker couldn't load the function):
            # surface it on the result futures and free the lease.
            self._complete_error(spec, e if isinstance(e, RayTpuError)
                                 else RayTpuError(f"task push failed: {e!r}"))
            lease.busy = False
            if state.queue:
                await self._pump(key, state)
            else:
                self._schedule_return(key, state, lease)
            return
        self._complete_task(spec, reply)
        lease.busy = False
        if state.queue:
            await self._pump(key, state)
        else:
            self._schedule_return(key, state, lease)

    def _schedule_return(self, key, state: _KeyState, lease: _LeasedWorker):
        loop = asyncio.get_event_loop()
        self._cancel_return(lease)
        lease.return_timer = loop.call_later(
            LEASE_IDLE_TIMEOUT_S,
            lambda: asyncio.ensure_future(self._maybe_return(key, state, lease)))

    def _cancel_return(self, lease: _LeasedWorker):
        if lease.return_timer is not None:
            lease.return_timer.cancel()
            lease.return_timer = None

    async def _maybe_return(self, key, state: _KeyState, lease: _LeasedWorker):
        if lease.busy or state.queue:
            return
        if lease in state.leases:
            state.leases.remove(lease)
        await self._return_lease(state, lease, dead=False)

    async def _return_lease(self, state, lease: _LeasedWorker, dead: bool):
        try:
            await lease.raylet.call("return_worker", lease_id=lease.lease_id,
                                    worker_dead=dead)
        except Exception:
            pass
        if lease.client is not None:
            await lease.client.close()

    def _complete_task(self, spec: TaskSpec, reply: dict):
        if spec.num_returns == self.STREAMING:
            gen = self._generators.pop(spec.task_id, None)
            if gen is None:
                return
            if reply["status"] == "ok":
                gen.finish(reply["streamed"])
            else:
                gen.fail(reply["error"], reply.get("streamed"))
            return
        if reply["status"] == "ok":
            returns = reply["returns"]
            node_id = reply.get("node_id")
            with self._mem_lock:
                for i, (kind, payload) in enumerate(returns):
                    oid = ObjectID.for_task_return(TaskID(spec.task_id), i).binary()
                    if kind == "v":
                        self.memory_store[oid] = serialization.deserialize(payload)
                    elif node_id is not None:
                        # Sealed in the executing node's plasma store.
                        self._object_locations[oid] = node_id
                    fut = self.result_futures.pop(oid, None)
                    if fut is not None and not fut.done():
                        fut.set_result(True)
        else:
            err = reply["error"]
            self._complete_error(spec, err)

    def _complete_error(self, spec: TaskSpec, err: RayTpuError):
        if spec.num_returns == self.STREAMING:
            gen = self._generators.pop(spec.task_id, None)
            if gen is not None:
                gen.fail(err)
            return
        with self._mem_lock:
            for i in range(spec.num_returns):
                oid = ObjectID.for_task_return(TaskID(spec.task_id), i).binary()
                self.memory_store[oid] = err
                fut = self.result_futures.pop(oid, None)
                if fut is not None and not fut.done():
                    fut.set_result(True)

    # ------------------------------------------------------------ actor tasks

    def create_actor(self, spec: ActorSpec, timeout: float = 300.0) -> dict:
        return self.io.run(self.gcs.call("create_actor", spec=spec, timeout=timeout))

    def submit_actor_task(self, actor_id: bytes, method_name: str, args, kwargs,
                          *, num_returns: int, name: str,
                          max_task_retries: int = 0) -> List[ObjectRef]:
        num_returns = self._normalize_num_returns(num_returns)
        ser_args, names = self.serialize_args(args, kwargs)
        task_id = TaskID.generate().binary()
        spec = TaskSpec(task_id=task_id, fn_id=b"", name=name, args=ser_args,
                        kwarg_names=names, num_returns=num_returns,
                        max_retries=max_task_retries, actor_id=actor_id,
                        method_name=method_name)
        client = self._actor_clients.get(actor_id)
        if client is None:
            client = self._actor_clients.setdefault(actor_id, _ActorClient(self, actor_id))
        if num_returns == self.STREAMING:
            gen = self._make_generator(task_id)
            self.io.spawn(client.enqueue(spec))
            return [gen]
        refs = [ObjectRef(ObjectID.for_task_return(TaskID(task_id), i).binary())
                for i in range(num_returns)]
        with self._mem_lock:
            for ref in refs:
                self.result_futures[ref.binary()] = SyncFuture()
        self.io.spawn(client.enqueue(spec))
        return refs

    def actor_stats(self, actor_id: bytes, timeout: float = 5.0) -> dict:
        """Query an actor worker's execution stats (queued + ongoing actor
        tasks) over a direct RPC served on the worker's IO loop — never
        queued behind user code (used by serve autoscaling)."""
        return self.actor_stats_many([actor_id], timeout=timeout)[0]

    def actor_stats_many(self, actor_ids: Sequence[bytes],
                         timeout: float = 5.0) -> List[Optional[dict]]:
        """Concurrent actor_stats over many actors; one wall-clock timeout
        budget for the whole batch. Unreachable actors yield None (their
        query coroutine is cancelled, not leaked)."""
        clients = []
        for actor_id in actor_ids:
            client = self._actor_clients.get(actor_id)
            if client is None:
                client = self._actor_clients.setdefault(
                    actor_id, _ActorClient(self, actor_id))
            clients.append(client)

        async def _one(client):
            try:
                await client._ensure_connected()
                return await client.client.call("actor_stats", timeout=timeout)
            except Exception:
                return None

        async def _all():
            return await asyncio.gather(
                *(asyncio.wait_for(_one(c), timeout) for c in clients),
                return_exceptions=True)

        results = self.io.run(_all(), timeout=timeout + 5)
        return [r if isinstance(r, dict) else None for r in results]

    def kill_actor(self, actor_id: bytes, no_restart: bool = True):
        self.io.run(self.gcs.call("kill_actor", actor_id=actor_id,
                                  no_restart=no_restart))

    def get_actor_info(self, actor_id=None, name=None, namespace="default") -> dict:
        return self.io.run(self.gcs.call("get_actor", actor_id=actor_id, name=name,
                                         namespace=namespace))

    # ------------------------------------------------------------ shutdown

    def shutdown(self, kill_cluster: bool):
        try:
            if kill_cluster:
                self.io.run(self.gcs.call("shutdown_cluster", timeout=5), timeout=10)
        except Exception:
            pass
        try:
            for client in self._actor_clients.values():
                if client.client is not None:
                    self.io.run(client.client.close(), timeout=2)
            self.io.run(self.gcs.close(), timeout=2)
            if self.raylet is not None:
                self.io.run(self.raylet.close(), timeout=2)
        except Exception:
            pass
        self.io.stop()
        if self.store is not None:
            self.store.close()


class _ActorClient:
    """Direct submission channel to one actor (actor_task_submitter.h:75):
    sequence numbers, ordered delivery, reconnect-on-restart.

    Submission is PIPELINED: up to MAX_INFLIGHT calls are outstanding at
    once, so a concurrent actor (max_concurrency > 1, or async methods)
    actually executes concurrently. Sends still happen in seq_no order (the
    pump creates call tasks in order; writes are FIFO under the client's
    write lock), so serial actors keep per-caller execution order. After a
    reconnect (actor restart), retried calls may re-arrive out of order
    relative to each other — matching the reference's at-most-once,
    retry-opt-in semantics."""

    MAX_INFLIGHT = 128

    def __init__(self, core: CoreWorker, actor_id: bytes):
        self.core = core
        self.actor_id = actor_id
        self.client: Optional[RpcClient] = None
        self.seq_no = 0
        self.connect_lock = asyncio.Lock()
        self._queue: asyncio.Queue = asyncio.Queue()
        self._pump_task: Optional[asyncio.Task] = None
        self._sem = asyncio.Semaphore(self.MAX_INFLIGHT)

    async def enqueue(self, spec: TaskSpec):
        """Per-caller FIFO: one pump drains the queue so wire order ==
        submission order (ActorSchedulingQueue sequencing analog)."""
        await self._queue.put(spec)
        if self._pump_task is None or self._pump_task.done():
            self._pump_task = asyncio.ensure_future(self._pump())

    async def _pump(self):
        while not self._queue.empty():
            spec = self._queue.get_nowait()
            try:
                dep_err = await self.core._resolve_dependencies(spec)
            except Exception as e:
                # A failed dependency resolve must not kill the pump (that
                # would strand every queued spec with hung result futures).
                self.core._complete_error(spec, ActorDiedError(
                    self.actor_id.hex(), f"dependency resolution failed: {e!r}"))
                continue
            if dep_err is not None:
                self.core._complete_error(spec, dep_err)
                continue
            spec.seq_no = self.seq_no
            self.seq_no += 1
            await self._sem.acquire()
            asyncio.ensure_future(self._call_one(spec))

    async def _ensure_connected(self):
        if self.client is not None:
            return
        async with self.connect_lock:
            if self.client is not None:
                return
            deadline = time.monotonic() + 120
            while True:
                info = await self.core.gcs.call("get_actor", actor_id=self.actor_id)
                if not info.get("found"):
                    raise ActorDiedError(self.actor_id.hex(), "unknown actor")
                state = info["state"]
                if state == "ALIVE":
                    client = RpcClient(*info["address"],
                                       on_push=self.core._on_worker_push)
                    await client.connect(timeout=15)
                    self.client = client
                    return
                if state == "DEAD":
                    raise ActorDiedError(self.actor_id.hex(),
                                         info.get("death_reason", ""))
                if time.monotonic() > deadline:
                    raise ActorDiedError(self.actor_id.hex(),
                                         f"stuck in state {state}")
                await asyncio.sleep(0.1)

    async def _drop_client(self, client: Optional[RpcClient]):
        """Close-once under concurrent failures: only the task whose client
        reference is still current tears it down."""
        if client is not None and self.client is client:
            self.client = None
            await client.close()

    async def _call_one(self, spec: TaskSpec):
        try:
            # Streaming methods never retry transparently (items already
            # consumed cannot be un-yielded; see _run_on_lease).
            attempts = (1 if spec.num_returns == CoreWorker.STREAMING
                        else spec.max_retries + 1)
            last_err: Optional[BaseException] = None
            client: Optional[RpcClient] = None
            while attempts > 0:
                attempts -= 1
                try:
                    await self._ensure_connected()
                    client = self.client
                    reply = await client.call("push_actor_task", spec=spec)
                    self.core._complete_task(spec, reply)
                    return
                except (ConnectionLost, OSError) as e:
                    # Connection died: drop the client; next attempt
                    # re-resolves the address (actor may be restarting).
                    await self._drop_client(client)
                    last_err = e
                except ActorDiedError as e:
                    self.core._complete_error(spec, e)
                    return
            self.core._complete_error(spec, ActorDiedError(
                self.actor_id.hex(), f"connection lost: {last_err!r}"))
        except Exception as e:
            self.core._complete_error(spec, ActorDiedError(
                self.actor_id.hex(), f"submit failed: {e!r}"))
        finally:
            self._sem.release()


# ---------------------------------------------------------------- globals

_global_worker: Optional[CoreWorker] = None
_global_lock = threading.Lock()


def global_worker() -> CoreWorker:
    if _global_worker is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return _global_worker


def set_global_worker(worker: Optional[CoreWorker]):
    global _global_worker
    with _global_lock:
        _global_worker = worker


def is_initialized() -> bool:
    return _global_worker is not None
