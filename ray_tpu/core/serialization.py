"""Value (de)serialization with zero-copy large buffers.

Reference analog: python/ray/_private/serialization.py + the plasma buffer
protocol. We use cloudpickle protocol 5: large contiguous buffers (numpy
arrays, bytes) are extracted out-of-band and laid out after the pickle stream
inside a single store object, so `get` reconstructs arrays as views over
shared memory without copying.

Object payload layout:
    [u32 n_buffers][u64 pickle_len][u64 len × n_buffers]
    [pickle bytes][pad to 8][buf 0][pad to 8][buf 1] ...
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, List, Optional, Tuple

import cloudpickle

# Buffers >= this go out-of-band (below it, copying beats the bookkeeping).
OUT_OF_BAND_THRESHOLD = 16 * 1024


def _align8(n: int) -> int:
    return (n + 7) & ~7


def serialize_with_refs(value: Any) -> Tuple[List, int, List]:
    """serialize() that also reports the ObjectRefs CONTAINED in the pickled
    graph (the owner pins them so a stored object keeps its inner refs alive
    — the nested-ref leg of the borrower protocol, reference_count.h:418)."""
    from ray_tpu.core import object_ref as ref_mod

    ref_mod.start_ref_collection()
    try:
        segments, total = serialize(value)
    finally:
        contained = ref_mod.finish_ref_collection()
    return segments, total, contained


# Fast-path markers: a top-level contiguous ndarray / bytes skips
# cloudpickle entirely (the dominant put() payloads; cloudpickle's
# reducer_override machinery costs ~0.1 ms/MiB-object). The flag rides the
# header's n_buffers field (real buffer counts never approach 2^31).
_FLAG_FAST = 0x8000_0000
_FAST_NDARRAY = 1
_FAST_BYTES = 2


def _try_fast_serialize(value: Any) -> Optional[Tuple[List, int]]:
    import numpy as np

    if isinstance(value, np.ndarray):
        # kind 'M'/'m' (datetime64/timedelta64) rejects memoryview; object
        # dtypes and non-contiguous layouts need pickle: all fall back.
        if (value.dtype.hasobject or value.dtype.kind in "Mm"
                or not value.flags.c_contiguous
                or value.nbytes < OUT_OF_BAND_THRESHOLD):
            return None
        meta = pickle.dumps((_FAST_NDARRAY, value.dtype.str, value.shape),
                            protocol=5)
        try:
            raw = memoryview(value).cast("B")
        except (ValueError, TypeError):
            return None  # exotic dtype: pickle path handles it
    elif type(value) is bytes:
        # bytes ONLY: bytearray must round-trip as bytearray (mutable),
        # which the pickle path preserves.
        if len(value) < OUT_OF_BAND_THRESHOLD:
            return None
        meta = pickle.dumps((_FAST_BYTES, None, None), protocol=5)
        raw = memoryview(value)
    else:
        return None
    header = struct.pack("<IQ", _FLAG_FAST | 1, len(meta)) + struct.pack(
        "<Q", raw.nbytes)
    segments: List = [header, meta]
    offset = len(header) + len(meta)
    pad = _align8(offset) - offset
    if pad:
        segments.append(b"\x00" * pad)
        offset += pad
    segments.append(raw)
    return segments, offset + raw.nbytes


def serialize(value: Any) -> Tuple[List, int]:
    """Serialize `value` to (segments, total_size).

    `segments` is a list of byte-likes whose concatenation is the object
    payload; callers write them into a store buffer (or b"".join them for
    inline transport) without extra copies of the large buffers.
    """
    fast = _try_fast_serialize(value)
    if fast is not None:
        return fast
    buffers: List[pickle.PickleBuffer] = []

    def buffer_callback(buf: pickle.PickleBuffer) -> bool:
        raw = buf.raw()
        if raw.nbytes >= OUT_OF_BAND_THRESHOLD and raw.contiguous:
            buffers.append(buf)
            return False  # out-of-band
        return True  # in-band

    pickled = cloudpickle.dumps(value, protocol=5, buffer_callback=buffer_callback)
    raw_views = [b.raw() for b in buffers]
    header = struct.pack("<IQ", len(raw_views), len(pickled)) + b"".join(
        struct.pack("<Q", v.nbytes) for v in raw_views)
    segments: List = [header, pickled]
    offset = len(header) + len(pickled)
    for v in raw_views:
        pad = _align8(offset) - offset
        if pad:
            segments.append(b"\x00" * pad)
            offset += pad
        segments.append(v)
        offset += v.nbytes
    return segments, offset


def write_segments(dst: memoryview, segments: List) -> None:
    off = 0
    for seg in segments:
        n = seg.nbytes if isinstance(seg, memoryview) else len(seg)
        dst[off:off + n] = seg
        off += n


def join_segments(segments: List) -> bytes:
    return b"".join(bytes(s) if isinstance(s, memoryview) else s for s in segments)


class PinnedBuffer:
    """A PEP-688 buffer that pins `pin` (e.g. a StoreBuffer read reference)
    for as long as any consumer (numpy array, bytes view) is alive.

    Zero-copy deserialization hands these to pickle: reconstructed arrays keep
    the PinnedBuffer as their base, so the store refcount is held until the
    arrays are garbage collected — eviction can never reuse live bytes.
    """

    __slots__ = ("_view", "_pin")

    def __init__(self, view: memoryview, pin: Any):
        self._view = view
        self._pin = pin

    def __buffer__(self, flags):
        return memoryview(self._view)


def deserialize(payload, pin: Any = None) -> Any:
    """Deserialize a payload (memoryview => out-of-band buffers are views).

    `pin` is attached to every out-of-band buffer: the returned object graph
    keeps it (and thus the underlying store read reference) alive for as long
    as the zero-copy arrays are.
    """
    view = payload if isinstance(payload, memoryview) else memoryview(payload)
    n_buffers, pickle_len = struct.unpack_from("<IQ", view, 0)
    if n_buffers & _FLAG_FAST:
        return _fast_deserialize(view, pickle_len, pin)
    lens = struct.unpack_from(f"<{n_buffers}Q", view, 12) if n_buffers else ()
    off = 12 + 8 * n_buffers
    pickled = view[off:off + pickle_len]
    off += pickle_len
    bufs = []
    for ln in lens:
        off = _align8(off)
        chunk = view[off:off + ln]
        bufs.append(PinnedBuffer(chunk, pin) if pin is not None else chunk)
        off += ln
    return pickle.loads(pickled, buffers=bufs)


def _fast_deserialize(view: memoryview, meta_len: int, pin: Any):
    import numpy as np

    (raw_len,) = struct.unpack_from("<Q", view, 12)
    off = 20
    meta = pickle.loads(view[off:off + meta_len])
    off = _align8(off + meta_len)
    chunk = view[off:off + raw_len]
    kind, dtype_str, shape = meta
    if kind == _FAST_BYTES:
        # bytes are immutable python objects: one copy at get (same as the
        # pickled path, which also copies in-band bytes).
        return bytes(chunk)
    src = PinnedBuffer(chunk, pin) if pin is not None else chunk
    arr = np.frombuffer(src, dtype=np.dtype(dtype_str)).reshape(shape)
    return arr
