"""Value (de)serialization with zero-copy large buffers.

Reference analog: python/ray/_private/serialization.py + the plasma buffer
protocol. We use cloudpickle protocol 5: large contiguous buffers (numpy
arrays, bytes) are extracted out-of-band and laid out after the pickle stream
inside a single store object, so `get` reconstructs arrays as views over
shared memory without copying.

Object payload layout:
    [u32 n_buffers][u64 pickle_len][u64 len × n_buffers]
    [pickle bytes][pad to 8][buf 0][pad to 8][buf 1] ...
"""

from __future__ import annotations

import pickle
import struct
import sys
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle
import numpy as _np

# Buffers >= this go out-of-band (below it, copying beats the bookkeeping).
OUT_OF_BAND_THRESHOLD = 16 * 1024

# Per-process serialization counters. `pickle` counts SLOW-path value
# serializations (a cloudpickle.dumps of the object graph — the thing the
# compiled-graph steady state must never do to an activation); the fast_*
# counters count header-only encodes whose payload bytes move as raw views.
# Tests snapshot these to prove zero host pickling on pipeline hot paths.
counters: Dict[str, int] = {
    "pickle": 0, "fast_ndarray": 0, "fast_bytes": 0, "fast_device": 0,
    "fast_close": 0, "deserialize_pickle": 0, "deserialize_fast": 0,
}


def counter_snapshot() -> Dict[str, int]:
    return dict(counters)


def counter_delta(since: Dict[str, int]) -> Dict[str, int]:
    return {k: counters[k] - since.get(k, 0) for k in counters}


def _align8(n: int) -> int:
    return (n + 7) & ~7


def serialize_with_refs(value: Any) -> Tuple[List, int, List]:
    """serialize() that also reports the ObjectRefs CONTAINED in the pickled
    graph (the owner pins them so a stored object keeps its inner refs alive
    — the nested-ref leg of the borrower protocol, reference_count.h:418)."""
    from ray_tpu.core import object_ref as ref_mod

    ref_mod.start_ref_collection()
    try:
        segments, total = serialize(value)
    finally:
        contained = ref_mod.finish_ref_collection()
    return segments, total, contained


# Fast-path markers: a top-level contiguous ndarray / bytes skips
# cloudpickle entirely (the dominant put() payloads; cloudpickle's
# reducer_override machinery costs ~0.1 ms/MiB-object). The flag rides the
# header's n_buffers field (real buffer counts never approach 2^31).
_FLAG_FAST = 0x8000_0000
_FAST_NDARRAY = 1
_FAST_BYTES = 2
_FAST_DEVICE = 3  # jax.Array: dlpack host view out, device_put back in
_FAST_CLOSE = 4   # dag.channel CLOSE sentinel: protocol frame, no payload


def _device_array_view(value: Any):
    """If `value` is a jax array we can move as raw bytes, return
    (numpy_host_view, dtype_name); else None.

    dlpack gives a zero-copy host view on the CPU backend (on TPU the
    fallback `np.asarray` is the one unavoidable D2H copy at the transfer
    seam) — either way the payload crosses processes as raw bytes, never
    through pickle. Sharded / multi-device arrays fall back to the pickle
    path, which understands jax's own reducers.
    """
    # sys.modules holds jax from the first `import jax` STATEMENT, before
    # its module body finishes — another thread serializing during that
    # window sees a partial module with no `Array` attribute. No jax array
    # can exist in the process until the import completes, so a missing
    # attribute safely means "not a jax array".
    jax = sys.modules.get("jax")
    jax_array_t = getattr(jax, "Array", None)
    if jax_array_t is None or not isinstance(value, jax_array_t):
        return None
    import numpy as np
    try:
        if not value.is_fully_addressable or len(value.sharding.device_set) != 1:
            return None
        # jax dispatch is async (even on CPU): the buffer behind the dlpack
        # view may still be being written by XLA when the channel memcpy
        # runs. Synchronize first — this is the same fence device_get takes.
        value.block_until_ready()
        try:
            host = np.from_dlpack(value)
        except Exception:
            host = np.asarray(value)  # e.g. bfloat16: numpy lacks the dtype
        if not host.flags.c_contiguous:
            host = np.ascontiguousarray(host)
        return host, str(value.dtype)
    except Exception:
        return None  # deleted/donated buffers etc.: let pickle raise cleanly


def _try_fast_serialize(value: Any) -> Optional[Tuple[List, int]]:
    import numpy as np

    if isinstance(value, np.ndarray):
        # kind 'M'/'m' (datetime64/timedelta64) rejects memoryview; object
        # dtypes and non-contiguous layouts need pickle: all fall back.
        if (value.dtype.hasobject or value.dtype.kind in "Mm"
                or not value.flags.c_contiguous
                or value.nbytes < OUT_OF_BAND_THRESHOLD):
            return None
        meta = pickle.dumps((_FAST_NDARRAY, value.dtype.str, value.shape),
                            protocol=5)
        try:
            raw = memoryview(value).cast("B")
        except (ValueError, TypeError):
            return None  # exotic dtype: pickle path handles it
        counters["fast_ndarray"] += 1
    elif type(value) is bytes:
        # bytes ONLY: bytearray must round-trip as bytearray (mutable),
        # which the pickle path preserves.
        if len(value) < OUT_OF_BAND_THRESHOLD:
            return None
        meta = pickle.dumps((_FAST_BYTES, None, None), protocol=5)
        raw = memoryview(value)
        counters["fast_bytes"] += 1
    else:
        # The channel CLOSE sentinel is protocol, not payload — it rides a
        # zero-byte fast frame so even teardown stays pickle-free (the
        # steady-state counters must not blame CLOSE on the data path).
        # Lazy module check mirrors _device_array_view: if dag.channel was
        # never imported here, value cannot be its sentinel.
        ch_mod = sys.modules.get("ray_tpu.dag.channel")
        if ch_mod is not None and isinstance(value,
                                             getattr(ch_mod, "_CloseToken",
                                                     ())):
            meta = pickle.dumps((_FAST_CLOSE, None, None), protocol=5)
            raw = memoryview(b"")
            counters["fast_close"] += 1
        else:
            dev = _device_array_view(value)
            if dev is None:
                return None
            # No size floor: even a scalar loss must never force
            # device_get + pickle on the pipeline hot path.
            host, dtype_name = dev
            meta = pickle.dumps((_FAST_DEVICE, dtype_name, host.shape),
                                protocol=5)
            raw = memoryview(host).cast("B")
            counters["fast_device"] += 1
    header = struct.pack("<IQ", _FLAG_FAST | 1, len(meta)) + struct.pack(
        "<Q", raw.nbytes)
    segments: List = [header, meta]
    offset = len(header) + len(meta)
    pad = _align8(offset) - offset
    if pad:
        segments.append(b"\x00" * pad)
        offset += pad
    segments.append(raw)
    return segments, offset + raw.nbytes


def serialize(value: Any) -> Tuple[List, int]:
    """Serialize `value` to (segments, total_size).

    `segments` is a list of byte-likes whose concatenation is the object
    payload; callers write them into a store buffer (or b"".join them for
    inline transport) without extra copies of the large buffers.
    """
    fast = _try_fast_serialize(value)
    if fast is not None:
        return fast
    buffers: List[pickle.PickleBuffer] = []

    def buffer_callback(buf: pickle.PickleBuffer) -> bool:
        raw = buf.raw()
        if raw.nbytes >= OUT_OF_BAND_THRESHOLD and raw.contiguous:
            buffers.append(buf)
            return False  # out-of-band
        return True  # in-band

    counters["pickle"] += 1
    pickled = cloudpickle.dumps(value, protocol=5, buffer_callback=buffer_callback)
    raw_views = [b.raw() for b in buffers]
    header = struct.pack("<IQ", len(raw_views), len(pickled)) + b"".join(
        struct.pack("<Q", v.nbytes) for v in raw_views)
    segments: List = [header, pickled]
    offset = len(header) + len(pickled)
    for v in raw_views:
        pad = _align8(offset) - offset
        if pad:
            segments.append(b"\x00" * pad)
            offset += pad
        segments.append(v)
        offset += v.nbytes
    return segments, offset


def write_segments(dst: memoryview, segments: List) -> None:
    off = 0
    for seg in segments:
        n = seg.nbytes if isinstance(seg, memoryview) else len(seg)
        dst[off:off + n] = seg
        off += n


def join_segments(segments: List) -> bytes:
    return b"".join(bytes(s) if isinstance(s, memoryview) else s for s in segments)


class PinnedBuffer(_np.ndarray):
    """An ndarray view over a store buffer that pins `pin` (e.g. a
    StoreBuffer read reference) for as long as any derived array is alive.

    Lifetime subtleties this class exists to get right:

    - numpy view/frombuffer chains COLLAPSE their base to the root plain
      ndarray — a subclass instance (and any attribute on it) is dropped
      from the chain, so the pin must NOT live on the subclass object.
    - jax's zero-copy `device_put` aliases the bytes of a plain ndarray and
      retains that exact object, but does not retain ndarray *subclasses*.

    So the pin is anchored with `weakref.finalize` to the inner plain uint8
    array (`.root`): every numpy view built over this buffer keeps `root`
    as its base, and `root` is also what jax retains after
    `np.frombuffer(pinned, ...)`. The store read reference is released only
    when the last derived array (host or device) is garbage collected —
    eviction can never recycle live bytes. An ndarray subclass (not a
    PEP-688 `__buffer__` class) because buffer-protocol consumers must work
    on every Python we support.
    """

    _pin: Any = None
    root: Any = None

    def __new__(cls, view: memoryview, pin: Any):
        import numpy as np
        import weakref

        root = np.frombuffer(view, dtype=np.uint8)
        if pin is not None:
            # The registry entry holds `pin` until `root` is collected;
            # the callback itself is a no-op — dropping the reference is
            # the release (StoreBuffer.__del__ decrements the store ref).
            weakref.finalize(root, _drop_pin, pin)
        self = root.view(cls)
        self._pin = pin
        self.root = root
        return self


def _drop_pin(pin: Any) -> None:
    """Finalizer target: exists only so weakref.finalize keeps `pin` alive
    exactly as long as the pinned root array."""


def deserialize(payload, pin: Any = None) -> Any:
    """Deserialize a payload (memoryview => out-of-band buffers are views).

    `pin` is attached to every out-of-band buffer: the returned object graph
    keeps it (and thus the underlying store read reference) alive for as long
    as the zero-copy arrays are.
    """
    view = payload if isinstance(payload, memoryview) else memoryview(payload)
    n_buffers, pickle_len = struct.unpack_from("<IQ", view, 0)
    if n_buffers & _FLAG_FAST:
        counters["deserialize_fast"] += 1
        return _fast_deserialize(view, pickle_len, pin)
    counters["deserialize_pickle"] += 1
    lens = struct.unpack_from(f"<{n_buffers}Q", view, 12) if n_buffers else ()
    off = 12 + 8 * n_buffers
    pickled = view[off:off + pickle_len]
    off += pickle_len
    bufs = []
    for ln in lens:
        off = _align8(off)
        chunk = view[off:off + ln]
        bufs.append(PinnedBuffer(chunk, pin) if pin is not None else chunk)
        off += ln
    return pickle.loads(pickled, buffers=bufs)


def _fast_deserialize(view: memoryview, meta_len: int, pin: Any):
    import numpy as np

    (raw_len,) = struct.unpack_from("<Q", view, 12)
    off = 20
    meta = pickle.loads(view[off:off + meta_len])
    off = _align8(off + meta_len)
    chunk = view[off:off + raw_len]
    kind, dtype_str, shape = meta
    if kind == _FAST_BYTES:
        # bytes are immutable python objects: one copy at get (same as the
        # pickled path, which also copies in-band bytes).
        return bytes(chunk)
    if kind == _FAST_DEVICE:
        return _device_from_raw(chunk, dtype_str, shape, pin)
    if kind == _FAST_CLOSE:
        from ray_tpu.dag.channel import CLOSE

        return CLOSE
    src = PinnedBuffer(chunk, pin) if pin is not None else chunk
    arr = np.frombuffer(src, dtype=np.dtype(dtype_str)).reshape(shape)
    return arr


def _resolve_dtype(dtype_name: str):
    import numpy as np
    try:
        return np.dtype(dtype_name)
    except TypeError:
        import ml_dtypes  # bfloat16 and friends register here, not in numpy
        return np.dtype(getattr(ml_dtypes, dtype_name))


def _device_from_raw(chunk: memoryview, dtype_name: str, shape, pin: Any,
                     device=None):
    """Rebuild a jax array from raw bytes: one synchronous host memcpy out
    of the store view, then device_put of the private copy.

    The copy is deliberate, not a missed optimization. Aliasing the store
    bytes (device_put zero-copies page-aligned hosts on the CPU backend)
    ties the ring slot's lifetime to when XLA drops the host reference —
    which happens inside a jax-internal reference cycle, i.e. at an
    arbitrary future gc, not at array death. A bounded channel ring whose
    slots free at gc time stalls its writer; a copy costs ~0.1 ms/MiB and
    makes the slot reusable the moment this returns. On TPU the equivalent
    copy is the H2D DMA at the transfer seam, fenced before the read
    reference is dropped. No pin needs to outlive this call.

    jnp.asarray (not device_put) for the default placement: it ingests the
    host copy synchronously on the calling thread, where device_put's
    async-transfer handoff can burn a scheduling quantum per array on
    small hosts."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    host = np.frombuffer(chunk, dtype=_resolve_dtype(dtype_name)).reshape(shape)
    if device is not None:
        return jax.device_put(np.array(host), device)
    return jnp.asarray(np.array(host))
