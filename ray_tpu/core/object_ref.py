"""ObjectRef: a future-like handle to a task result or put object.

Reference analog: python/ray/_raylet.pyx ObjectRef + ownership in
src/ray/core_worker/reference_count.h (ours records the owner address for
the cross-node pull protocol).
"""

from __future__ import annotations

from typing import Optional


class ObjectRef:
    __slots__ = ("_id", "_owner", "__weakref__")

    def __init__(self, object_id: bytes, owner: Optional[bytes] = None):
        self._id = object_id
        self._owner = owner

    def binary(self) -> bytes:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    @property
    def owner(self) -> Optional[bytes]:
        return self._owner

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __hash__(self):
        return hash(self._id)

    def __repr__(self):
        return f"ObjectRef({self._id.hex()[:16]})"

    def __reduce__(self):
        return (ObjectRef, (self._id, self._owner))

    # Allow `await ref` inside async actors / drivers.
    def __await__(self):
        from ray_tpu.core.worker import global_worker
        worker = global_worker()

        async def _get():
            import asyncio
            loop = asyncio.get_event_loop()
            return await loop.run_in_executor(None, worker.get_one, self, None)

        return _get().__await__()
