"""ObjectRef: a future-like handle to a task result or put object.

Reference analog: python/ray/_raylet.pyx ObjectRef + the distributed
reference counting in src/ray/core_worker/reference_count.h. Each live
ObjectRef pyobject counts toward its process's local reference count for the
underlying object id; when a ref crosses a process boundary (any pickling
path — task args by value, nested containers, actor state), unpickling
registers the receiving process as a BORROWER with the object's owner
(reference_count.h:558-615 borrower protocol). The owner frees the object
everywhere once local refs, borrowers, pins, and containing objects all
drop (delete-on-zero).
"""

from __future__ import annotations

import threading
from typing import Optional, Tuple

# Thread-local collector: ray_tpu.core.serialization activates this while
# pickling a value so the owner learns which refs the serialized bytes
# CONTAIN (nested-ref pinning: a stored object keeps its inner refs alive).
_collect = threading.local()


def start_ref_collection():
    _collect.refs = []


def finish_ref_collection():
    refs = getattr(_collect, "refs", [])
    _collect.refs = None
    return refs


def _deserialize_ref(object_id: bytes, owner: Optional[bytes],
                     owner_addr: Optional[Tuple[str, int]]) -> "ObjectRef":
    """Unpickling entry point: every ref that arrives from another process
    registers with the local worker (borrow bookkeeping)."""
    ref = ObjectRef(object_id, owner=owner, owner_addr=owner_addr)
    try:
        from ray_tpu.core import worker as worker_mod

        if worker_mod.is_initialized():
            worker_mod.global_worker().register_ref(ref, arrived=True)
    except Exception:
        pass
    return ref


class ObjectRef:
    __slots__ = ("_id", "_owner", "_owner_addr", "_registered", "__weakref__")

    def __init__(self, object_id: bytes, owner: Optional[bytes] = None,
                 owner_addr: Optional[Tuple[str, int]] = None):
        self._id = object_id
        self._owner = owner
        self._owner_addr = tuple(owner_addr) if owner_addr else None
        self._registered = False

    def binary(self) -> bytes:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    @property
    def owner(self) -> Optional[bytes]:
        return self._owner

    @property
    def owner_addr(self) -> Optional[Tuple[str, int]]:
        return self._owner_addr

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __hash__(self):
        return hash(self._id)

    def __repr__(self):
        return f"ObjectRef({self._id.hex()[:16]})"

    def __reduce__(self):
        refs = getattr(_collect, "refs", None)
        if refs is not None:
            refs.append(self)
        return (_deserialize_ref, (self._id, self._owner, self._owner_addr))

    def __del__(self):
        if not self._registered:
            return
        try:
            from ray_tpu.core import worker as worker_mod

            w = worker_mod._global_worker
            if w is not None:
                w.ref_dropped(self._id)
        except Exception:
            pass

    # Allow `await ref` inside async actors / drivers.
    def __await__(self):
        from ray_tpu.core.worker import global_worker
        worker = global_worker()

        async def _get():
            import asyncio
            loop = asyncio.get_event_loop()
            return await loop.run_in_executor(None, worker.get_one, self, None)

        return _get().__await__()
