"""Placement group public API.

Reference analog: python/ray/util/placement_group.py (placement_group(),
PlacementGroup.ready/wait, placement_group_table) and
python/ray/util/scheduling_strategies.py:15 PlacementGroupSchedulingStrategy.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu.core import worker as worker_mod
from ray_tpu.core.exceptions import PlacementGroupError
from ray_tpu.utils.ids import PlacementGroupID

PACK = "PACK"
SPREAD = "SPREAD"
STRICT_PACK = "STRICT_PACK"
STRICT_SPREAD = "STRICT_SPREAD"


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]]):
        self.id = pg_id
        self.bundles = bundles

    @property
    def bundle_count(self) -> int:
        return len(self.bundles)

    def table(self) -> dict:
        core = worker_mod.global_worker()
        return core.io.run(core.gcs.call("get_placement_group", pg_id=self.id.binary()))

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout_seconds
        while time.monotonic() < deadline:
            info = self.table()
            if info.get("state") == "CREATED":
                return True
            if info.get("state") == "REMOVED":
                return False
            time.sleep(0.05)
        return False

    def ready(self):
        """Returns an ObjectRef-like blocking helper: `pg.wait()` preferred."""
        return self

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundles))


def placement_group(bundles: List[Dict[str, float]], strategy: str = PACK,
                    name: str = "") -> PlacementGroup:
    if not bundles:
        raise ValueError("placement group needs at least one bundle")
    for b in bundles:
        if not b or any(v < 0 for v in b.values()):
            raise ValueError(f"invalid bundle {b}")
    core = worker_mod.global_worker()
    pg_id = PlacementGroupID.generate()
    reply = core.io.run(core.gcs.call(
        "create_placement_group", pg_id=pg_id.binary(),
        bundles=[{k: float(v) for k, v in b.items()} for b in bundles],
        strategy=strategy, name=name))
    if not reply.get("ok"):
        raise PlacementGroupError(reply.get("error", "creation failed"))
    return PlacementGroup(pg_id, bundles)


def remove_placement_group(pg: PlacementGroup):
    core = worker_mod.global_worker()
    core.io.run(core.gcs.call("remove_placement_group", pg_id=pg.id.binary()))


def placement_group_table() -> List[dict]:
    core = worker_mod.global_worker()
    return core.io.run(core.gcs.call("list_placement_groups"))
