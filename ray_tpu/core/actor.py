"""Actor API: ActorClass and ActorHandle.

Reference analog: python/ray/actor.py (ActorClass._remote:893 -> GCS-mediated
creation; ActorHandle method submission via the direct actor transport).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.core import worker as worker_mod
from ray_tpu.core.task_spec import ActorSpec
from ray_tpu.runtime.scheduling import PlacementGroupStrategy
from ray_tpu.runtime_env import prepare_runtime_env
from ray_tpu.utils.ids import ActorID


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str, num_returns: int = 1):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns

    def options(self, num_returns: int = 1):
        return ActorMethod(self._handle, self._method_name, num_returns)

    def remote(self, *args, **kwargs):
        core = worker_mod.global_worker()
        refs = core.submit_actor_task(
            self._handle._actor_id, self._method_name, args, kwargs,
            num_returns=self._num_returns,
            name=f"{self._handle._class_name}.{self._method_name}",
            max_task_retries=self._handle._max_task_retries)
        if self._num_returns in (1, "streaming"):
            return refs[0]
        return refs

    def bind(self, *args, **kwargs):
        """Build a DAG node for this method call (ray_tpu.dag)."""
        from ray_tpu.dag.node import ClassMethodNode

        return ClassMethodNode(self._handle, self._method_name, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError("Actor methods cannot be called directly; use .remote()")


class ActorHandle:
    def __init__(self, actor_id: bytes, class_name: str, max_task_retries: int = 0):
        self._actor_id = actor_id
        self._class_name = class_name
        self._max_task_retries = max_task_retries

    def __getattr__(self, item):
        if item.startswith("_"):
            raise AttributeError(item)
        return ActorMethod(self, item)

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:12]})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_name, self._max_task_retries))


class ActorClass:
    # Default num_cpus=0 matches the reference: an actor's lifetime holds no
    # CPU (only explicit num_cpus/num_tpus reservations pin resources).
    def __init__(self, cls, *, num_cpus: float = 0.0, num_tpus: float = 0.0,
                 resources: Optional[Dict[str, float]] = None, max_restarts: int = 0,
                 max_task_retries: int = 0, max_concurrency: int = 1,
                 name: Optional[str] = None, namespace: str = "default",
                 lifetime: Optional[str] = None, scheduling_strategy=None,
                 runtime_env: Optional[dict] = None):
        self._cls = cls
        self._num_cpus = num_cpus
        self._num_tpus = num_tpus
        self._resources = dict(resources or {})
        self._max_restarts = max_restarts
        self._max_task_retries = max_task_retries
        self._max_concurrency = max_concurrency
        self._name = name
        self._namespace = namespace
        self._lifetime = lifetime
        self._scheduling_strategy = scheduling_strategy
        self._runtime_env = runtime_env

    def options(self, **overrides) -> "ActorClass":
        kw = dict(num_cpus=self._num_cpus, num_tpus=self._num_tpus,
                  resources=dict(self._resources), max_restarts=self._max_restarts,
                  max_task_retries=self._max_task_retries,
                  max_concurrency=self._max_concurrency, name=self._name,
                  namespace=self._namespace, lifetime=self._lifetime,
                  scheduling_strategy=self._scheduling_strategy,
                  runtime_env=self._runtime_env)
        kw.update(overrides)
        return ActorClass(self._cls, **kw)

    def _resource_demand(self) -> Dict[str, float]:
        demand = dict(self._resources)
        if self._num_cpus:
            demand["CPU"] = float(self._num_cpus)
        if self._num_tpus:
            demand["TPU"] = float(self._num_tpus)
        return demand

    def remote(self, *args, **kwargs) -> ActorHandle:
        core = worker_mod.global_worker()
        class_id = core.register_class(self._cls)
        ser_args, names, pins = core.serialize_args(args, kwargs)
        core.pin_args(pins)
        pg_id, bundle_index = None, -1
        strategy = self._scheduling_strategy
        if isinstance(strategy, PlacementGroupStrategy):
            pg_id = strategy.placement_group.id.binary()
            bundle_index = strategy.bundle_index
        spec = ActorSpec(
            actor_id=ActorID.generate().binary(),
            class_id=class_id, name=self._name,
            class_name=self._cls.__name__, args=ser_args, kwarg_names=names,
            resources=self._resource_demand(), max_restarts=self._max_restarts,
            max_task_retries=self._max_task_retries,
            max_concurrency=self._max_concurrency,
            scheduling_strategy=strategy, placement_group_id=pg_id,
            placement_group_bundle_index=bundle_index, namespace=self._namespace,
            runtime_env=prepare_runtime_env(
                core, core.merge_job_env(self._runtime_env)))
        try:
            reply = core.create_actor(spec)
        finally:
            core.unpin_args(pins)
        if not reply.get("ok"):
            raise RuntimeError(f"actor creation failed: {reply.get('error')}")
        return ActorHandle(spec.actor_id, self._cls.__name__, self._max_task_retries)

    def __call__(self, *args, **kwargs):
        raise TypeError(f"Actor class {self._cls.__name__} cannot be instantiated "
                        "directly; use .remote()")


def get_actor(name: str, namespace: str = "default") -> ActorHandle:
    core = worker_mod.global_worker()
    info = core.get_actor_info(name=name, namespace=namespace)
    if not info.get("found") or info["state"] == "DEAD":
        raise ValueError(f"no live actor named {name!r}")
    return ActorHandle(info["actor_id"], info["class_name"])
