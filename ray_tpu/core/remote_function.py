"""@ray_tpu.remote functions.

Reference analog: python/ray/remote_function.py (RemoteFunction._remote:303).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from ray_tpu.core import worker as worker_mod
from ray_tpu.runtime.scheduling import PlacementGroupStrategy

DEFAULT_MAX_RETRIES = 3


class RemoteFunction:
    def __init__(self, fn, *, num_returns: int = 1, num_cpus: float = 1.0,
                 num_tpus: float = 0.0, resources: Optional[Dict[str, float]] = None,
                 max_retries: int = DEFAULT_MAX_RETRIES, scheduling_strategy=None,
                 runtime_env: Optional[dict] = None):
        self._fn = fn
        self._num_returns = num_returns
        self._num_cpus = num_cpus
        self._num_tpus = num_tpus
        self._resources = dict(resources or {})
        self._max_retries = max_retries
        self._scheduling_strategy = scheduling_strategy
        self._runtime_env = runtime_env
        functools.update_wrapper(self, fn)

    def options(self, **overrides) -> "RemoteFunction":
        kw = dict(
            num_returns=self._num_returns, num_cpus=self._num_cpus,
            num_tpus=self._num_tpus, resources=dict(self._resources),
            max_retries=self._max_retries,
            scheduling_strategy=self._scheduling_strategy,
            runtime_env=self._runtime_env)
        kw.update(overrides)
        return RemoteFunction(self._fn, **kw)

    def _resource_demand(self) -> Dict[str, float]:
        demand = dict(self._resources)
        if self._num_cpus:
            demand["CPU"] = float(self._num_cpus)
        if self._num_tpus:
            demand["TPU"] = float(self._num_tpus)
        return demand

    def remote(self, *args, **kwargs):
        core = worker_mod.global_worker()
        pg_id, bundle_index = None, -1
        strategy = self._scheduling_strategy
        if isinstance(strategy, PlacementGroupStrategy):
            pg_id = strategy.placement_group.id.binary()
            bundle_index = strategy.bundle_index
        refs = core.submit_task(
            self._fn, args, kwargs,
            name=getattr(self._fn, "__qualname__", str(self._fn)),
            num_returns=self._num_returns,
            resources=self._resource_demand(),
            max_retries=self._max_retries,
            scheduling_strategy=strategy,
            placement_group_id=pg_id, bundle_index=bundle_index,
            runtime_env=self._runtime_env)
        if self._num_returns in (1, "streaming"):
            return refs[0]
        return refs

    def bind(self, *args, **kwargs):
        """Build a DAG node for this task call (ray_tpu.dag)."""
        from ray_tpu.dag.node import FunctionNode

        return FunctionNode(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{getattr(self._fn, '__name__', '?')}' cannot be called "
            "directly; use .remote() (or access the original via __wrapped__).")
