"""Exception types surfaced by the public API.

Reference analog: python/ray/exceptions.py.
"""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all ray_tpu errors."""


class TaskError(RayTpuError):
    """A task raised an exception; re-raised at `get` with remote traceback."""

    def __init__(self, function_name: str, traceback_str: str,
                 cause: BaseException | None = None):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(f"task {function_name} failed:\n{traceback_str}")

    def __reduce__(self):
        # Exceptions with non-(args)-shaped __init__ need explicit reduce to
        # survive the RPC pickle path.
        return (type(self), (self.function_name, self.traceback_str, self.cause))


class ActorError(RayTpuError):
    pass


class ActorDiedError(ActorError):
    def __init__(self, actor_id_hex: str, reason: str = ""):
        self.actor_id_hex = actor_id_hex
        self.reason = reason
        super().__init__(f"actor {actor_id_hex[:12]} died: {reason}")

    @property
    def cause(self) -> str:
        """Typed death cause (CAUSE_PREEMPTION when the hosting node was
        drained/preempted with notice, CAUSE_CRASH otherwise)."""
        return death_cause(self.reason)

    def __reduce__(self):
        return (type(self), (self.actor_id_hex, self.reason))


class ActorUnavailableError(ActorError):
    pass


# Marker embedded in GCS death reasons for nodes/actors lost to a slice
# failure domain; `actor_death_error` keys off it so the caller-side error
# type survives the string-shaped death_reason plumbing.
TPU_SLICE_LOST_MARKER = "TpuSliceLost"

# Marker embedded in GCS death reasons for nodes that died at the end of a
# drain window (spot/preemptible retirement with advance notice). Callers
# use it — via `death_cause` — to distinguish a *planned* capacity loss
# (retry freely, do not consume retry budgets) from a surprise crash.
NODE_PREEMPTED_MARKER = "NodePreempted"

# Typed death causes derivable from a string-shaped death reason.
CAUSE_PREEMPTION = "preemption"
CAUSE_CRASH = "crash"


def death_cause(reason: "str | None") -> str:
    """Classify a death reason string into a typed cause. The markers ride
    inside the reason (the reason plumbing through GCS pubsub, actor death
    records, and wire messages is string-shaped — same trick as
    TPU_SLICE_LOST_MARKER)."""
    if NODE_PREEMPTED_MARKER in (reason or ""):
        return CAUSE_PREEMPTION
    return CAUSE_CRASH


class NodeDiedError(RayTpuError):
    """A node left the cluster. `cause` distinguishes a graceful
    drain/preemption (CAUSE_PREEMPTION — the death was announced in
    advance and is infinitely retryable) from a crash (CAUSE_CRASH)."""

    def __init__(self, node_id_hex: str, reason: str = ""):
        self.node_id_hex = node_id_hex
        self.reason = reason
        super().__init__(f"node {node_id_hex[:12]} died: {reason}")

    @property
    def cause(self) -> str:
        return death_cause(self.reason)

    def __reduce__(self):
        return (type(self), (self.node_id_hex, self.reason))


class TpuSliceLostError(ActorDiedError):
    """An ICI slice failure domain was lost: one host of a multi-host TPU
    slice died, so the GCS fate-shared its siblings and everything pinned
    to the slice (actors, tasks, in-flight collectives) fails immediately
    rather than running against a broken ICI domain.

    Subclasses ActorDiedError so existing actor-failure handling keeps
    working; Train's controller additionally treats it as a gang-restart
    signal (train/elastic.py is_gang_failure)."""

    def __init__(self, actor_id_hex: str, reason: str = ""):
        super().__init__(actor_id_hex, reason)


def actor_death_error(actor_id_hex: str, reason: str) -> ActorDiedError:
    """Typed error for an actor death reason reported by the GCS: deaths
    caused by a lost slice surface as TpuSliceLostError (fast gang-restart
    signal), everything else as plain ActorDiedError."""
    if TPU_SLICE_LOST_MARKER in (reason or ""):
        return TpuSliceLostError(actor_id_hex, reason)
    return ActorDiedError(actor_id_hex, reason)


class WeightSyncError(RayTpuError):
    """A weight hot-swap payload failed validation against the loaded model
    (pytree structure, leaf shape, or dtype mismatch — or the engine was
    mid-generation). Raised by `LLMEngine.update_weights` BEFORE any state
    is touched, so the next prefill never runs against a half-applied tree;
    the RLHF weight-sync path surfaces it to the trainer instead of the
    failure appearing deep inside paged attention."""


class CollectiveAbortError(RayTpuError):
    """A blocking collective op was aborted — the group's abort flag was
    set (locally, via the GCS KV, or by the peer-liveness watchdog after a
    rank stopped heartbeating) — instead of hanging to the socket timeout."""

    def __init__(self, group_name: str, reason: str = ""):
        self.group_name = group_name
        self.reason = reason
        super().__init__(
            f"collective group {group_name!r} aborted: {reason}")

    def __reduce__(self):
        return (type(self), (self.group_name, self.reason))


class TaskCancelledError(RayTpuError):
    """The task producing this object was cancelled via ray_tpu.cancel()
    (reference analog: ray.exceptions.TaskCancelledError). Raised by
    get() on the task's return refs."""


class WorkerCrashedError(RayTpuError):
    """The worker executing a task died (e.g. OOM-killed, segfault)."""


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class ObjectLostError(RayTpuError):
    """An object's data is gone everywhere. `oid` (when known) lets the
    owner's submitter reconstruct the exact lost dependency recursively
    (object_recovery_manager.h:38 analog)."""

    def __init__(self, message: str, oid: "bytes | None" = None,
                 cause: "str | None" = None):
        super().__init__(message)
        self.oid = oid
        # Explicit cause wins; otherwise derive from the message (drain
        # paths embed NODE_PREEMPTED_MARKER in it).
        self.cause = cause or death_cause(message)

    def __reduce__(self):
        # Default Exception pickling drops kwargs; keep oid and cause across
        # the wire (the recovery path reads them on the submitting side).
        return (type(self),
                (self.args[0] if self.args else "", self.oid, self.cause))


class RuntimeEnvSetupError(RayTpuError):
    pass


class PlacementGroupError(RayTpuError):
    pass
