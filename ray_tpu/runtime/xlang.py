"""Cross-language wire values + envelope (the non-pickle RPC dialect).

Reference analog: src/ray/common/ray_object.h + the msgpack-based
cross-language serialization used by the Java/C++ workers
(src/ray/core_worker/transport/ — cross-language args must be
language-neutral, never pickled). Our Python wire frames carry pickled
envelopes; a C++ (or any non-Python) peer instead sends frames tagged
with the `RTX` magic whose body is this self-describing binary encoding.
Transport auth (mutual HMAC handshake + per-frame MAC, runtime/rpc.py)
is identical for both dialects — the MAC covers the body bytes before
either decoder runs.

XValue encoding (one tag byte, little-endian everywhere):

  0x00 None        --
  0x01 False       --
  0x02 True        --
  0x03 int         8B signed
  0x04 float       8B IEEE-754 double
  0x05 str         u32 len + utf-8
  0x06 bytes       u32 len + raw
  0x07 list        u32 count + XValue*
  0x08 dict        u32 count + (u32 keylen + utf-8 key + XValue)*
  0x09 ndarray     u8 dtypelen + ascii dtype ("<f4"...), u8 ndim,
                   u64*ndim dims, raw C-order buffer

Envelope (body of one RTX frame):

  u8 kind | u8 has_msg_id | u64 msg_id | u16 methodlen + utf-8 method |
  XValue data (dict for requests; any XValue for replies)

Anything not representable raises XEncodeError — cross-language calls
are restricted to this vocabulary by design (the pickle escape hatch is
exactly what a non-Python peer must not need).
"""

from __future__ import annotations

import struct
from typing import Any, Tuple

_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")
_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")

T_NONE, T_FALSE, T_TRUE, T_INT, T_FLOAT = 0, 1, 2, 3, 4
T_STR, T_BYTES, T_LIST, T_DICT, T_NDARRAY = 5, 6, 7, 8, 9


class XEncodeError(TypeError):
    pass


class XDecodeError(ValueError):
    pass


def encode_value(v: Any, out: bytearray) -> None:
    if v is None:
        out.append(T_NONE)
    elif v is False:
        out.append(T_FALSE)
    elif v is True:
        out.append(T_TRUE)
    elif isinstance(v, int):
        out.append(T_INT)
        try:
            out += _I64.pack(v)
        except struct.error:
            raise XEncodeError(f"int {v} outside the wire's int64 range")
    elif isinstance(v, float):
        out.append(T_FLOAT)
        out += _F64.pack(v)
    elif isinstance(v, str):
        b = v.encode("utf-8")
        out.append(T_STR)
        out += _U32.pack(len(b))
        out += b
    elif isinstance(v, (bytes, bytearray, memoryview)):
        b = bytes(v)
        out.append(T_BYTES)
        out += _U32.pack(len(b))
        out += b
    elif isinstance(v, (list, tuple)):
        out.append(T_LIST)
        out += _U32.pack(len(v))
        for item in v:
            encode_value(item, out)
    elif isinstance(v, dict):
        out.append(T_DICT)
        out += _U32.pack(len(v))
        for k, item in v.items():
            if not isinstance(k, str):
                raise XEncodeError(
                    f"xlang dict keys must be str, got {type(k).__name__}")
            kb = k.encode("utf-8")
            out += _U32.pack(len(kb))
            out += kb
            encode_value(item, out)
    else:
        import numpy as np

        if isinstance(v, np.ndarray):
            # ascontiguousarray promotes 0-d to 1-d; keep the true shape.
            arr = np.ascontiguousarray(v).reshape(v.shape)
            dt = arr.dtype.str.encode("ascii")  # e.g. b"<f4"
            out.append(T_NDARRAY)
            out.append(len(dt))
            out += dt
            out.append(arr.ndim)
            for d in arr.shape:
                out += _U64.pack(d)
            out += arr.tobytes()
        elif isinstance(v, (np.integer,)):
            encode_value(int(v), out)
        elif isinstance(v, (np.floating,)):
            encode_value(float(v), out)
        elif isinstance(v, (np.bool_,)):
            encode_value(bool(v), out)
        else:
            raise XEncodeError(
                f"type {type(v).__name__} is not cross-language "
                "representable (allowed: None/bool/int/float/str/bytes/"
                "list/dict/ndarray)")


def encode(v: Any) -> bytes:
    out = bytearray()
    encode_value(v, out)
    return bytes(out)


def _decode(buf: memoryview, pos: int) -> Tuple[Any, int]:
    tag = buf[pos]
    pos += 1
    if tag == T_NONE:
        return None, pos
    if tag == T_FALSE:
        return False, pos
    if tag == T_TRUE:
        return True, pos
    if tag == T_INT:
        return _I64.unpack_from(buf, pos)[0], pos + 8
    if tag == T_FLOAT:
        return _F64.unpack_from(buf, pos)[0], pos + 8
    if tag == T_STR:
        n = _U32.unpack_from(buf, pos)[0]
        pos += 4
        return bytes(buf[pos:pos + n]).decode("utf-8"), pos + n
    if tag == T_BYTES:
        n = _U32.unpack_from(buf, pos)[0]
        pos += 4
        return bytes(buf[pos:pos + n]), pos + n
    if tag == T_LIST:
        n = _U32.unpack_from(buf, pos)[0]
        pos += 4
        items = []
        for _ in range(n):
            item, pos = _decode(buf, pos)
            items.append(item)
        return items, pos
    if tag == T_DICT:
        n = _U32.unpack_from(buf, pos)[0]
        pos += 4
        d = {}
        for _ in range(n):
            kl = _U32.unpack_from(buf, pos)[0]
            pos += 4
            k = bytes(buf[pos:pos + kl]).decode("utf-8")
            pos += kl
            d[k], pos = _decode(buf, pos)
        return d, pos
    if tag == T_NDARRAY:
        import numpy as np

        dl = buf[pos]
        pos += 1
        dt = np.dtype(bytes(buf[pos:pos + dl]).decode("ascii"))
        pos += dl
        ndim = buf[pos]
        pos += 1
        shape = []
        for _ in range(ndim):
            shape.append(_U64.unpack_from(buf, pos)[0])
            pos += 8
        nbytes = dt.itemsize
        for d in shape:
            nbytes *= d
        arr = np.frombuffer(
            bytes(buf[pos:pos + nbytes]), dtype=dt).reshape(shape)
        return arr, pos + nbytes
    raise XDecodeError(f"unknown xvalue tag {tag}")


def decode(data) -> Any:
    v, pos = _decode(memoryview(data), 0)
    if pos != len(data):
        raise XDecodeError(f"trailing bytes after xvalue ({len(data)-pos})")
    return v


# ------------------------------------------------------------- envelope

def encode_envelope(kind: int, msg_id, method: str, data: Any) -> bytes:
    mb = method.encode("utf-8")
    out = bytearray()
    out.append(kind)
    out.append(0 if msg_id is None else 1)
    out += _U64.pack(msg_id or 0)
    out += _U16.pack(len(mb))
    out += mb
    encode_value(data, out)
    return bytes(out)


def decode_envelope(body) -> Tuple[int, Any, str, Any]:
    buf = memoryview(body)
    kind = buf[0]
    has_id = buf[1]
    msg_id = _U64.unpack_from(buf, 2)[0]
    ml = _U16.unpack_from(buf, 10)[0]
    method = bytes(buf[12:12 + ml]).decode("utf-8")
    data, pos = _decode(buf, 12 + ml)
    if pos != len(buf):
        raise XDecodeError("trailing bytes after envelope")
    return kind, (msg_id if has_id else None), method, data


def sanitize_reply(v: Any) -> Any:
    """Normalize a handler reply for the xlang wire: exceptions become
    strings (the error-reply convention), containers recurse, numpy
    scalars unwrap. Anything else non-representable is left as-is so the
    subsequent encode raises XEncodeError — the transport then reports a
    structured error instead of silently repr()-corrupting a value."""
    if v is None or isinstance(v, (bool, int, float, str, bytes)):
        return v
    if isinstance(v, (list, tuple)):
        return [sanitize_reply(x) for x in v]
    if isinstance(v, dict):
        return {str(k): sanitize_reply(x) for k, x in v.items()}
    import numpy as np

    if isinstance(v, (np.integer, np.floating, np.bool_)):
        return v.item()
    if isinstance(v, BaseException):
        return f"{type(v).__name__}: {v}"
    return v
