"""Typed wire schema: versioned message structs over the RPC frame.

Reference analog: src/ray/protobuf/ (21 .proto files) — the property that
matters is CROSS-VERSION MESSAGE EVOLUTION: a v(N+1) process can add
fields without breaking v(N) peers, and decoding never depends on both
sides agreeing on the full field set. The pickle wire gave structure no
schema; this module adds protobuf's evolution rules without a compiler:

  * messages declare numbered, typed fields (number = wire identity;
    renames are free, numbers are forever);
  * encoding is field-tagged TLV — unknown field numbers are SKIPPED on
    decode (forward compatibility: old readers tolerate new writers);
  * absent fields decode to their declared defaults (backward
    compatibility: new readers tolerate old writers);
  * nested messages, lists, and string-keyed maps compose; ANY is the
    audited pickle escape hatch for payloads that are genuinely code
    (task args), not schema.

Frame integration: an encoded message travels as one `bytes` value inside
the existing authenticated frame (runtime/rpc.py adds transport auth/MAC;
this layer adds structure). Handlers opt in per message type.

Wire format per field:  [u32 field_no << 3 | wire_type][u32 length][payload]
Message = concatenation of encoded fields, any order.
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, Dict, List, Optional, Tuple, Type

# wire types (3 bits)
_WT_VARBYTES = 0   # length-delimited scalar payload (int/float/str/bytes/bool)
_WT_MSG = 1        # nested message
_WT_LIST = 2       # repeated inner type
_WT_MAP = 3        # string-keyed map of inner type
_WT_ANY = 4        # pickled (escape hatch)

_TAG = struct.Struct("<I")
_LEN = struct.Struct("<I")


class FieldType:
    """Scalar/composite field type descriptors."""

    def __init__(self, kind: str, inner: Any = None):
        self.kind = kind
        self.inner = inner

    def __repr__(self):
        return f"FieldType({self.kind})"


INT = FieldType("int")
FLOAT = FieldType("float")
BOOL = FieldType("bool")
STR = FieldType("str")
BYTES = FieldType("bytes")
ANY = FieldType("any")


def LIST(inner) -> FieldType:  # noqa: N802 (schema DSL)
    return FieldType("list", inner)


def MAP(inner) -> FieldType:  # noqa: N802
    return FieldType("map", inner)


def MSG(msg_cls) -> FieldType:  # noqa: N802
    return FieldType("msg", msg_cls)


class Field:
    __slots__ = ("number", "type", "default")

    def __init__(self, number: int, ftype: FieldType, default: Any = None):
        if not 1 <= number < (1 << 29):
            raise ValueError(f"field number out of range: {number}")
        self.number = number
        self.type = ftype
        self.default = default


def _default_for(f: Field):
    if f.default is not None:
        return f.default
    return {"int": 0, "float": 0.0, "bool": False, "str": "",
            "bytes": b"", "list": None, "map": None, "msg": None,
            "any": None}[f.type.kind]


def _wire_type(ftype: FieldType) -> int:
    return {"int": _WT_VARBYTES, "float": _WT_VARBYTES,
            "bool": _WT_VARBYTES, "str": _WT_VARBYTES,
            "bytes": _WT_VARBYTES, "msg": _WT_MSG, "list": _WT_LIST,
            "map": _WT_MAP, "any": _WT_ANY}[ftype.kind]


def _payload_encoder(ftype: FieldType):
    """Closure encoding one field's payload — kind dispatch resolved at
    class-definition time, not per call."""
    k = ftype.kind
    if k == "int":
        return struct.Struct("<q").pack
    if k == "float":
        return struct.Struct("<d").pack
    if k == "bool":
        return lambda v: b"\x01" if v else b"\x00"
    if k == "str":
        return str.encode
    if k == "bytes":
        return bytes
    if k == "any":
        return lambda v: pickle.dumps(v, protocol=5)
    if k == "msg":
        return lambda v: v.encode()
    if k == "list":
        inner = _payload_encoder(ftype.inner)

        def enc_list(value):
            parts = []
            for item in value:
                p = inner(item)
                parts.append(_LEN.pack(len(p)))
                parts.append(p)
            return b"".join(parts)

        return enc_list
    if k == "map":
        inner = _payload_encoder(ftype.inner)

        def enc_map(value):
            parts = []
            for key, item in value.items():
                kb = key.encode()
                p = inner(item)
                parts.append(_LEN.pack(len(kb)))
                parts.append(kb)
                parts.append(_LEN.pack(len(p)))
                parts.append(p)
            return b"".join(parts)

        return enc_map
    raise TypeError(f"unknown field kind {k!r}")


def _payload_decoder(ftype: FieldType):
    """Closure decoding one field's payload (see _payload_encoder)."""
    k = ftype.kind
    if k == "int":
        unpack = struct.Struct("<q").unpack
        return lambda p: unpack(p)[0]
    if k == "float":
        unpack = struct.Struct("<d").unpack
        return lambda p: unpack(p)[0]
    if k == "bool":
        return lambda p: bytes(p) != b"\x00"
    if k == "str":
        return lambda p: str(p, "utf-8")
    if k == "bytes":
        return bytes
    if k == "any":
        return pickle.loads
    if k == "msg":
        return ftype.inner.decode
    if k == "list":
        inner = _payload_decoder(ftype.inner)

        def dec_list(payload):
            out = []
            off = 0
            n = len(payload)
            while off < n:
                (ln,) = _LEN.unpack_from(payload, off)
                off += 4
                out.append(inner(payload[off:off + ln]))
                off += ln
            return out

        return dec_list
    if k == "map":
        inner = _payload_decoder(ftype.inner)

        def dec_map(payload):
            out = {}
            off = 0
            n = len(payload)
            while off < n:
                (kl,) = _LEN.unpack_from(payload, off)
                off += 4
                key = str(payload[off:off + kl], "utf-8")
                off += kl
                (vl,) = _LEN.unpack_from(payload, off)
                off += 4
                out[key] = inner(payload[off:off + vl])
                off += vl
            return out

        return dec_map
    raise TypeError(f"unknown field kind {k!r}")


class MessageMeta(type):
    def __new__(mcls, name, bases, ns):
        cls = super().__new__(mcls, name, bases, ns)
        fields: Dict[str, Field] = {}
        for base in bases:
            fields.update(getattr(base, "_fields", {}))
        numbers = {f.number for f in fields.values()}
        for key, val in ns.items():
            if isinstance(val, Field):
                if val.number in numbers:
                    raise TypeError(
                        f"{name}.{key}: duplicate field number {val.number}")
                numbers.add(val.number)
                fields[key] = val
        cls._fields = fields
        cls._by_number = {f.number: (n, f) for n, f in fields.items()}
        # Precompiled per-field codecs, resolved ONCE at class definition:
        # string kind-dispatch per field per call costs ~50us per TaskSpec
        # on the actor-call hot path (measured ~20% of call throughput).
        cls._encoders = tuple(
            (n, _TAG.pack((f.number << 3) | _wire_type(f.type)),
             _payload_encoder(f.type))
            for n, f in fields.items())
        cls._decoders = {
            f.number: (n, _wire_type(f.type), _payload_decoder(f.type))
            for n, f in fields.items()}
        cls._scalar_defaults = {
            n: _default_for(f) for n, f in fields.items()
            if f.type.kind not in ("list", "map") or f.default is not None}
        cls._container_defaults = tuple(
            (n, list if f.type.kind == "list" else dict)
            for n, f in fields.items()
            if f.type.kind in ("list", "map") and f.default is None)
        return cls


class Message(metaclass=MessageMeta):
    """Base class: subclass with `Field` class attributes.

    >>> class Heartbeat(Message):
    ...     node_id = Field(1, BYTES)
    ...     available = Field(2, MAP(FLOAT))
    """

    _fields: Dict[str, Field] = {}
    _by_number: Dict[int, Tuple[str, Field]] = {}

    def __init__(self, **kwargs):
        d = self.__dict__
        d.update(self._scalar_defaults)
        for name, factory in self._container_defaults:
            d[name] = factory()  # fresh containers per instance
        for name, value in kwargs.items():
            if name not in self._fields:
                raise TypeError(
                    f"{type(self).__name__} has no field {name!r}")
            d[name] = value

    def __eq__(self, other):
        return (type(self) is type(other)
                and all(getattr(self, n) == getattr(other, n)
                        for n in self._fields))

    def __repr__(self):
        body = ", ".join(f"{n}={getattr(self, n)!r}" for n in self._fields)
        return f"{type(self).__name__}({body})"

    # -- encode ------------------------------------------------------------

    def encode(self) -> bytes:
        out: List[bytes] = []
        d = self.__dict__
        for name, tag, enc in self._encoders:
            value = d[name]
            if value is None:
                continue
            payload = enc(value)
            out.append(tag)
            out.append(_LEN.pack(len(payload)))
            out.append(payload)
        return b"".join(out)

    @classmethod
    def decode(cls, data) -> "Message":
        view = memoryview(data)
        msg = cls()
        d = msg.__dict__
        decoders = cls._decoders
        off = 0
        end = len(view)
        while off < end:
            (tag,) = _TAG.unpack_from(view, off)
            (length,) = _LEN.unpack_from(view, off + 4)
            off += 8
            payload = view[off:off + length]
            off += length
            entry = decoders.get(tag >> 3)
            if entry is None:
                continue  # unknown field from a newer writer: SKIP
            name, wt, dec = entry
            if tag & 7 != wt:
                continue  # wire-type mismatch across versions: default
            try:
                d[name] = dec(payload)
            except Exception:
                # Malformed payload across versions: keep the default
                # rather than failing the whole message.
                continue
        return msg


def _encode_scalar(ftype: FieldType, value) -> bytes:
    k = ftype.kind
    if k == "int":
        return struct.pack("<q", value)
    if k == "float":
        return struct.pack("<d", value)
    if k == "bool":
        return b"\x01" if value else b"\x00"
    if k == "str":
        return value.encode()
    if k == "bytes":
        return bytes(value)
    raise TypeError(f"not a scalar: {k}")


def _decode_scalar(ftype: FieldType, payload: memoryview):
    k = ftype.kind
    if k == "int":
        return struct.unpack("<q", payload)[0]
    if k == "float":
        return struct.unpack("<d", payload)[0]
    if k == "bool":
        return payload != b"\x00" and bytes(payload) != b"\x00"
    if k == "str":
        return str(payload, "utf-8")
    if k == "bytes":
        return bytes(payload)
    raise TypeError(f"not a scalar: {k}")


def _encode_payload(ftype: FieldType, value) -> bytes:
    k = ftype.kind
    if k == "msg":
        return value.encode()
    if k == "list":
        parts = []
        for item in value:
            p = _encode_payload(ftype.inner, item)
            parts.append(_LEN.pack(len(p)))
            parts.append(p)
        return b"".join(parts)
    if k == "map":
        parts = []
        for key, item in value.items():
            kb = key.encode()
            p = _encode_payload(ftype.inner, item)
            parts.append(_LEN.pack(len(kb)))
            parts.append(kb)
            parts.append(_LEN.pack(len(p)))
            parts.append(p)
        return b"".join(parts)
    if k == "any":
        return pickle.dumps(value, protocol=5)
    return _encode_scalar(ftype, value)


def _encode_field(number: int, ftype: FieldType, value) -> bytes:
    payload = _encode_payload(ftype, value)
    return (_TAG.pack((number << 3) | _wire_type(ftype))
            + _LEN.pack(len(payload)) + payload)


def _decode_payload(ftype: FieldType, payload: memoryview):
    k = ftype.kind
    if k == "msg":
        return ftype.inner.decode(payload)
    if k == "list":
        out = []
        off = 0
        while off < len(payload):
            (ln,) = _LEN.unpack_from(payload, off)
            off += 4
            out.append(_decode_payload(ftype.inner, payload[off:off + ln]))
            off += ln
        return out
    if k == "map":
        out = {}
        off = 0
        while off < len(payload):
            (kl,) = _LEN.unpack_from(payload, off)
            off += 4
            key = str(payload[off:off + kl], "utf-8")
            off += kl
            (vl,) = _LEN.unpack_from(payload, off)
            off += 4
            out[key] = _decode_payload(ftype.inner, payload[off:off + vl])
            off += vl
        return out
    if k == "any":
        return pickle.loads(payload)
    return _decode_scalar(ftype, payload)


def _decode_value(ftype: FieldType, wire_type: int, payload: memoryview):
    if wire_type != _wire_type(ftype):
        raise TypeError("wire type mismatch")
    return _decode_payload(ftype, payload)


# --------------------------------------------------------------- schemas
#
# Core control-plane DTOs (the gcs_service.proto / node_manager.proto
# analogs). Field numbers are FOREVER: never reuse a number, only add.

class NodeInfoMsg(Message):
    node_id = Field(1, BYTES)
    host = Field(2, STR)
    port = Field(3, INT)
    resources = Field(4, MAP(FLOAT))
    available = Field(5, MAP(FLOAT))
    labels = Field(6, MAP(STR))
    is_head = Field(7, BOOL)
    alive = Field(8, BOOL, default=True)
    object_store_path = Field(9, STR)
    # Two-phase drain: the node is still alive (leases/objects keep
    # working) but is scheduled for retirement at drain_deadline (unix
    # seconds; 0.0 = not draining). Old peers skip unknown fields.
    draining = Field(10, BOOL)
    drain_deadline = Field(11, FLOAT)


class HeartbeatMsg(Message):
    node_id = Field(1, BYTES)
    available = Field(2, MAP(FLOAT))
    known_version = Field(3, INT, default=-1)
    known_epoch = Field(4, STR)
    backlog = Field(5, ANY)   # per-class demand shapes (advisory)


class ViewDeltaMsg(Message):
    version = Field(1, INT)
    epoch = Field(2, STR)
    full = Field(3, LIST(MSG(NodeInfoMsg)))
    deltas = Field(4, LIST(MSG(NodeInfoMsg)))
    is_full = Field(5, BOOL)


class LeaseRequestMsg(Message):
    resources = Field(1, MAP(FLOAT))
    for_actor = Field(2, BOOL)
    placement_group_id = Field(3, BYTES)
    bundle_index = Field(4, INT, default=-1)
    runtime_env_hash = Field(5, BYTES)
    env_key = Field(6, STR)
    req_id = Field(7, BYTES)
    # Requesting worker's ident (hex): lets the raylet reclaim leases whose
    # holder died while caching them idle (see raylet._reclaim_holder_leases).
    holder = Field(8, STR)


class LeaseReplyMsg(Message):
    """RequestWorkerLeaseReply analog (node_manager.proto): grant, refusal,
    cancellation, or a spillback redirect to another raylet."""

    ok = Field(1, BOOL)
    error = Field(2, STR)
    canceled = Field(3, BOOL)
    spillback_host = Field(4, STR)
    spillback_port = Field(5, INT, default=-1)
    spillback_node = Field(6, BYTES)
    lease_id = Field(7, BYTES)
    worker_id = Field(8, BYTES)
    worker_host = Field(9, STR)
    worker_port = Field(10, INT, default=-1)
    node_id = Field(11, BYTES)
    # Batch extension: which request this reply resolves (echoes the
    # LeaseRequestMsg.req_id), and whether the entry is still queued at
    # the raylet — a pending entry's real resolution arrives later as a
    # `lease_grant` push on the same connection.
    req_id = Field(12, BYTES)
    pending = Field(13, BOOL)

    @classmethod
    def from_reply(cls, reply: dict) -> "LeaseReplyMsg":
        msg = cls(ok=bool(reply.get("ok")),
                  error=str(reply.get("error") or ""),
                  canceled=bool(reply.get("canceled")),
                  pending=bool(reply.get("pending")),
                  req_id=reply.get("req_id") or b"")
        sb = reply.get("spillback")
        if sb:
            msg.spillback_host, msg.spillback_port = str(sb[0]), int(sb[1])
            msg.spillback_node = reply.get("spillback_node") or b""
        if reply.get("ok") and reply.get("lease_id"):
            msg.lease_id = reply["lease_id"]
            msg.worker_id = reply.get("worker_id") or b""
            addr = reply.get("worker_address")
            if addr:
                msg.worker_host, msg.worker_port = str(addr[0]), int(addr[1])
            msg.node_id = reply.get("node_id") or b""
        return msg

    def to_reply(self) -> dict:
        reply: Dict[str, Any] = {"ok": self.ok}
        if self.canceled:
            reply["canceled"] = True
        if self.pending:
            reply["pending"] = True
        if self.req_id:
            reply["req_id"] = self.req_id
        if self.error:
            reply["error"] = self.error
        if self.spillback_port >= 0:
            reply["spillback"] = (self.spillback_host, self.spillback_port)
            if self.spillback_node:
                reply["spillback_node"] = self.spillback_node
        if self.ok and self.lease_id:
            reply["lease_id"] = self.lease_id
            reply["worker_id"] = self.worker_id
            if self.worker_port >= 0:
                reply["worker_address"] = (self.worker_host, self.worker_port)
            reply["node_id"] = self.node_id
        return reply


class TaskSpecMsg(Message):
    """TaskSpec envelope (core_worker.proto:441 PushTaskRequest analog).

    The ENVELOPE — ids, routing, options — is schema; everything that is
    genuinely code/opaque (args, kwarg names, scheduling strategy,
    runtime_env, pinned oids) travels as ONE `payload` ANY field — the
    audited pickle escape hatch, exactly the split the reference draws
    between TaskSpec protos and its pickled function/arg payloads. One
    combined field, not five: each ANY is a separate pickle.dumps, and
    per-call encode cost is the actor-call hot path (a 4->1 pickle
    consolidation measured ~25% higher async actor-call throughput)."""

    task_id = Field(1, BYTES)
    fn_id = Field(2, BYTES)
    name = Field(3, STR)
    # Field 4 is VALUE-versioned (same ANY wire type both versions): a
    # 5-tuple (args, kwarg_names, scheduling_strategy, runtime_env,
    # pinned_oids) from current writers; the bare args LIST from the
    # first-cut schema, whose remaining pieces arrived in the now
    # write-retired fields 5/12/15/16 below. TaskSpec.from_wire
    # disambiguates by shape, so a first-cut writer decodes losslessly.
    payload = Field(4, ANY)
    kwarg_names_v1 = Field(5, ANY)           # decode-only (retired writer)
    num_returns = Field(6, INT, default=1)
    resources = Field(7, MAP(FLOAT))
    max_retries = Field(8, INT, default=3)
    actor_id = Field(9, BYTES)
    method_name = Field(10, STR)
    seq_no = Field(11, INT)
    scheduling_strategy_v1 = Field(12, ANY)  # decode-only (retired writer)
    placement_group_id = Field(13, BYTES)
    placement_group_bundle_index = Field(14, INT, default=-1)
    runtime_env_v1 = Field(15, ANY)          # decode-only (retired writer)
    pinned_oids_v1 = Field(16, LIST(BYTES))  # decode-only (retired writer)
    # Distributed-trace propagation (tracing_helper.py _inject_tracing
    # analog): the caller's trace id + submit-span id travel as typed
    # envelope fields so the executing worker stitches its execute span
    # under the driver's, across processes. Empty = caller not tracing.
    trace_id = Field(17, BYTES)
    parent_span_id = Field(18, BYTES)


class SliceLostMsg(Message):
    """Slice failure-domain event (no reference proto: the reference has no
    slice concept — see ROADMAP "TPU chips/ICI slices"). Published by the
    GCS on the `slice_lost` channel and pushed to sibling raylets when any
    host of a multi-host TPU slice dies: the slice is ONE failure domain,
    so siblings fate-share in the same health tick."""

    slice_name = Field(1, STR)
    nodes = Field(2, LIST(BYTES))      # every node id of the lost slice
    origin_node = Field(3, BYTES)      # the host whose death triggered it
    reason = Field(4, STR)


class TaskReplyMsg(Message):
    """PushTaskReply analog: status + returns; errors are exceptions
    (ANY), return payloads are serialized values (ANY)."""

    status = Field(1, STR)
    returns = Field(2, ANY)
    error = Field(3, ANY)
    node_id = Field(4, BYTES)
    streamed = Field(5, INT, default=-1)

    @classmethod
    def from_reply(cls, reply: dict) -> "TaskReplyMsg":
        msg = cls(status=reply.get("status") or "")
        if "returns" in reply:
            msg.returns = reply["returns"]
        if "error" in reply:
            msg.error = reply["error"]
        if reply.get("node_id"):
            msg.node_id = reply["node_id"]
        if "streamed" in reply:
            msg.streamed = int(reply["streamed"])
        return msg

    def to_reply(self) -> dict:
        reply: Dict[str, Any] = {"status": self.status}
        if self.returns is not None:
            reply["returns"] = self.returns
        if self.error is not None:
            reply["error"] = self.error
        if self.node_id:
            reply["node_id"] = self.node_id
        if self.streamed >= 0:
            reply["streamed"] = self.streamed
        return reply


# ------------------------------------------------- control-plane batching
#
# One framed message per tick/pump instead of N per-item RPCs. These ride
# the same TLV rules as everything above: unknown fields skip, absent
# fields default, numbers are forever.

class LeaseBatchRequestMsg(Message):
    """A pump's worth of lease requests, granted in ONE scheduling pass.

    The raylet enqueues every entry, runs a single `_dispatch_pending()`,
    and replies immediately: entries resolved by that pass (grant, error,
    spillback) come back in `entries`; everything still queued is listed
    in `pending` and resolves later via a `lease_grant` push carrying a
    LeaseReplyMsg with the matching req_id. Waiting for all entries in
    the reply would deadlock — a speculative lease behind a running task
    only grants after that task finishes, which needs the reply."""

    entries = Field(1, LIST(MSG(LeaseRequestMsg)))


class LeaseBatchReplyMsg(Message):
    entries = Field(1, LIST(MSG(LeaseReplyMsg)))  # resolved now (req_id set)
    pending = Field(2, LIST(BYTES))               # req_ids still queued
    error = Field(3, STR)


class TaskEventMsg(Message):
    """One task state transition (gcs.proto TaskEvents analog)."""

    task_id = Field(1, STR)     # hex
    name = Field(2, STR)
    state = Field(3, STR)
    actor_id = Field(4, STR)    # hex, "" = not an actor task
    worker = Field(5, STR)
    time = Field(6, FLOAT)
    error = Field(7, STR)

    @classmethod
    def from_event(cls, ev: dict) -> "TaskEventMsg":
        return cls(task_id=ev.get("task_id") or "",
                   name=ev.get("name") or "",
                   state=ev.get("state") or "",
                   actor_id=ev.get("actor_id") or "",
                   worker=ev.get("worker") or "",
                   time=float(ev.get("time") or 0.0),
                   error=str(ev.get("error") or ""))

    def to_event(self) -> dict:
        return {"task_id": self.task_id, "name": self.name,
                "state": self.state,
                "actor_id": self.actor_id or None,
                "worker": self.worker, "time": self.time,
                "error": self.error or None}


class TaskEventBatchMsg(Message):
    """One flusher tick: every buffered event + the wait-edge snapshot +
    the drop count in a single typed frame (replaces N dict-pickles)."""

    events = Field(1, LIST(MSG(TaskEventMsg)))
    reporter = Field(2, STR)
    node_id = Field(3, BYTES)
    # wait_edges semantics match the pickled handler: has_wait_edges=False
    # means "no update", True with an empty list means "clear".
    has_wait_edges = Field(4, BOOL)
    wait_edges = Field(5, ANY)
    dropped = Field(6, INT)     # events trimmed from the buffer since last tick


class MetricsReportMsg(Message):
    """One metrics flush tick: the node/pid-scoped snapshot as one typed
    frame (same JSON payload the kv_put path shipped, minus the pickle)."""

    node = Field(1, STR)
    pid = Field(2, INT)
    payload = Field(3, BYTES)   # JSON snapshot_all() bytes


# --------------------------------------------------- zero-pickle transfer
#
# Object pull/push headers for the raw-frame RPC fast path: the chunk
# bytes ride OUT-OF-BAND as the frame payload (never pickled, received
# straight off the socket), only this small header is schema-encoded.

class ObjChunkRequestMsg(Message):
    oid = Field(1, BYTES)
    offset = Field(2, INT)
    length = Field(3, INT)


class ObjChunkReplyMsg(Message):
    found = Field(1, BOOL)
    total = Field(2, INT)
    metadata = Field(3, BYTES)
    error = Field(4, STR)


class ObjPutMsg(Message):
    oid = Field(1, BYTES)
    offset = Field(2, INT)
    total = Field(3, INT)
    metadata = Field(4, BYTES)
    seal = Field(5, BOOL)


class AckMsg(Message):
    ok = Field(1, BOOL)
    error = Field(2, STR)
    existed = Field(3, BOOL)


# ------------------------------------------------ cluster prefix store
#
# GCS prefix-table RPCs (llm/prefix_store.py <-> gcs/server.py). Headers
# only — the spilled KV pages ride OUT-OF-BAND as the raw-frame payload,
# exactly like the object pull/push path above. `token_ids` is the full
# root-anchored token prefix the entry covers: adopters verify it
# byte-for-byte against their own prompt before scattering pages (the
# cluster chain uses a FIXED salt so digests compare across processes;
# token verification is what makes a forged digest useless).

class PrefixEntryMsg(Message):
    digest = Field(1, BYTES)           # cluster_chain(token_ids)[-1]
    lora_id = Field(2, STR)            # "" = base model
    weights_version = Field(3, INT)    # adopt only on exact match
    block_size = Field(4, INT)
    n_tokens = Field(5, INT)
    token_ids = Field(6, LIST(INT))
    nbytes = Field(7, INT)             # encoded payload size
    owner_replica = Field(8, STR)      # live-holder hint (router fallback)
    node_id = Field(9, BYTES)          # publisher's node (death pruning)
    deployment = Field(10, STR)


class PrefixLookupMsg(Message):
    # Digest chain from the first block the caller is missing, upward:
    # the GCS answers with the contiguous run it holds from digests[0].
    digests = Field(1, LIST(BYTES))
    lora_id = Field(2, STR)
    weights_version = Field(3, INT)
    block_size = Field(4, INT)
    want_payload = Field(5, BOOL)      # False = owner-hint probe only
    replica = Field(6, STR)            # adopter tag -> new live-owner hint


class PrefixLookupReplyMsg(Message):
    found = Field(1, BOOL)
    entries = Field(2, LIST(MSG(PrefixEntryMsg)))
    error = Field(3, STR)


class PrefixPurgeMsg(Message):
    owner_replica = Field(1, STR)
    node_id = Field(2, BYTES)
    deployment = Field(3, STR)
    digests = Field(4, LIST(BYTES))
    below_weights_version = Field(5, INT)
    # True: blank live-owner hints only (replica eject/death — the pages,
    # homed in the GCS byte plane, stay adoptable). False: drop rows.
    clear_owner_only = Field(6, BOOL)


class PrefixPurgeReplyMsg(Message):
    ok = Field(1, BOOL)
    purged = Field(2, INT)
    owners_cleared = Field(3, INT)


# ------------------------------------------------ LLM KV handoff header
#
# Typed head frame of the disaggregated prefill->decode / live-migration
# KV stream (llm/disagg.py). The portable request state stays JSON bytes
# (it is heterogeneous, small, and already pickle-free); the trace fields
# carry the per-request trace context across the handoff so the decode
# replica's adopt span parent-links to the sender's handoff span — the
# serving-plane analog of TaskSpecMsg fields 17/18.

class KVHandoffMsg(Message):
    state_json = Field(1, BYTES)     # json.dumps(portable request state)
    kv_dtype = Field(2, STR)
    kv_shape = Field(3, LIST(INT))
    migrated = Field(4, BOOL)        # live session migration vs prefill handoff
    trace_id = Field(5, BYTES)       # 16-byte stitched-request trace id
    parent_span_id = Field(6, BYTES)  # sender's handoff span (8 bytes)
