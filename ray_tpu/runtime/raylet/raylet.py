"""Raylet: the per-node manager.

Reference analog: src/ray/raylet/ — NodeManager (node_manager.h:118; lease
handler node_manager.cc:1915), WorkerPool (worker_pool.h:127 PopWorker,
prestart :234), LocalTaskManager (local_task_manager.cc:57), and the node's
plasma store which it creates and owns (object_manager/plasma/store_runner).

One process per node. Grants worker leases to drivers (normal tasks) and to
the GCS (actor creation); owns local resource accounting including
placement-group bundle reservations (PlacementGroupResourceManager analog).
"""

from __future__ import annotations

import asyncio
import collections
import logging
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional, Tuple

from ray_tpu.runtime import events as events_mod
from ray_tpu.runtime import metric_defs, scheduling
from ray_tpu.runtime.object_store import ObjectStore
from ray_tpu.runtime.rpc import RawReply, RpcClient, RpcError, RpcServer
from ray_tpu.utils.ids import NodeID, WorkerID

logger = logging.getLogger(__name__)

DEFAULT_OBJECT_STORE_MEMORY = 2 << 30

# Hot gauge: set on every dispatch tick — bind once, skip per-set tag work.
_PENDING_LEASES = metric_defs.PENDING_LEASES.bind()


def _store_dir(session_dir: str) -> str:
    """Where the shared-memory arena file lives: /dev/shm (tmpfs) when
    available, like the reference's plasma store. A disk-backed session
    dir (e.g. /tmp on ext4) turns every fresh-page write into filesystem
    block allocation + writeback — measured 5-20x slower cold puts (the
    r3 microbench's 86x put/get asymmetry was exactly this). Override
    with RAY_TPU_STORE_DIR."""
    override = os.environ.get("RAY_TPU_STORE_DIR")
    if override:
        return override
    shm = "/dev/shm"
    if os.path.isdir(shm) and os.access(shm, os.W_OK):
        return shm
    return session_dir


class WorkerHandle:
    def __init__(self, worker_id: bytes, proc: subprocess.Popen):
        self.worker_id = worker_id
        self.proc = proc
        self.busy_since: Optional[float] = None  # leased-task start (OOM policy)
        self.address: Optional[Tuple[str, int]] = None
        self.ready = asyncio.Event()
        self.is_actor = False
        self.actor_id: Optional[bytes] = None
        self.lease_id: Optional[bytes] = None
        self.lease_resources: Dict[str, float] = {}
        self.pg_key: Optional[Tuple[bytes, int]] = None
        self.req_id: Optional[bytes] = None
        # Worker ident (hex) of the lease HOLDER (the submitter caching this
        # lease), so its death can reclaim the lease (_reclaim_holder_leases).
        self.leased_to: str = ""
        # runtime_env fingerprint of work this process has executed: a
        # worker contaminated by env A's py_modules/working_dir is never
        # reused for env B (worker_pool.h runtime-env-keyed PopWorker).
        self.env_key: Optional[str] = None


class PendingLease:
    def __init__(self, resources, for_actor, pg_key, fut, req_id=None,
                 env_key=None, holder=""):
        self.resources = resources
        self.for_actor = for_actor
        self.pg_key = pg_key
        self.fut = fut
        self.req_id = req_id
        self.env_key = env_key
        self.holder = holder
        self.enqueued = time.monotonic()


class Raylet:
    # Class-level default so dispatch-path helpers work on partially
    # constructed instances (unit tests build bare Raylets) — __init__
    # shadows it per-instance when a drain starts.
    _draining = False

    def __init__(self, gcs_address: Tuple[str, int], session_dir: str,
                 resources: Dict[str, float], labels: Dict[str, str],
                 object_store_memory: int = DEFAULT_OBJECT_STORE_MEMORY,
                 is_head: bool = False, host: str = "127.0.0.1",
                 worker_env: Optional[Dict[str, str]] = None):
        self.node_id = NodeID.generate().binary()
        self.gcs_address = gcs_address
        self.session_dir = session_dir
        self.total_resources = dict(resources)
        self.available = dict(resources)
        self.labels = labels
        self.is_head = is_head
        self.worker_env = worker_env or {}
        self.server = RpcServer(host, 0)
        self.server.register_all(self)
        self.store_path = os.path.join(
            _store_dir(session_dir), f"store_{self.node_id.hex()[:12]}.shm")
        self.object_store_memory = object_store_memory
        self.store: Optional[ObjectStore] = None
        self.gcs: Optional[RpcClient] = None
        self._workers: Dict[bytes, WorkerHandle] = {}
        self._idle: List[WorkerHandle] = []
        # Per-scheduling-class lease queues (ClusterTaskManager analog,
        # cluster_task_manager.cc:49 QueueAndScheduleTask / :188
        # ScheduleAndDispatchTasks): a scheduling class = (resource shape,
        # bundle), one FIFO per class, round-robin dispatch across classes
        # so a backlogged shape can't head-of-line-block the others. All
        # members of a class share one shape and pool, so a non-fitting
        # head blocks only its class and dispatch is O(classes), not
        # O(pending). Cluster-wide-infeasible classes park in _infeasible
        # (they also feed autoscaler demand via heartbeat backlog) and are
        # retried whenever the cluster resource view changes.
        self._queues: "collections.OrderedDict[tuple, collections.deque]" = \
            collections.OrderedDict()
        self._infeasible: Dict[tuple, collections.deque] = {}
        # Placement-group bundle reservations: (pg_id, bundle_index) ->
        # {"resources": ..., "available": ...}; prepared-but-uncommitted hold
        # resources too (2PC).
        self._bundles: Dict[Tuple[bytes, int], Dict] = {}
        self._shutdown = asyncio.Event()
        self._monitor_task = None
        self._heartbeat_task = None
        self._memory_task = None
        self._spill_task = None
        self._cluster_view: List[dict] = []
        # Two-phase drain: set by the GCS's `drain_self` RPC (or the view
        # delta as backup). While draining, running leases finish but new
        # non-PG lease classes spill to peers, bundle prepares are refused,
        # and a background task migrates primary object copies off-node.
        self._draining = False
        self._drain_reason = ""
        self._drain_deadline = 0.0
        self._drain_progress: Dict[str, int] = {}
        self._drain_migrate_task = None
        # Incremental resource-view sync state (see _heartbeat_loop).
        self._view_version = 0
        self._view_epoch = None  # GCS instance id; mismatch -> full resync
        self._view_nodes: Dict[bytes, dict] = {}
        # Node-level runtime-env agent (reference: _private/runtime_env/
        # agent/): refcounts materialized env URIs across this node's
        # workers and GCs unpinned ones over a byte budget.
        from ray_tpu.config import cfg as _cfg
        from ray_tpu.runtime_envs.cache import UriCache

        self._env_cache = UriCache(
            max_bytes=getattr(_cfg(), "runtime_env_cache_bytes", 10 << 30),
            delete_fn=self._delete_env_uri)
        self._env_holds: Dict[str, set] = {}  # worker_ident -> {uri}

    # ---- lifecycle -------------------------------------------------------

    async def start(self):
        os.makedirs(self.session_dir, exist_ok=True)
        os.makedirs(os.path.join(self.session_dir, "logs"), exist_ok=True)
        self.store = ObjectStore(self.store_path, capacity=self.object_store_memory,
                                 create=True)
        from ray_tpu.runtime.object_store.spill import SpillManager
        self.spill = SpillManager(
            self.store, os.path.join(self.session_dir, "spill"))
        # Per-node store-occupancy gauges, refreshed each heartbeat tick.
        node_tag = {"node": self.node_id.hex()[:12]}
        self._g_store_used = metric_defs.OBJECT_STORE_USED.bind(node_tag)
        self._g_store_capacity = \
            metric_defs.OBJECT_STORE_CAPACITY.bind(node_tag)
        self._g_spilled = metric_defs.OBJECT_STORE_SPILLED.bind(node_tag)
        await self.server.start()
        self.gcs = RpcClient(*self.gcs_address, auto_reconnect=True,
                             reconnect_timeout=120,
                             on_reconnect=self._on_gcs_reconnect)
        await self.gcs.connect(timeout=30)
        reply = await self.gcs.call(
            "register_node", node_id=self.node_id, address=self.server.address,
            resources=self.total_resources, object_store_path=self.store_path,
            is_head=self.is_head, labels=self.labels)
        assert reply["ok"]
        self._monitor_task = asyncio.ensure_future(self._monitor_workers())
        self._heartbeat_task = asyncio.ensure_future(self._heartbeat_loop())
        self._memory_task = asyncio.ensure_future(self._memory_monitor_loop())
        self._spill_task = asyncio.ensure_future(self._proactive_spill_loop())
        from ray_tpu.runtime.log_monitor import LogMonitor
        self._log_monitor = LogMonitor(
            os.path.join(self.session_dir, "logs"),
            lambda ch, msg: self.gcs.call("publish", channel=ch, message=msg),
            self.node_id.hex())
        self._log_task = asyncio.ensure_future(
            self._log_monitor.run(self._shutdown))
        # Worker prestart (worker_pool.h:234 analog): warm idle workers so
        # the first lease skips process-spawn latency. Bounded by CPU count;
        # off by default (worker_prestart=0) — each prestart is a real
        # process.
        from ray_tpu.config import cfg as _cfg

        prestart = min(int(self.total_resources.get("CPU", 0)),
                       _cfg().worker_prestart)
        self._prestart_tasks = [
            asyncio.ensure_future(self._prestart_one())
            for _ in range(max(0, prestart))]
        logger.info("raylet %s up at %s resources=%s", self.node_id.hex()[:12],
                    self.server.address, self.total_resources)
        return self

    async def _prestart_one(self):
        w = self._spawn_worker()
        try:
            await asyncio.wait_for(w.ready.wait(), timeout=120)
        except asyncio.TimeoutError:
            return
        if w.address is not None and w.lease_id is None:
            self._park_idle(w)

    async def _on_gcs_reconnect(self, client):
        """GCS restarted (NotifyGCSRestart analog): re-register so the new
        GCS (possibly without durable storage) learns this node again."""
        try:
            await client._call_once("register_node", 30, dict(
                node_id=self.node_id, address=self.server.address,
                resources=self.total_resources,
                object_store_path=self.store_path,
                is_head=self.is_head, labels=self.labels))
        except Exception:
            logger.warning("re-register after GCS reconnect failed")

    async def _heartbeat_loop(self):
        # Heartbeats push availability up to the GCS; the reply piggybacks
        # version-gated DELTAS of the cluster view — this raylet's spillback
        # routing table (ray_syncer resource gossip analog,
        # src/ray/common/ray_syncer/). An idle cluster exchanges no node
        # data at all; a full snapshot only flows on first sync or after
        # falling behind the GCS's capped change log.
        from ray_tpu.runtime import wire
        from ray_tpu.runtime.rpc import RpcError

        use_typed = True
        while not self._shutdown.is_set():
            try:
                # Typed-schema heartbeat (wire.HeartbeatMsg/ViewDeltaMsg):
                # structure evolves per-field across versions instead of
                # all-or-nothing pickled dicts. Falls back to the legacy
                # handler against an older GCS (the rolling-upgrade case
                # the schema exists for).
                if use_typed:
                    hb = wire.HeartbeatMsg(
                        node_id=self.node_id,
                        available=dict(self.available),
                        known_version=self._view_version,
                        known_epoch=self._view_epoch or "",
                        backlog=self._backlog())
                    try:
                        reply = await self.gcs.call("node_heartbeat2",
                                                    m=hb.encode())
                    except RpcError as e:
                        if "no handler" not in str(e):
                            raise
                        logger.warning("GCS lacks node_heartbeat2; "
                                       "falling back to legacy heartbeat")
                        use_typed = False
                        continue
                else:
                    reply = await self.gcs.call(
                        "node_heartbeat", node_id=self.node_id,
                        available=self.available, backlog=self._backlog(),
                        known_version=self._view_version,
                        known_epoch=self._view_epoch)
                if reply.get("unknown"):
                    # Restarted GCS lost us (no durable storage): re-register.
                    await self._on_gcs_reconnect(self.gcs)
                    self._view_version = 0
                    self._view_epoch = None
                    self._view_nodes.clear()
                else:
                    view = reply.get("view")
                    if use_typed:
                        view = self._decode_view(view)
                    self._apply_view(view)
            except Exception:
                pass
            try:
                if self.store is not None:
                    self._g_store_used.set(float(self.store.used))
                    self._g_store_capacity.set(float(self.store.capacity))
                if self.spill is not None:
                    self._g_spilled.set(float(self.spill.spilled_bytes()))
            except Exception:
                pass
            from ray_tpu.config import cfg
            await asyncio.sleep(cfg().heartbeat_interval_s)

    @staticmethod
    def _decode_view(encoded) -> Optional[dict]:
        if not encoded:
            return None
        from ray_tpu.runtime import wire

        msg = wire.ViewDeltaMsg.decode(encoded)

        def node_dict(n):
            return {"node_id": n.node_id, "address": (n.host, n.port),
                    "resources": n.resources, "available": n.available,
                    "labels": n.labels, "is_head": n.is_head,
                    "alive": n.alive,
                    "object_store_path": n.object_store_path,
                    "draining": n.draining,
                    "drain_deadline": n.drain_deadline}

        view = {"version": msg.version, "epoch": msg.epoch or None}
        nodes = [node_dict(n) for n in (msg.full if msg.is_full
                                        else msg.deltas)]
        if msg.is_full:
            view["full"] = nodes
        else:
            view["deltas"] = nodes
        return view

    def _apply_view(self, view: Optional[dict]):
        if not view:
            return
        if "full" in view:
            self._view_nodes = {n["node_id"]: n for n in view["full"]}
        else:
            for n in view.get("deltas", ()):
                self._view_nodes[n["node_id"]] = n
        # Backup drain trigger: if the GCS's direct `drain_self` RPC was
        # lost, our own draining flag still arrives via the view delta.
        me = self._view_nodes.get(self.node_id)
        if me is not None and me.get("draining") and not self._draining:
            self._start_drain("drain (via view sync)",
                              max(0.0, float(me.get("drain_deadline") or 0.0)
                                  - time.time()))
        # Dead nodes delivered their final not-alive delta: drop them so
        # the table stays bounded by LIVE nodes under churn.
        for nid in [nid for nid, n in self._view_nodes.items()
                    if not n.get("alive", True)]:
            del self._view_nodes[nid]
        self._view_version = view["version"]
        self._view_epoch = view.get("epoch")
        self._cluster_view = list(self._view_nodes.values())
        if self._infeasible and (view.get("full") or view.get("deltas")):
            self._retry_infeasible()

    async def _memory_monitor_loop(self):
        """Kill one leased worker per tick while the node is over the memory
        threshold (memory_monitor.h:52 usage callback + retriable-FIFO
        worker_killing_policy). The child watcher reports the death; the
        submitter's retry path resubmits the task."""
        from ray_tpu.runtime.memory_monitor import MemoryMonitor

        monitor = MemoryMonitor()
        while not self._shutdown.is_set():
            await asyncio.sleep(1.0)
            try:
                if not monitor.over_threshold():
                    continue
                victim = monitor.pick_victim(list(self._workers.values()))
                if victim is None:
                    continue
                logger.warning(
                    "node memory over %.0f%%: killing worker %s (task running "
                    "%.1fs) to relieve pressure", monitor.threshold * 100,
                    victim.worker_id.hex()[:12],
                    time.monotonic() - victim.busy_since)
                metric_defs.OOM_KILLS.inc()
                victim.proc.kill()
                self._emit_event(
                    events_mod.OOM_KILL,
                    f"memory over {monitor.threshold:.0%}: killed worker "
                    f"{victim.worker_id.hex()[:12]} to relieve pressure",
                    severity=events_mod.ERROR)
            except Exception:
                logger.exception("memory monitor tick failed")

    def _emit_event(self, event_type: str, message: str, **kwargs):
        """Ship one typed cluster event to the GCS ring, fire-and-forget.
        The raylet has no core worker, so it bypasses events.emit and uses
        its own auto-reconnecting GCS client; must be called on the loop."""
        try:
            ev = events_mod.make_event(event_type, message, source="raylet",
                                       node_id=self.node_id, **kwargs)
            fut = asyncio.ensure_future(
                self.gcs.call("report_events", events=[ev], timeout=5))
            fut.add_done_callback(lambda f: f.exception())  # best-effort
        except Exception:
            logger.debug("event emit failed", exc_info=True)

    async def run_forever(self):
        await self._shutdown.wait()
        logger.info("raylet shutting down")
        await self._cleanup()
        logger.info("raylet cleanup complete")

    async def _cleanup(self):
        for task in (self._monitor_task, self._heartbeat_task,
                     self._memory_task, self._spill_task,
                     getattr(self, '_log_task', None)):
            if task:
                task.cancel()
        for w in list(self._workers.values()):
            try:
                w.proc.terminate()
            except Exception:
                pass
        deadline = time.monotonic() + 3
        for w in list(self._workers.values()):
            try:
                w.proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except Exception:
                try:
                    w.proc.kill()
                except Exception:
                    pass
        if self.store is not None:
            self.store.close()
            try:
                os.unlink(self.store_path)
            except OSError:
                pass
        await self.server.close()

    async def handle_shutdown_node(self, conn):
        logger.info("shutdown_node received")
        self._shutdown.set()
        return {"ok": True}

    async def handle_slice_lost(self, conn, m: bytes):
        """Fate-share with the ICI slice (typed wire.SliceLostMsg): a
        sibling host of this node's slice died, so this node's workers are
        running against a broken ICI domain. Kill them all immediately —
        their leases/tasks fail now instead of hanging on dead collectives
        — then shut the raylet down (the GCS already marked us dead; a
        production deployment replaces the whole slice as one unit)."""
        from ray_tpu.runtime import wire

        msg = wire.SliceLostMsg.decode(m)
        logger.warning(
            "slice %r lost (%s): fate-sharing — killing %d worker(s) and "
            "shutting down", msg.slice_name, msg.reason, len(self._workers))
        for w in list(self._workers.values()):
            try:
                w.proc.kill()
            except Exception:
                pass
        self._shutdown.set()
        return {"ok": True}

    # ---- graceful drain (advance-notice retirement) ----------------------

    async def handle_drain_self(self, conn, reason: str = "",
                                deadline_s: float = 0.0):
        """The GCS announced this node's retirement (spot preemption with
        notice). Enter drain mode: running leases finish, but new work
        spills to peers and primary object copies migrate off-node before
        the deadline kill."""
        self._start_drain(reason, deadline_s)
        return {"ok": True, "draining": True,
                "objects_total": self._drain_progress.get("objects_total")}

    def _start_drain(self, reason: str, deadline_s: float):
        if self._draining:
            return
        self._draining = True
        self._drain_reason = reason
        self._drain_deadline = time.time() + max(0.0, deadline_s)
        logger.warning("raylet %s draining (%s): deadline in %.1fs",
                       self.node_id.hex()[:12], reason, deadline_s)
        try:
            self._g_draining = metric_defs.NODES_DRAINING.bind(
                {"node": self.node_id.hex()[:12]})
            self._g_draining.set(1.0)
        except Exception:
            pass
        self._drain_migrate_task = asyncio.ensure_future(
            self._drain_migrate_objects())
        # Queued non-PG lease classes re-route now rather than running a
        # task that dies with the node.
        for key in [k for k, q in list(self._queues.items())
                    if q and k[1] is None]:
            q = self._queues.pop(key)
            asyncio.ensure_future(self._resolve_spillback_class(key, q))

    def _drain_peers(self) -> List[dict]:
        return [n for n in self._cluster_view
                if n.get("alive") and not n.get("draining")
                and n["node_id"] != self.node_id]

    async def _drain_migrate_objects(self):
        """Proactively re-replicate this node's primary object copies onto
        live non-draining peers, then report the new homes to the GCS
        relocation table — so a `get()` after the deadline finds the moved
        copy instead of paying ObjectLostError + lineage re-execution.
        Peers PULL via their existing `fetch_and_relay` chunked path (the
        same machinery as broadcast); whatever doesn't finish before the
        kill falls back to the reactive path by design."""
        if self.store is None:
            return
        try:
            oids = [oid for oid in self.store.list_objects()
                    if self.store.contains(oid)]
        except Exception:
            logger.exception("drain: object enumeration failed")
            return
        self._drain_progress = {"objects_total": len(oids),
                                "objects_migrated": 0, "objects_failed": 0}
        if not oids:
            return
        peers = self._drain_peers()
        if not peers:
            # Gossip may lag replacement capacity launched at notice time:
            # confirm against the GCS before giving up.
            try:
                self._cluster_view = await self.gcs.call("get_nodes")
                peers = self._drain_peers()
            except Exception:
                pass
        if not peers:
            logger.warning("drain: no live peer to migrate %d object(s) to",
                           len(oids))
            self._drain_progress["objects_failed"] = len(oids)
            return
        moved: List[bytes] = []
        by_peer: Dict[bytes, List[bytes]] = {}
        for i, oid in enumerate(oids):
            by_peer.setdefault(peers[i % len(peers)]["node_id"], []).append(oid)
        peer_by_id = {p["node_id"]: p for p in peers}
        for peer_id, batch in by_peer.items():
            peer = peer_by_id[peer_id]
            client = RpcClient(*tuple(peer["address"]))
            try:
                await client.connect(timeout=10)
                for oid in batch:
                    try:
                        r = await client.call(
                            "fetch_and_relay", oid=oid,
                            source=self.server.address, targets=[],
                            timeout=60)
                        if r.get("ok"):
                            moved.append(oid)
                            self._drain_progress["objects_migrated"] += 1
                        else:
                            self._drain_progress["objects_failed"] += 1
                    except Exception:
                        self._drain_progress["objects_failed"] += 1
                # Report per-peer so partial progress still lands in the
                # relocation table if the deadline interrupts us.
                if moved:
                    await self.gcs.call("report_object_locations",
                                        node_id=peer_id,
                                        oids=[o for o in moved
                                              if o in set(batch)])
            except Exception:
                self._drain_progress["objects_failed"] += len(batch)
                logger.warning("drain: migration to peer %s failed",
                               peer_id.hex()[:12], exc_info=True)
            finally:
                try:
                    await client.close()
                except Exception:
                    pass
        logger.info("drain: migrated %d/%d object(s) off node",
                    self._drain_progress["objects_migrated"], len(oids))

    # ---- worker pool (worker_pool.h) -------------------------------------

    def _park_idle(self, w: WorkerHandle):
        """Return a worker to the idle pool, bounded: with env-keyed reuse,
        distinct runtime_envs would otherwise strand ever more mismatched
        idle processes (reference: idle-worker killing, worker_pool.cc).
        Oldest idle worker dies first when over the cap."""
        from ray_tpu.config import cfg

        self._idle.append(w)
        cap = max(1, cfg().worker_pool_max_idle)
        while len(self._idle) > cap:
            victim = self._idle.pop(0)
            # Keep the handle in _workers: _monitor_workers polls, reaps,
            # and reports the death like every other kill path (popping it
            # here would leak an unreaped zombie if SIGTERM is ignored).
            try:
                victim.proc.terminate()
            except Exception:
                pass

    def _spawn_worker(self) -> WorkerHandle:
        metric_defs.WORKERS_STARTED.inc()
        worker_id = WorkerID.generate().binary()
        env = dict(os.environ)
        env.update(self.worker_env)
        env["RAY_TPU_WORKER_ID"] = worker_id.hex()
        env["RAY_TPU_NODE_ID"] = self.node_id.hex()
        env["RAY_TPU_RAYLET_ADDR"] = f"{self.server.host}:{self.server.port}"
        env["RAY_TPU_GCS_ADDR"] = f"{self.gcs_address[0]}:{self.gcs_address[1]}"
        env["RAY_TPU_STORE_PATH"] = self.store_path
        env["RAY_TPU_SESSION_DIR"] = self.session_dir
        log_path = os.path.join(self.session_dir, "logs",
                                f"worker_{worker_id.hex()[:12]}.log")
        log_file = open(log_path, "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.runtime.worker_main"],
            env=env, stdout=log_file, stderr=subprocess.STDOUT,
            start_new_session=True)
        log_file.close()
        handle = WorkerHandle(worker_id, proc)
        self._workers[worker_id] = handle
        return handle

    async def handle_worker_ready(self, conn, worker_id: bytes, address):
        w = self._workers.get(worker_id)
        if w is None:
            return {"ok": False}
        w.address = tuple(address)
        w.ready.set()
        conn.meta["worker_id"] = worker_id
        return {"ok": True}

    async def _proactive_spill_loop(self):
        """Background spilling above a fill watermark: the raylet (not a
        task worker mid-put) absorbs the disk IO, so workers rarely hit
        StoreFullError's inline spill-before-evict path. The raylet IS the
        node's dedicated IO process in this serverless-store design
        (reference analog: worker_pool.h:381 dedicated spill I/O workers +
        local_object_manager spill triggers)."""
        from ray_tpu.config import cfg

        high = cfg().spill_high_watermark
        low = cfg().spill_low_watermark
        if high <= 0:
            return
        while not self._shutdown.is_set():
            await asyncio.sleep(0.25)
            try:
                store = self.store
                if store is None or store.capacity == 0:
                    continue
                if store.used / store.capacity < high:
                    continue
                target = int(store.capacity * low)
                # Off-loop: file IO must not stall lease dispatch.
                await asyncio.get_event_loop().run_in_executor(
                    None, self._spill_down_to, target)
            except Exception:
                logger.exception("proactive spill pass failed")

    def _spill_down_to(self, target_bytes: int):
        need = self.store.used - target_bytes
        if need <= 0:
            return
        freed = self.spill.spill_until(need)
        if freed:
            logger.info("proactive spill: %d bytes -> disk (used %.0f%%)",
                        freed, 100 * self.store.used / self.store.capacity)

    # ---- runtime-env agent (per-node URI refcount + GC) ------------------

    def _delete_env_uri(self, uri: str) -> int:
        from ray_tpu.runtime_envs.plugin import _REGISTRY, _ensure_builtin

        _ensure_builtin()
        cache_dir = os.path.join(self.session_dir, "runtime_resources")
        for plugin in _REGISTRY.values():
            try:
                freed = plugin.delete(uri, cache_dir)
                if freed:
                    return freed
            except Exception:
                logger.exception("env uri delete failed: %s via %s",
                                 uri, plugin.name)
        return 0

    def _env_uri_size(self, uri: str) -> int:
        """Plugin-dispatched size accounting (plugins own URI layouts;
        custom env kinds would otherwise be recorded as 0 bytes and escape
        the byte budget)."""
        from ray_tpu.runtime_envs.plugin import _REGISTRY, _ensure_builtin

        _ensure_builtin()
        cache_dir = os.path.join(self.session_dir, "runtime_resources")
        for plugin in _REGISTRY.values():
            try:
                size = plugin.size(uri, cache_dir)
                if size:
                    return size
            except Exception:
                continue
        return 0

    async def handle_env_hold(self, conn, uris: List[str], worker: str = "",
                              release_others: bool = False):
        """A worker materialized/activated these env URIs: pin them. With
        release_others=True, drop the worker's pins on URIs NOT in this
        set (env switch on a reused worker must not accumulate pins for
        envs it no longer runs). Size accounting via plugin dispatch.

        Ordering: hold() BEFORE add() — add() can trigger eviction, and a
        just-materialized unpinned URI must never be its own victim while
        the worker that extracted it is importing from it."""
        held = self._env_holds.setdefault(worker or "anon", set())
        if release_others:
            for uri in list(held - set(uris)):
                held.discard(uri)
                self._env_cache.release(uri)
        for uri in uris:
            if uri in held:
                continue
            held.add(uri)
            self._env_cache.hold(uri)
            if not self._env_cache.contains(uri):
                self._env_cache.add(uri, self._env_uri_size(uri))
        return {"ok": True}

    async def handle_env_release(self, conn, uris: List[str],
                                 worker: str = ""):
        held = self._env_holds.get(worker or "anon", set())
        for uri in uris:
            if uri in held:
                held.discard(uri)
                self._env_cache.release(uri)
        return {"ok": True}

    async def handle_env_stats(self, conn):
        return self._env_cache.stats()

    def _release_env_holds(self, worker_ident: str):
        for uri in self._env_holds.pop(worker_ident, set()):
            self._env_cache.release(uri)

    async def _monitor_workers(self):
        """Child watcher: detect worker process exits (worker death path)."""
        while not self._shutdown.is_set():
            await asyncio.sleep(0.2)
            for w in list(self._workers.values()):
                if w.proc.poll() is not None:
                    del self._workers[w.worker_id]
                    if w in self._idle:
                        self._idle.remove(w)
                    self._release_env_holds(w.worker_id.hex())
                    reason = f"worker exited with code {w.proc.returncode}"
                    if w.lease_resources:
                        scheduling.add(self._lease_pool(w.pg_key), w.lease_resources)
                    if not w.ready.is_set():
                        w.ready.set()  # unblock lease waiters; address stays None
                    try:
                        # pid lets the GCS purge the dead reporter's
                        # metrics:<node>:<pid> snapshot + history rings.
                        await self.gcs.call("report_worker_death", node_id=self.node_id,
                                            worker_id=w.worker_id, actor_id=w.actor_id,
                                            reason=reason, pid=w.proc.pid)
                    except Exception:
                        pass
                    await self._reclaim_holder_leases(w.worker_id.hex())
                    await self._dispatch_pending()

    async def _reclaim_holder_leases(self, holder: str):
        """Reclaim every lease whose HOLDER just died.

        return_worker only ever arrives from the lease holder (submitters
        cache idle leases for lease_idle_timeout_s before returning them),
        so a client killed while holding cached leases — e.g. an actor
        running a task-submitting loop — would otherwise leak its granted
        resources forever: available CPUs pin at 0, every later lease
        request starves, and the still-alive leased workers idle unleasable.
        The leased worker itself keeps running; it just goes back in the
        idle pool."""
        if not holder:
            return
        freed = False
        for w in list(self._workers.values()):
            if w.lease_id is not None and w.leased_to == holder:
                logger.info("reclaiming lease %s (holder %s died)",
                            w.lease_id.hex()[:8], holder[:12])
                try:
                    scheduling.add(self._lease_pool(w.pg_key),
                                   w.lease_resources)
                except Exception:
                    pass  # bundle already released with its PG
                w.lease_id = None
                w.lease_resources = {}
                w.pg_key = None
                w.req_id = None
                w.busy_since = None
                w.leased_to = ""
                freed = True
                if not w.is_actor:
                    self._park_idle(w)
        if freed:
            await self._dispatch_pending()

    # ---- resource accounting ---------------------------------------------

    def _lease_pool(self, pg_key: Optional[Tuple[bytes, int]]) -> Dict[str, float]:
        """The resource pool a lease draws from: node-level, or a committed
        placement-group bundle."""
        if pg_key is None:
            return self.available
        bundle = self._bundles.get(pg_key)
        if bundle is None:
            raise RuntimeError(f"no bundle {pg_key[0].hex()[:12]}:{pg_key[1]} on this node")
        return bundle["available"]

    # ---- leases (node_manager.cc:1915 HandleRequestWorkerLease) ----------

    async def handle_lease_worker2(self, conn, m: bytes):
        """Typed-schema lease request (wire.LeaseRequestMsg in,
        LeaseReplyMsg out — node_manager.proto RequestWorkerLease analog).
        A newer submitter's extra fields skip on decode here; our reply's
        fields it doesn't know skip on its side."""
        from ray_tpu.runtime import wire

        req = wire.LeaseRequestMsg.decode(m)
        reply = await self.handle_lease_worker(
            conn, dict(req.resources), for_actor=req.for_actor,
            placement_group_id=req.placement_group_id or None,
            bundle_index=req.bundle_index,
            req_id=req.req_id or None, env_key=req.env_key or None,
            holder=req.holder or "")
        return wire.LeaseReplyMsg.from_reply(reply).encode()

    async def handle_lease_batch2(self, conn, m: bytes):
        """A pump's worth of lease requests granted in ONE scheduling pass
        (the amortized HandleRequestWorkerLease): N enqueues, one
        `_dispatch_pending()`, one reply frame. Entries that pass resolves
        synchronously (queue errors, immediate refusals) come back inline;
        everything else is listed as `pending` and resolves later via a
        `lease_grant` push on this connection. Waiting for all entries
        here would deadlock — a speculative lease queued behind a running
        task only grants after that task finishes, which needs this reply
        to have been delivered."""
        from ray_tpu.runtime import wire

        batch = wire.LeaseBatchRequestMsg.decode(m)
        reply = wire.LeaseBatchReplyMsg()
        waiting = []
        for req in batch.entries:
            req_id = req.req_id or os.urandom(8)
            pg_key = None
            if req.placement_group_id:
                idx = (req.bundle_index if req.bundle_index >= 0
                       else self._any_bundle_index(req.placement_group_id))
                if idx is None:
                    r = wire.LeaseReplyMsg.from_reply({
                        "ok": False,
                        "error": "placement group bundle not on this node"})
                    r.req_id = req_id
                    reply.entries.append(r)
                    continue
                pg_key = (req.placement_group_id, idx)
            fut = asyncio.get_event_loop().create_future()
            pend = PendingLease(dict(req.resources), req.for_actor, pg_key,
                                fut, req_id, env_key=req.env_key or None,
                                holder=req.holder or "")
            key = self._sched_class(pend.resources, pg_key, pend.env_key)
            self._queues.setdefault(key, collections.deque()).append(pend)
            waiting.append((req_id, fut))
        await self._dispatch_pending()
        # A few cooperative yields let resolutions the pass scheduled via
        # ensure_future (errors, spillback verdicts, grants onto already-
        # warm workers) land inline in this reply instead of as per-entry
        # pushes. Bounded and non-blocking: sleep(0) only yields the loop,
        # so a grant stuck behind a real worker spawn can't stall the
        # reply — it just comes back `pending`.
        for _ in range(8):
            if all(f.done() for _, f in waiting):
                break
            await asyncio.sleep(0)
        for req_id, fut in waiting:
            if fut.done():
                r = wire.LeaseReplyMsg.from_reply(fut.result())
                r.req_id = req_id
                reply.entries.append(r)
            else:
                reply.pending.append(req_id)
                fut.add_done_callback(
                    lambda f, rid=req_id: asyncio.ensure_future(
                        self._push_lease_grant(conn, rid, f)))
        return reply.encode()

    async def _push_lease_grant(self, conn, req_id: bytes, fut):
        try:
            result = fut.result()
        except Exception as e:
            result = {"ok": False, "error": repr(e)}
        from ray_tpu.runtime import wire

        r = wire.LeaseReplyMsg.from_reply(result)
        r.req_id = req_id
        try:
            await conn.push("lease_grant",
                            {"req_id": req_id, "m": r.encode()})
        except Exception:
            logger.debug("lease_grant push for %s failed (peer gone)",
                         req_id.hex())

    async def handle_lease_worker(self, conn, resources: Dict[str, float],
                                  for_actor: bool = False,
                                  placement_group_id: Optional[bytes] = None,
                                  bundle_index: int = -1,
                                  req_id: Optional[bytes] = None,
                                  env_key: Optional[str] = None,
                                  holder: str = ""):
        pg_key = None
        if placement_group_id is not None:
            idx = bundle_index if bundle_index >= 0 else self._any_bundle_index(placement_group_id)
            if idx is None:
                return {"ok": False, "error": "placement group bundle not on this node"}
            pg_key = (placement_group_id, idx)
        logger.debug("lease_worker: res=%s avail=%s pending=%d", resources,
                     self.available, self._pending_count())
        fut = asyncio.get_event_loop().create_future()
        req = PendingLease(resources, for_actor, pg_key, fut, req_id,
                           env_key=env_key, holder=holder)
        key = self._sched_class(resources, pg_key, env_key)
        self._queues.setdefault(key, collections.deque()).append(req)
        await self._dispatch_pending()
        return await fut

    @staticmethod
    def _sched_class(resources: Dict[str, float],
                     pg_key: Optional[Tuple[bytes, int]],
                     env_key: Optional[str] = None) -> tuple:
        """Scheduling-class key: resource shape + bundle + runtime-env
        fingerprint. All requests in a class draw the same amounts from the
        same pool AND can share pooled workers, so feasibility and worker
        reuse are properties of the CLASS, not the request."""
        shape = tuple(sorted((k, float(v)) for k, v in resources.items()
                             if v > scheduling.EPS))
        return (shape, pg_key, env_key)

    def _pending_count(self) -> int:
        return (sum(len(q) for q in self._queues.values())
                + sum(len(q) for q in self._infeasible.values()))

    def _backlog(self) -> List[dict]:
        """Per-class backlog for heartbeats/stats (autoscaler demand feed;
        GcsAutoscalerStateManager analog)."""
        out = []
        for key, q in list(self._queues.items()) + \
                list(self._infeasible.items()):
            if q:
                out.append({"shape": dict(key[0]), "count": len(q),
                            "infeasible": key in self._infeasible})
        return out

    async def handle_cancel_lease_request(self, conn, req_id: bytes):
        """Cancel a lease request: still-queued -> dequeue; already granted
        (grant raced the caller's timeout) -> reclaim the worker."""
        for table in (self._queues, self._infeasible):
            for key, q in list(table.items()):
                for req in q:
                    if req.req_id == req_id:
                        q.remove(req)
                        if not q:
                            del table[key]
                        if not req.fut.done():
                            req.fut.set_result({"ok": False, "canceled": True})
                        return {"ok": True}
        for w in self._workers.values():
            if w.req_id == req_id and w.lease_id is not None:
                scheduling.add(self._lease_pool(w.pg_key), w.lease_resources)
                w.lease_id = None
                w.lease_resources = {}
                w.pg_key = None
                w.req_id = None
                w.busy_since = None
                w.leased_to = ""
                if not w.is_actor:
                    self._park_idle(w)
                await self._dispatch_pending()
                return {"ok": True, "reclaimed": True}
        return {"ok": False}

    async def handle_cancel_lease_batch(self, conn, req_ids: List[bytes]):
        """Batched cancel fan-in: one frame retires a whole pump's worth of
        extra in-flight lease requests instead of one RPC per req_id."""
        canceled = 0
        for rid in req_ids:
            r = await self.handle_cancel_lease_request(conn, rid)
            if r.get("ok"):
                canceled += 1
        return {"ok": True, "canceled": canceled}

    def _any_bundle_index(self, pg_id: bytes) -> Optional[int]:
        for (gid, idx), b in self._bundles.items():
            if gid == pg_id and b["committed"]:
                return idx
        return None

    async def _dispatch_pending(self):
        """Per-class round-robin dispatch (ScheduleAndDispatchTasks analog,
        cluster_task_manager.cc:188 + local_task_manager.cc:57).

        Each pass walks the scheduling classes once; within a class, grants
        run strictly FIFO from the head while the class's pool fits the
        shape. A class whose head can't be placed locally either blocks
        (in-use resources will free up), spills its whole queue (another
        node's total capacity fits — the shape is identical for every
        member), or parks as infeasible. Classes that received a grant
        rotate to the back so a hot shape can't starve the rest."""
        progressed = True
        while progressed:
            progressed = False
            for key in list(self._queues.keys()):
                q = self._queues.get(key)
                if not q:
                    self._queues.pop(key, None)
                    continue
                if self._draining and key[1] is None:
                    # Draining: new non-PG work re-routes to peers instead
                    # of starting here and dying at the deadline. (PG-bundle
                    # classes stay — the bundle is committed on this node.)
                    del self._queues[key]
                    asyncio.ensure_future(
                        self._resolve_spillback_class(key, q))
                    continue
                granted_here = 0
                while q:
                    req = q[0]
                    if req.fut.done():  # canceled under us
                        q.popleft()
                        continue
                    try:
                        pool = self._lease_pool(req.pg_key)
                    except RuntimeError as e:
                        q.popleft()
                        if not req.fut.done():
                            req.fut.set_result({"ok": False, "error": str(e)})
                        continue
                    if not scheduling.fits(pool, req.resources):
                        cap = (self.total_resources if req.pg_key is None
                               else self._bundles[req.pg_key]["resources"])
                        if not scheduling.fits(cap, req.resources):
                            # Never placeable here: spill/park the whole
                            # class (identical shape -> identical verdict).
                            del self._queues[key]
                            asyncio.ensure_future(
                                self._resolve_spillback_class(key, q))
                        break  # class blocked locally; next class
                    scheduling.subtract(pool, req.resources)
                    q.popleft()
                    granted_here += 1
                    progressed = True
                    metric_defs.LEASES_GRANTED.inc()
                    logger.debug("dispatch: granting lease res=%s avail=%s",
                                 req.resources, self.available)
                    asyncio.ensure_future(self._grant_lease(req))
                if not self._queues.get(key):
                    self._queues.pop(key, None)
                elif granted_here:
                    self._queues.move_to_end(key)
        _PENDING_LEASES.set(self._pending_count())

    async def _resolve_spillback_class(self, key: tuple, q: "collections.deque"):
        """A class that can never run locally: route every member to the
        best remote node, or park the class as infeasible until the cluster
        view changes (reference keeps infeasible tasks queued and feeds
        them to the autoscaler rather than erroring,
        cluster_task_manager.cc infeasible_tasks_)."""
        reply = self._spillback_for_shape(dict(key[0]))
        if reply is None:
            # The gossip view can lag a just-registered node; confirm against
            # the GCS before declaring the class infeasible cluster-wide.
            try:
                self._cluster_view = await self.gcs.call("get_nodes")
                reply = self._spillback_for_shape(dict(key[0]))
            except Exception:
                pass
        live = collections.deque(r for r in q if not r.fut.done())
        if reply is None:
            if live:
                logger.warning(
                    "lease class %s infeasible cluster-wide; parking %d "
                    "request(s) until resources appear", key[0], len(live))
                old = self._infeasible.get(key)
                if old:
                    old.extend(live)
                else:
                    self._infeasible[key] = live
                _PENDING_LEASES.set(self._pending_count())
            return
        for req in live:
            if not req.fut.done():
                metric_defs.LEASES_SPILLED.inc()
                req.fut.set_result(reply)

    def _spillback_for_shape(self, resources: Dict[str, float]) -> Optional[dict]:
        """Best remote node whose TOTAL capacity fits the shape
        (HandleRequestWorkerLease spillback reply,
        cluster_resource_scheduler.cc:149 GetBestSchedulableNode), or None."""
        candidates = [
            n for n in self._cluster_view
            if n.get("alive") and not n.get("draining")
            and n["node_id"] != self.node_id
            and scheduling.fits(n["resources"], resources)]
        if not candidates:
            return None
        best = min(candidates, key=lambda n: scheduling.utilization_score(
            n["resources"], n.get("available", n["resources"]), resources))
        return {"ok": False, "spillback": tuple(best["address"]),
                "spillback_node": best["node_id"]}

    def _retry_infeasible(self):
        """Cluster view changed: re-queue parked classes that some node's
        total capacity now satisfies (or that now fit locally)."""
        for key in list(self._infeasible.keys()):
            shape = dict(key[0])
            cap = self.total_resources if key[1] is None else \
                self._bundles.get(key[1], {}).get("resources", {})
            if (scheduling.fits(cap, shape)
                    or self._spillback_for_shape(shape) is not None):
                q = self._infeasible.pop(key)
                old = self._queues.get(key)
                if old:
                    old.extend(q)
                else:
                    self._queues[key] = q
                asyncio.ensure_future(self._dispatch_pending())

    async def _grant_lease(self, req: PendingLease):
        try:
            w = None
            if not req.for_actor:
                # runtime_env-keyed reuse: only a worker that ran the SAME
                # env (or a fresh prestarted one, env_key None) is eligible
                # — process state from another env must not leak in. Exact
                # matches win over fresh workers so the fresh pool stays
                # available for other envs.
                for want_fresh in (False, True):
                    for cand in reversed(self._idle):
                        if cand.env_key == (None if want_fresh
                                            else req.env_key):
                            self._idle.remove(cand)
                            w = cand
                            break
                    if w is not None:
                        break
            if w is None:
                w = self._spawn_worker()
            w.env_key = req.env_key
            if not w.ready.is_set():  # warm worker: skip the timer+task
                await asyncio.wait_for(w.ready.wait(), timeout=120)
            if w.address is None:
                raise RuntimeError("worker died during startup")
            w.lease_id = os.urandom(8)
            w.lease_resources = dict(req.resources)
            w.pg_key = req.pg_key
            w.is_actor = req.for_actor
            w.req_id = req.req_id
            w.leased_to = req.holder or ""
            w.busy_since = time.monotonic()
            if not req.fut.done():
                logger.debug("grant_lease: worker=%s addr=%s", w.worker_id.hex()[:8], w.address)
                req.fut.set_result({
                    "ok": True, "lease_id": w.lease_id, "worker_id": w.worker_id,
                    "worker_address": w.address, "node_id": self.node_id,
                })
        except Exception as e:
            scheduling.add(self._lease_pool(req.pg_key), req.resources)
            if not req.fut.done():
                req.fut.set_result({"ok": False, "error": repr(e)})

    async def handle_return_worker(self, conn, lease_id: bytes, worker_dead: bool = False):
        logger.debug("return_worker: lease=%s avail=%s", lease_id.hex()[:8], self.available)
        for w in self._workers.values():
            if w.lease_id == lease_id:
                scheduling.add(self._lease_pool(w.pg_key), w.lease_resources)
                w.lease_id = None
                w.lease_resources = {}
                w.pg_key = None
                w.busy_since = None
                w.leased_to = ""
                if worker_dead:
                    try:
                        w.proc.terminate()
                    except Exception:
                        pass
                elif not w.is_actor:
                    self._park_idle(w)
                await self._dispatch_pending()
                return {"ok": True}
        return {"ok": False}

    async def handle_mark_actor(self, conn, worker_id: bytes, actor_id: bytes):
        w = self._workers.get(worker_id)
        if w is None:
            return {"ok": False}
        w.is_actor = True
        w.actor_id = actor_id
        return {"ok": True}

    async def handle_kill_worker(self, conn, worker_id: bytes, force: bool = True):
        w = self._workers.get(worker_id)
        if w is None:
            return {"ok": False}
        try:
            w.proc.kill() if force else w.proc.terminate()
        except Exception:
            pass
        return {"ok": True}

    # ---- placement group bundles: 2PC target (Prepare/Commit) ------------

    async def handle_prepare_bundle(self, conn, pg_id: bytes, bundle_index: int,
                                    resources: Dict[str, float]):
        key = (pg_id, bundle_index)
        if key in self._bundles:
            return {"ok": True}  # idempotent retry
        if self._draining:
            # A bundle prepared here would be killed at the drain deadline;
            # refusing makes the PG planner pick a live node (its own plan
            # already excludes draining nodes — this closes the race).
            return {"ok": False, "error": "node draining"}
        if not scheduling.fits(self.available, resources):
            return {"ok": False, "error": "insufficient resources at prepare"}
        scheduling.subtract(self.available, resources)
        self._bundles[key] = {"resources": dict(resources),
                              "available": dict(resources), "committed": False}
        return {"ok": True}

    async def handle_commit_bundle(self, conn, pg_id: bytes, bundle_index: int):
        b = self._bundles.get((pg_id, bundle_index))
        if b is None:
            return {"ok": False}
        b["committed"] = True
        self._retry_infeasible()
        await self._dispatch_pending()
        return {"ok": True}

    async def handle_cancel_bundle(self, conn, pg_id: bytes, bundle_index: int):
        b = self._bundles.pop((pg_id, bundle_index), None)
        if b is not None:
            scheduling.add(self.available, b["resources"])
        return {"ok": True}

    async def handle_return_bundle(self, conn, pg_id: bytes, bundle_index: int):
        b = self._bundles.pop((pg_id, bundle_index), None)
        if b is not None:
            scheduling.add(self.available, b["resources"])
            # Kill workers still leased inside the bundle.
            for w in list(self._workers.values()):
                if w.pg_key == (pg_id, bundle_index):
                    try:
                        w.proc.terminate()
                    except Exception:
                        pass
        await self._dispatch_pending()
        return {"ok": True}

    # ---- introspection ----------------------------------------------------

    @property
    def _pull_sem(self):
        """Admission control for serving cross-node reads: bound concurrent
        chunk reads so a broadcast storm cannot starve the raylet's loop
        (PullManager admission analog, pull_manager.h:51)."""
        sem = getattr(self, "_pull_sem_obj", None)
        if sem is None:
            from ray_tpu.config import cfg

            sem = self._pull_sem_obj = asyncio.Semaphore(
                cfg().pull_admission_concurrency)
        return sem

    async def handle_pull_object(self, conn, oid: bytes, offset: int = 0,
                                 length: int = 4 << 20):
        """Chunked cross-node object read: shm store first, spill dir second
        (ObjectManager::HandlePull analog, object_manager.proto:60-61; push is
        pull-driven here — the requester re-calls until it has total bytes)."""
        async with self._pull_sem:
            metric_defs.PULLS_SERVED.inc()
            try:
                buf = self.store.get(oid, timeout=0)
            except Exception:
                rec = self.spill.read_chunk(oid, offset, length)
                if rec is None:
                    return {"found": False}
                total, metadata, chunk = rec
                return {"found": True, "total": total, "metadata": metadata,
                        "chunk": chunk}
            try:
                data = buf.data
                return {"found": True, "total": len(data),
                        "metadata": bytes(buf.metadata),
                        "chunk": bytes(data[offset:offset + length])}
            finally:
                buf.release()

    async def handle_pull_object_raw(self, conn, m, payload):
        """Zero-pickle twin of handle_pull_object: ObjChunkRequestMsg in,
        the chunk rides OUT as the raw-frame payload — the object bytes
        are copied once out of the arena and hit the socket without ever
        entering a pickle buffer."""
        from ray_tpu.runtime import wire

        req = wire.ObjChunkRequestMsg.decode(m)
        async with self._pull_sem:
            metric_defs.PULLS_SERVED.inc()
            try:
                buf = self.store.get(req.oid, timeout=0)
            except Exception:
                rec = self.spill.read_chunk(req.oid, req.offset, req.length)
                if rec is None:
                    return RawReply(
                        wire.ObjChunkReplyMsg(found=False).encode())
                total, metadata, chunk = rec
                return RawReply(
                    wire.ObjChunkReplyMsg(
                        found=True, total=total,
                        metadata=bytes(metadata or b"")).encode(),
                    chunk)
            try:
                data = buf.data
                return RawReply(
                    wire.ObjChunkReplyMsg(
                        found=True, total=len(data),
                        metadata=bytes(buf.metadata)).encode(),
                    bytes(data[req.offset:req.offset + req.length]))
            finally:
                buf.release()

    async def handle_put_object_raw(self, conn, m, payload):
        """Zero-pickle twin of handle_put_object: the chunk arrives as the
        raw-frame payload (a memoryview over the receive buffer) and is
        copied exactly once, into the store arena."""
        from ray_tpu.runtime import wire

        req = wire.ObjPutMsg.decode(m)
        r = await self.handle_put_object(
            conn, req.oid, payload, req.offset, req.total,
            metadata=req.metadata, seal=req.seal)
        return RawReply(wire.AckMsg(ok=bool(r.get("ok")),
                                    error=str(r.get("error") or ""),
                                    existed=bool(r.get("existed"))).encode())

    async def _pull_from(self, client: RpcClient, oid: bytes):
        """Whole-object pull from a peer raylet: raw-frame fast path with
        a legacy pickled fallback for old peers. Returns (buf, metadata)
        or None if the peer lost the object."""
        from ray_tpu.config import cfg
        from ray_tpu.runtime import wire

        chunk_bytes = cfg().pull_chunk_bytes
        try:
            buf, off, total, metadata = None, 0, 0, b""
            while True:
                mrep, payload = await client.call_raw(
                    "pull_object_raw",
                    m=wire.ObjChunkRequestMsg(oid=oid, offset=off,
                                              length=chunk_bytes).encode())
                rep = wire.ObjChunkReplyMsg.decode(mrep)
                if not rep.found:
                    return None
                if buf is None:
                    total, metadata = rep.total, rep.metadata
                    buf = bytearray(total)
                n = len(payload)
                buf[off:off + n] = payload
                off += n
                if off >= total:
                    return buf, metadata
                if n == 0:
                    raise RuntimeError("truncated pull")
        except RpcError as e:
            if "no handler" not in str(e):
                raise
        chunks, off, total, metadata = [], 0, None, b""
        while True:
            r = await client.call("pull_object", oid=oid, offset=off,
                                  length=chunk_bytes)
            if not r.get("found"):
                return None
            total = r["total"]
            metadata = r.get("metadata", b"")
            chunks.append(r["chunk"])
            off += len(r["chunk"])
            if off >= total:
                buf = bytearray(total)
                pos = 0
                for c in chunks:
                    buf[pos:pos + len(c)] = c
                    pos += len(c)
                return buf, metadata
            if not r["chunk"]:
                raise RuntimeError("truncated pull")

    async def handle_fetch_and_relay(self, conn, oid: bytes,
                                     source: Tuple[str, int],
                                     targets: List[Tuple[str, int]],
                                     fanout: int = 2):
        """Broadcast leg: pull `oid` from `source` into the local store, then
        fan the remaining `targets` out as subtrees relaying from THIS node —
        O(log n) depth, no single-source bottleneck (PushManager/broadcast
        analog, push_manager.h:30; the 1 GiB x 50-node envelope case)."""
        if not self.store.contains(oid):
            client = RpcClient(*tuple(source))
            try:
                await client.connect(timeout=15)
                rec = await self._pull_from(client, oid)
                if rec is None:
                    return {"ok": False, "error": "source lost the object"}
                data, metadata = rec
                try:
                    view = self.store.create(oid, len(data), metadata)
                    view[:] = data
                    view.release()
                    self.store.seal(oid)
                except ValueError:
                    pass  # concurrent create: someone else sealed it
            finally:
                await client.close()
        if not targets:
            return {"ok": True, "relayed": 0}
        # Split targets into `fanout` subtrees, each led by its first node.
        groups = [targets[i::fanout] for i in range(fanout)]
        subcalls = []
        for g in groups:
            if not g:
                continue
            leader, rest = tuple(g[0]), [tuple(t) for t in g[1:]]
            subcalls.append(self._relay_to(oid, leader, rest, fanout))
        results = await asyncio.gather(*subcalls, return_exceptions=True)
        failed = [r for r in results
                  if isinstance(r, Exception) or not r.get("ok")]
        if failed:
            return {"ok": False, "error": f"{len(failed)} subtree(s) failed"}
        return {"ok": True, "relayed": len(targets)}

    async def _relay_to(self, oid, leader, rest, fanout):
        client = RpcClient(*leader)
        try:
            await client.connect(timeout=15)
            return await client.call(
                "fetch_and_relay", oid=oid, source=self.server.address,
                targets=rest, fanout=fanout, timeout=600)
        finally:
            await client.close()

    async def handle_put_object(self, conn, oid: bytes, chunk: bytes,
                                offset: int, total: int,
                                metadata: bytes = b"", seal: bool = False):
        """Remote-client write path: a store-less driver (Ray Client analog,
        util/client/) materializes put() objects into this node's store over
        chunked RPC; the final chunk seals."""
        if self.store.contains(oid):
            return {"ok": True, "existed": True}
        try:
            if offset == 0:
                self.store.abort(oid)  # reclaim a crashed partial create
                view = self.store.create(oid, total, metadata)
                self._client_puts = getattr(self, "_client_puts", {})
                self._client_puts[oid] = view
            view = self._client_puts[oid]
            view[offset:offset + len(chunk)] = chunk
            if seal:
                view.release()
                self.store.seal(oid)
                del self._client_puts[oid]
            return {"ok": True}
        except Exception as e:
            v = getattr(self, "_client_puts", {}).pop(oid, None)
            if v is not None:
                try:
                    v.release()
                except Exception:
                    pass
                self.store.abort(oid)
            return {"ok": False, "error": repr(e)}

    async def handle_free_object(self, conn, oid: bytes):
        """Owner-directed delete of a local copy (delete-on-zero leg of the
        ownership protocol; reference: plasma Delete + spilled-file cleanup
        in local_object_manager)."""
        try:
            self.store.delete(oid)
        except Exception:
            pass
        try:
            if self.spill is not None:
                self.spill.delete(oid)
        except Exception:
            pass
        return {"ok": True}

    async def handle_node_stats(self, conn):
        return {
            "node_id": self.node_id,
            "resources": self.total_resources,
            "available": self.available,
            "num_workers": len(self._workers),
            "num_idle": len(self._idle),
            "num_pending_leases": self._pending_count(),
            "backlog": self._backlog(),
            "object_store_used": self.store.used if self.store else 0,
            "object_store_capacity": self.store.capacity if self.store else 0,
            "spilled_bytes": (self.spill.spilled_bytes()
                              if self.spill else 0),
            "draining": self._draining,
            "drain_reason": self._drain_reason,
            "drain_deadline": self._drain_deadline,
            "drain_progress": dict(self._drain_progress),
            "bundles": [
                {"pg_id": k[0], "bundle_index": k[1], "committed": v["committed"],
                 "resources": v["resources"], "available": v["available"]}
                for k, v in self._bundles.items()],
        }

    async def handle_dump_spans(self, conn):
        """Cluster trace aggregation fan-in: this raylet's own span ring
        plus every ready local worker's (each worker runtime answers the
        same `dump_spans` RPC). Per-worker failures are dropped — a dying
        worker must not block the cluster timeline. Spans stitch across
        processes by the trace/span ids in their `args`, not by clock."""
        from ray_tpu.util import tracing

        node = self.node_id.hex()[:12]
        procs = [{"label": f"raylet:{node}", "spans": tracing.get_spans()}]

        async def fetch(w):
            client = RpcClient(*w.address)
            await client.connect(timeout=5)
            try:
                spans = await client.call("dump_spans", timeout=10)
                return {"label": f"worker:{node}:{w.worker_id.hex()[:8]}",
                        "spans": spans}
            finally:
                await client.close()

        results = await asyncio.gather(
            *(fetch(w) for w in list(self._workers.values())
              if w.address is not None),
            return_exceptions=True)
        procs.extend(r for r in results if isinstance(r, dict))
        return {"processes": procs}

    async def handle_dump_stacks(self, conn):
        """Hang diagnosis fan-in: this raylet's own annotated stacks plus
        every ready local worker's (each worker runtime answers the same
        `dump_stacks` RPC). Per-worker failures are dropped — a wedged or
        dying worker must not block the cluster-wide dump."""
        from ray_tpu.utils import debug

        node = self.node_id.hex()[:12]
        procs = [debug.render_stacks(f"raylet:{node}")]

        async def fetch(w):
            client = RpcClient(*w.address)
            await client.connect(timeout=5)
            try:
                proc = await client.call("dump_stacks", timeout=10)
                proc["label"] = f"{proc.get('label') or 'worker'} " \
                                f"node:{node}"
                return proc
            finally:
                await client.close()

        results = await asyncio.gather(
            *(fetch(w) for w in list(self._workers.values())
              if w.address is not None),
            return_exceptions=True)
        procs.extend(r for r in results if isinstance(r, dict))
        return {"processes": procs}

    async def handle_list_objects(self, conn, limit: int = 1000):
        """Cluster memory fan-in: every local worker's owner-side object
        table (the `state.summarize_objects()` building block). Workers
        that don't answer are skipped."""
        async def fetch(w):
            client = RpcClient(*w.address)
            await client.connect(timeout=5)
            try:
                return await client.call("list_objects", limit=limit,
                                         timeout=10)
            finally:
                await client.close()

        results = await asyncio.gather(
            *(fetch(w) for w in list(self._workers.values())
              if w.address is not None),
            return_exceptions=True)
        rows = []
        node = self.node_id.hex()[:12]
        for r in results:
            if isinstance(r, list):
                for row in r:
                    row.setdefault("node", node)
                rows.extend(r)
        return {"objects": rows}
