"""Raylet process entrypoint (src/ray/raylet/main.cc analog)."""

import argparse
import asyncio
import json
import logging
import os


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--resources", default="{}")
    parser.add_argument("--labels", default="{}")
    parser.add_argument("--object-store-memory", type=int,
                        default=2 << 30)
    parser.add_argument("--is-head", action="store_true")
    parser.add_argument("--ready-file", default=None)
    parser.add_argument("--worker-env", default="{}")
    args = parser.parse_args()

    from ray_tpu.utils.debug import register_stack_dump_signal

    register_stack_dump_signal()
    logging.basicConfig(
        level=os.environ.get("RAY_TPU_LOG_LEVEL", "INFO"),
        format="[raylet %(asctime)s %(levelname)s %(name)s] %(message)s")

    from ray_tpu.runtime.raylet.raylet import Raylet

    host, port = args.gcs_address.rsplit(":", 1)

    async def run():
        import signal

        raylet = Raylet(
            gcs_address=(host, int(port)),
            session_dir=args.session_dir,
            resources=json.loads(args.resources),
            labels=json.loads(args.labels),
            object_store_memory=args.object_store_memory,
            is_head=args.is_head,
            worker_env=json.loads(args.worker_env),
        )
        await raylet.start()
        loop = asyncio.get_event_loop()
        loop.add_signal_handler(signal.SIGTERM, raylet._shutdown.set)
        loop.add_signal_handler(signal.SIGINT, raylet._shutdown.set)
        if args.ready_file:
            tmp = args.ready_file + ".tmp"
            with open(tmp, "w") as f:
                f.write(json.dumps({
                    "node_id": raylet.node_id.hex(),
                    "address": list(raylet.server.address),
                    "store_path": raylet.store_path,
                }))
            os.replace(tmp, args.ready_file)
        await raylet.run_forever()

    asyncio.run(run())


if __name__ == "__main__":
    main()
