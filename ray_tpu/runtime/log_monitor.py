"""Log monitor: tail worker log files and publish lines to GCS pubsub.

Reference analog: python/ray/_private/log_monitor.py:103 (LogMonitor tails
per-worker files, publishes to GCS pubsub, driver prints with a
``(pid=..., ip=...)`` prefix). Runs inside the raylet process here — one
tailer per node over ``<session>/logs/*.log``.
"""

from __future__ import annotations

import asyncio
import glob
import logging
import os
from typing import Dict

logger = logging.getLogger(__name__)

LOG_CHANNEL = "worker_logs"


class LogMonitor:
    """Polls the session log dir; publishes new lines via a callback."""

    def __init__(self, logs_dir: str, publish, node_id_hex: str,
                 poll_interval: float = 0.5, pattern: str = "worker_*.log"):
        self.logs_dir = logs_dir
        self.pattern = pattern
        self.publish = publish          # async fn(channel, message)
        self.node_id_hex = node_id_hex
        self.poll_interval = poll_interval
        self._offsets: Dict[str, int] = {}

    def _scan_once_sync(self):
        """Collect (fname, [lines]) updates since the previous scan."""
        updates = []
        for path in sorted(glob.glob(os.path.join(self.logs_dir, self.pattern))):
            try:
                size = os.path.getsize(path)
                offset = self._offsets.get(path, 0)
                if size <= offset:
                    if size < offset:      # truncated/rotated: restart
                        self._offsets[path] = 0
                    continue
                with open(path, "rb") as f:
                    f.seek(offset)
                    chunk = f.read(size - offset)
                # Only consume complete lines; partial tails wait for the
                # writer to finish them.
                last_nl = chunk.rfind(b"\n")
                if last_nl < 0:
                    continue
                self._offsets[path] = offset + last_nl + 1
                lines = chunk[:last_nl].decode("utf-8", "replace").splitlines()
                if lines:
                    updates.append((os.path.basename(path), lines))
            except OSError:
                continue
        return updates

    async def run(self, shutdown: asyncio.Event):
        while not shutdown.is_set():
            try:
                for fname, lines in self._scan_once_sync():
                    await self.publish(LOG_CHANNEL, {
                        "node_id": self.node_id_hex,
                        "file": fname,
                        "lines": lines,
                    })
            except Exception:
                logger.exception("log monitor scan failed")
            await asyncio.sleep(self.poll_interval)


def attach_driver_log_stream(core) -> None:
    """Driver-side: subscribe to the worker-log pubsub channel and mirror
    lines to this process's stderr (log_monitor.py -> driver stdout path in
    the reference). Enabled unless RAY_TPU_LOG_TO_DRIVER=0."""
    import sys

    from ray_tpu.runtime.rpc import RpcClient

    if os.environ.get("RAY_TPU_LOG_TO_DRIVER", "1") == "0":
        return

    async def on_push(method, data):
        if method != "pubsub" or data.get("channel") != LOG_CHANNEL:
            return
        msg = data["message"]
        prefix = f"({msg['file'].rsplit('.',1)[0]}, node={msg['node_id'][:8]})"
        for line in msg["lines"]:
            print(f"{prefix} {line}", file=sys.stderr)

    async def _resubscribe(client):
        await client._call_once("subscribe", 30, dict(channels=[LOG_CHANNEL]))

    async def _connect():
        host, port = core.gcs.host, core.gcs.port
        client = RpcClient(host, port, on_push=on_push, auto_reconnect=True,
                           on_reconnect=_resubscribe)
        await client.connect(timeout=30)
        await client.call("subscribe", channels=[LOG_CHANNEL])
        return client

    try:
        core._log_stream_client = core.io.run(_connect())
    except Exception:
        logger.warning("driver log streaming unavailable", exc_info=True)
