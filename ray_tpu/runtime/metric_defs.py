"""Native runtime metric definitions: the central table of what the
runtime itself measures.

Reference analog: src/ray/stats/metric_defs.cc (every native metric —
task counts, scheduler state, object store usage, gRPC latencies — defined
in one place and exported through the metrics agent). Ours defines the
runtime metrics once; components import and bump them, and every process's
metrics ride the existing snapshot/Prometheus path (util/metrics.py +
dashboard /metrics).
"""

from __future__ import annotations

from ray_tpu.util.metrics import Counter, Gauge, Histogram

# -- core worker -----------------------------------------------------------

TASKS_SUBMITTED = Counter(
    "ray_tpu_tasks_submitted_total",
    "task submissions from this process (normal tasks)")
TASKS_FINISHED = Counter(
    "ray_tpu_tasks_finished_total",
    "tasks whose result landed back at this owner, by outcome",
    tag_keys=("outcome",))                       # ok | error | retried
ACTOR_CALLS = Counter(
    "ray_tpu_actor_calls_total", "actor method submissions")
OBJECTS_OWNED = Gauge(
    "ray_tpu_owned_objects", "objects this worker currently owns")
SPILLED_BYTES = Counter(
    "ray_tpu_spilled_bytes_total", "bytes spilled to external storage")
RESTORED_BYTES = Counter(
    "ray_tpu_restored_bytes_total", "bytes restored from external storage")
RECONSTRUCTIONS = Counter(
    "ray_tpu_object_reconstructions_total",
    "lineage re-executions triggered by lost objects")
TASK_EVENTS_DROPPED = Counter(
    "ray_tpu_task_events_dropped_total",
    "task state events trimmed from this worker's buffer before flush "
    "(buffer overflow; raise task_events_max or lower the flush interval)")

# -- raylet ----------------------------------------------------------------

LEASES_GRANTED = Counter(
    "ray_tpu_leases_granted_total", "worker leases granted by this raylet")
LEASES_SPILLED = Counter(
    "ray_tpu_leases_spilled_total",
    "lease requests redirected to another node (spillback)")
WORKERS_STARTED = Counter(
    "ray_tpu_workers_started_total", "worker processes spawned")
OOM_KILLS = Counter(
    "ray_tpu_oom_kills_total", "workers killed by the memory monitor")
PENDING_LEASES = Gauge(
    "ray_tpu_pending_leases", "queued lease requests on this raylet")
OBJECT_STORE_USED = Gauge(
    "ray_tpu_object_store_used_bytes",
    "bytes occupied in this node's shared object-store arena",
    tag_keys=("node",))
OBJECT_STORE_CAPACITY = Gauge(
    "ray_tpu_object_store_capacity_bytes",
    "total size of this node's shared object-store arena",
    tag_keys=("node",))
OBJECT_STORE_SPILLED = Gauge(
    "ray_tpu_object_store_spilled_bytes",
    "bytes currently resident in this node's spill directory",
    tag_keys=("node",))
NODES_DRAINING = Gauge(
    "ray_tpu_nodes_draining",
    "1 while this node is draining toward an announced retirement "
    "deadline (advance-notice preemption), 0 otherwise",
    tag_keys=("node",))

# -- object plane ----------------------------------------------------------

PULLS_SERVED = Counter(
    "ray_tpu_object_pulls_served_total",
    "cross-node object chunk reads served")
PULL_LATENCY = Histogram(
    "ray_tpu_object_pull_seconds", "end-to-end remote object pull latency",
    boundaries=[0.001, 0.01, 0.05, 0.25, 1.0, 5.0, 30.0])

# -- data ------------------------------------------------------------------

DATA_BACKPRESSURE = Counter(
    "ray_tpu_data_backpressure_total",
    "dataset producer throttle ENGAGEMENTS (idle->throttled transitions) "
    "under object-store pressure")
DATA_BLOCKS_PRODUCED = Counter(
    "ray_tpu_data_blocks_produced_total",
    "blocks pulled through streaming data-plane producers (all consumers "
    "on this process)")
DATA_INPUT_WAIT_MS = Histogram(
    "ray_tpu_data_input_wait_ms",
    "time a streaming consumer blocked in next(batch) — near-zero means "
    "the pipeline fully hid ingestion behind compute",
    boundaries=[0.1, 0.5, 1, 5, 10, 50, 100, 500, 1000])
DATA_BACKLOG_DEPTH = Gauge(
    "ray_tpu_data_backlog_depth",
    "produced-but-unconsumed batches in this process's streaming rings "
    "(bounded by prefetch_batches — the backpressure proof)")

# -- collectives -----------------------------------------------------------
# Per-(op, algo) traffic and latency of the out-of-graph collective plane.
# `algo` distinguishes the chunked ring data plane from the legacy rank-0
# hub; components bind() a tag set once and bump the bound handles so the
# per-chunk accounting stays off the hot path.

COLLECTIVE_OPS = Counter(
    "ray_tpu_collective_ops_total",
    "out-of-graph collective operations completed",
    tag_keys=("op", "algo"))
COLLECTIVE_BYTES_SENT = Counter(
    "ray_tpu_collective_bytes_sent_total",
    "bytes sent on collective data-plane links",
    tag_keys=("op", "algo"))
COLLECTIVE_BYTES_RECV = Counter(
    "ray_tpu_collective_bytes_recv_total",
    "bytes received on collective data-plane links",
    tag_keys=("op", "algo"))
COLLECTIVE_OP_LATENCY = Histogram(
    "ray_tpu_collective_op_seconds",
    "end-to-end latency of out-of-graph collective ops",
    boundaries=[0.001, 0.01, 0.05, 0.25, 1.0, 5.0, 30.0],
    tag_keys=("op", "algo"))

# -- serve / llm -----------------------------------------------------------

SERVE_REQUESTS = Counter(
    "ray_tpu_serve_requests_total", "requests routed through handles",
    tag_keys=("deployment",))
LLM_TOKENS_GENERATED = Counter(
    "ray_tpu_llm_tokens_generated_total", "tokens sampled by LLM engines")
LLM_STEP_COMPILES = Counter(
    "ray_tpu_llm_step_compiles_total",
    "XLA compiles triggered by new step-shape signatures (warmup pays "
    "these; any growth in the steady-state loop is a silent-recompile "
    "stall worth chasing)")

# Speculative decoding (engine n-gram drafts + unified-tick acceptance
# sampling): the accepted/proposed ratio is the speculation win per
# deployment — near 1.0 means the draft source predicts the model well,
# near 0 means verify launches are wasted work.
LLM_SPEC_PROPOSED = Counter(
    "ray_tpu_llm_spec_proposed_total",
    "draft tokens submitted to speculative verification")
LLM_SPEC_ACCEPTED = Counter(
    "ray_tpu_llm_spec_accepted_total",
    "draft tokens accepted by speculative verification")

# Per-replica engine depth + KV occupancy: the same numbers
# LLMServer.engine_stats() feeds the router's pow2/admission logic, pushed
# as gauges so dashboards see what the router sees.
LLM_RUNNING = Gauge(
    "ray_tpu_llm_running", "requests in decode on this replica",
    tag_keys=("replica",))
LLM_WAITING = Gauge(
    "ray_tpu_llm_waiting", "requests queued before prefill on this replica",
    tag_keys=("replica",))
LLM_PREFILLING = Gauge(
    "ray_tpu_llm_prefilling", "requests mid-chunked-prefill on this replica",
    tag_keys=("replica",))
LLM_KV_FREE_BLOCKS = Gauge(
    "ray_tpu_llm_kv_free_blocks", "free KV cache pages on this replica",
    tag_keys=("replica",))
LLM_KV_TOTAL_BLOCKS = Gauge(
    "ray_tpu_llm_kv_total_blocks", "total KV cache pages on this replica",
    tag_keys=("replica",))
LLM_PREFIX_HITS = Gauge(
    "ray_tpu_llm_prefix_hits", "prefix-cache block hits (cumulative)",
    tag_keys=("replica",))
LLM_PREFIX_TOKENS_SAVED = Gauge(
    "ray_tpu_llm_prefix_tokens_saved",
    "prompt tokens skipped via prefix cache (cumulative)",
    tag_keys=("replica",))
LLM_TOKENS_PER_S = Gauge(
    "ray_tpu_llm_tokens_per_s", "decode throughput EWMA on this replica",
    tag_keys=("replica",))

# Router plane (llm/router.py) + disaggregated KV handoffs (llm/disagg.py).
LLM_ROUTER_SHED = Counter(
    "ray_tpu_llm_router_shed_total",
    "requests shed by SLO admission (projected TTFT over the SLO)",
    tag_keys=("deployment",))
LLM_ROUTER_AFFINITY = Counter(
    "ray_tpu_llm_router_affinity_total",
    "router picks by prefix/session-affinity outcome",
    tag_keys=("outcome",))                       # hit | miss
LLM_KV_HANDOFFS = Counter(
    "ray_tpu_llm_kv_handoffs_total",
    "prefill->decode KV page handoffs adopted")

# Per-request latency attribution (llm/engine.py _finish_trace): each
# finished request decomposes its TTFT into queue/prefill/handoff time and
# its mean inter-token gap into decode/stall time — the histogram twins of
# the per-request trace spans, so fleet-wide tail regressions name a phase
# before anyone pulls a single trace.
LLM_TTFT_BREAKDOWN_MS = Histogram(
    "ray_tpu_llm_ttft_breakdown_ms",
    "per-request time-to-first-token by phase: queue (submit->admit), "
    "prefill (admit->first token), handoff (disagg KV stream gaps)",
    boundaries=[0.1, 0.5, 1, 5, 10, 50, 100, 500, 1000, 5000],
    tag_keys=("phase",))                         # queue | prefill | handoff
LLM_ITL_BREAKDOWN_MS = Histogram(
    "ray_tpu_llm_itl_breakdown_ms",
    "per-request MEAN inter-token gap by phase: decode (engine ticks) and "
    "stall (migration pauses amortized over the request's gaps)",
    boundaries=[0.1, 0.5, 1, 5, 10, 50, 100, 500, 1000],
    tag_keys=("phase",))                         # decode | stall

# Fleet resilience (llm/router.py FleetSupervisor): failover replays,
# drain-plane session migrations, and the live-replica count the router's
# health tracker believes in. All roll up into
# state.summary()["llm_serving"] like every other ray_tpu_llm_* series.
LLM_FAILOVERS = Counter(
    "ray_tpu_llm_failovers_total",
    "in-flight requests replayed on a surviving replica after a failure",
    tag_keys=("deployment",))
LLM_SESSIONS_MIGRATED = Counter(
    "ray_tpu_llm_sessions_migrated_total",
    "live sessions moved replica->replica (KV pages over the drain plane)",
    tag_keys=("deployment",))
LLM_REPLICAS_HEALTHY = Gauge(
    "ray_tpu_llm_replicas_healthy",
    "replicas the router currently considers live and routable",
    tag_keys=("deployment",))

# Tiered KV prefix store (llm/prefix_store.py): tier="host" is the
# replica-local pinned-RAM spill pool, tier="store" the GCS-homed cluster
# table that survives replica death and restarts.
LLM_PREFIX_SPILLS = Counter(
    "ray_tpu_llm_prefix_spills_total",
    "prefix KV pages demoted into a store tier instead of being dropped",
    tag_keys=("tier",))                          # host | store
LLM_PREFIX_ADOPTIONS = Counter(
    "ray_tpu_llm_prefix_adoptions_total",
    "spilled prefix blocks re-adopted into an engine (re-prefill avoided)",
    tag_keys=("tier",))                          # host | store
LLM_PREFIX_STORE_BYTES = Gauge(
    "ray_tpu_llm_prefix_store_bytes",
    "bytes currently held in this replica's host prefix tier")
LLM_PREFIX_STALE_REJECTED = Counter(
    "ray_tpu_llm_prefix_stale_rejected_total",
    "spilled prefix entries refused at adoption (weights version mismatch)")

# Checkpoint plane (checkpoint/plane.py): the snapshot histogram is the
# train-step stall, the persist histogram is the background cost — the
# 5x-plus gap between them is the async plane's whole point.
CKPT_SNAPSHOT_MS = Histogram(
    "ray_tpu_ckpt_snapshot_ms",
    "device->host snapshot stall per save (the only part a train step "
    "waits for)",
    boundaries=[0.1, 0.5, 1, 5, 10, 50, 100, 500, 1000])
CKPT_PERSIST_MS = Histogram(
    "ray_tpu_ckpt_persist_ms",
    "background shard persist + commit duration per save",
    boundaries=[1, 5, 10, 50, 100, 500, 1000, 5000, 30000])
CKPT_BYTES = Counter(
    "ray_tpu_ckpt_bytes_total",
    "checkpoint bytes persisted by this process (per-rank shard bytes)")


ALL_METRICS = [v for v in list(globals().values())
               if isinstance(v, (Counter, Gauge, Histogram))]
