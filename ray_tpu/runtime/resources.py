"""Node resource detection, TPU-first.

Reference analog: python/ray/_private/accelerators/tpu.py:70
TPUAcceleratorManager (chip detection via /dev/accel* | /dev/vfio/*, pod-type
metadata, TPU_VISIBLE_CHIPS isolation) generalized into this framework's
first-class resource model: a node advertises {"CPU", "memory", "TPU", ...}
plus labels ("tpu-pod-type", "tpu-slice", "tpu-worker-id") that the
scheduler/placement-group code uses for ICI-contiguous placement.
"""

from __future__ import annotations

import glob
import os
from typing import Dict, Optional, Tuple


def detect_tpu_chips() -> int:
    """Count local TPU chips. Test/override hook: RAY_TPU_FAKE_TPU_CHIPS."""
    fake = os.environ.get("RAY_TPU_FAKE_TPU_CHIPS")
    if fake:
        return int(fake)
    visible = os.environ.get("TPU_VISIBLE_CHIPS")
    if visible:
        return len([c for c in visible.split(",") if c.strip() != ""])
    accel = glob.glob("/dev/accel*")
    if accel:
        return len(accel)
    # /dev/vfio nodes are NOT TPU-specific (GPU passthrough binds vfio-pci
    # too): only trust them as chips when the environment says this host is
    # part of a TPU pod/slice.
    if detect_tpu_pod_type():
        vfio = glob.glob("/dev/vfio/[0-9]*")
        if vfio:
            return len(vfio)
    return 0


def detect_tpu_pod_type() -> Optional[str]:
    """Pod/slice type, e.g. "v5e-8". From env (GCE metadata requires egress;
    deployments set TPU_POD_TYPE / TPU_ACCELERATOR_TYPE)."""
    return os.environ.get("TPU_POD_TYPE") or os.environ.get("TPU_ACCELERATOR_TYPE")


def tpu_slice_labels() -> Dict[str, str]:
    labels: Dict[str, str] = {}
    pod = detect_tpu_pod_type()
    if pod:
        labels["tpu-pod-type"] = pod
        worker_id = os.environ.get("TPU_WORKER_ID", "0")
        labels["tpu-worker-id"] = worker_id
        # A host that owns all chips of a single-host slice advertises the
        # slice as intact: STRICT_PACK bundles prefer such nodes so a
        # bundle-per-chip group gets contiguous ICI.
        labels["tpu-slice"] = f"{pod}-{os.environ.get('TPU_NAME', 'local')}-{worker_id}"
    return labels


def node_resources(num_cpus: Optional[float] = None,
                   num_tpus: Optional[float] = None,
                   memory: Optional[int] = None,
                   resources: Optional[Dict[str, float]] = None) -> Dict[str, float]:
    out: Dict[str, float] = {}
    if num_cpus is None:
        num_cpus = float(os.cpu_count() or 1)
    out["CPU"] = float(num_cpus)
    if num_tpus is None:
        num_tpus = float(detect_tpu_chips())
    if num_tpus:
        out["TPU"] = float(num_tpus)
        pod = detect_tpu_pod_type()
        if pod:
            # Headline resource for slice-head scheduling, mirroring the
            # reference's "TPU-{pod_type}-head" custom resource.
            out[f"TPU-{pod}-head"] = 1.0
    if memory is None:
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemTotal:"):
                        memory = int(line.split()[1]) * 1024
                        break
        except OSError:
            memory = 0
    if memory:
        out["memory"] = float(memory)
    # Non-TPU accelerator families via the manager registry (GPU, plugins):
    # TPU stays first-class above; others contribute when present.
    from ray_tpu.runtime import accelerators as accel_mod

    for name, n in accel_mod.detect_accelerators().items():
        if name != "TPU" and name not in out:
            out[name] = n
    for k, v in (resources or {}).items():
        out[k] = float(v)
    return out


def visible_chip_env(chip_ids: Tuple[int, ...]) -> Dict[str, str]:
    """Env vars that confine a worker to specific chips (TPU_VISIBLE_CHIPS
    isolation, reference tpu.py set_current_process_visible_accelerator_ids)."""
    ids = ",".join(str(c) for c in chip_ids)
    return {
        "TPU_VISIBLE_CHIPS": ids,
        "TPU_CHIPS_PER_PROCESS_BOUNDS": f"1,{len(chip_ids)},1",
    }
