"""Asyncio RPC layer: framed-pickle request/reply + push channels.

Reference analog: src/ray/rpc/ (GrpcServer grpc_server.h:88, ClientCallManager
client_call.h, retryable_grpc_client.cc). The wire is a length-prefixed pickle
frame over TCP; the programming model mirrors gRPC async services: named
handlers on servers, awaitable calls on clients, plus server->client pushes
for pubsub. Transport is swappable behind these two classes.

Frame: [4-byte magic "RTP"+version][u32 length][pickle payload][16B MAC*]
Payload: (kind, msg_id, method, data)
  kind: 0 = request, 1 = reply, 2 = error reply, 3 = push (one-way)
A bad magic drops the connection (ProtocolMismatch) before any pickle runs.
*When a session token is set, connections mutually authenticate at accept
and every frame carries a keyed-blake2b MAC over (direction, seq, body),
verified before pickle.loads — see the wire-auth section below.
"""

from __future__ import annotations

import asyncio
import logging
import pickle
import struct
import threading
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

logger = logging.getLogger(__name__)


def _chaos_enabled() -> bool:
    """Fault injection (runtime/chaos.py) — active only when configured via
    RAY_TPU_CHAOS or programmatically; one cheap check on the hot path."""
    import os

    from ray_tpu.runtime import chaos as chaos_mod

    return (chaos_mod._instance is not None and chaos_mod._instance.enabled
            ) or bool(os.environ.get("RAY_TPU_CHAOS"))


# Wire format (the protobuf-IDL analog, src/ray/protobuf/): every frame is
# `magic+version | length | pickle(body)`. The magic rejects foreign/garbage
# connections at the first frame instead of failing inside pickle, and the
# embedded version turns a mixed-version cluster into a loud, diagnosable
# error instead of undefined unpickling behavior.
PROTOCOL_VERSION = 1
_MAGIC = b"RTP" + bytes([PROTOCOL_VERSION])
# Cross-language dialect: same framing/auth/MAC, body is the xlang binary
# envelope (runtime/xlang.py) instead of pickle — what non-Python peers
# (cpp/raytpu_client) speak. A connection switches to xlang replies after
# its first RTX frame.
_X_MAGIC = b"RTX" + bytes([PROTOCOL_VERSION])
# Raw dialect: the zero-pickle fast path for schema'd messages. Body is
# [u8 kind][u64 msg_id][u16 method_len][method utf8][u32 m_len][m][payload]
# where `m` is a wire.Message encoding (runtime/wire.py) and `payload` is
# out-of-band bulk bytes (object chunks) that reach the handler as a
# memoryview over the receive buffer — no pickle.dumps/loads anywhere on
# the path. Same MAC/auth rules as every other frame. Error replies stay
# pickled (rare path, carries real exceptions).
_R_MAGIC = b"RTR" + bytes([PROTOCOL_VERSION])
_R_PRE = struct.Struct("<BQH")
_R_MLEN = struct.Struct("<I")
_HDR = struct.Struct("<4sI")
KIND_REQUEST, KIND_REPLY, KIND_ERROR, KIND_PUSH = 0, 1, 2, 3
MAX_FRAME = 1 << 31


class Raw:
    """Raw-frame envelope: schema header bytes + out-of-band payload.

    Requests decoded off an RTR frame arrive at handlers as
    `handler(conn, m, payload)`; a handler returning a `Raw` (alias
    `RawReply`) gets its reply emitted as an RTR frame — end to end, the
    bulk payload is never pickled and never copied into a pickle buffer."""

    __slots__ = ("m", "payload")

    def __init__(self, m: bytes = b"", payload=b""):
        self.m = m
        self.payload = payload


RawReply = Raw

# ---------------------------------------------------------------- wire auth
#
# A pickle wire must earn what protobuf gets for free: anyone who can reach
# a port must NOT get arbitrary-code execution via pickle.loads — in EITHER
# direction. Every cluster session mints a random token (start_gcs, node.py)
# and each connection runs a MUTUAL challenge-response:
#
#   server -> client : "RTA"+ver + sc (32-byte challenge)
#   client -> server : cc (32-byte challenge) + HMAC(token, "c"+sc+cc)
#   server -> client : HMAC(token, "s"+sc+cc)
#
# The client proof gates the server (no pickle from unauthenticated
# clients); the server proof gates the client (a spoofed/hijacked endpoint
# — port reuse after a raylet dies, TCP injection — cannot feed the client
# pickle frames). Both sides then derive a per-session MAC key
# HMAC(token, "k"+sc+cc) and every frame carries a 16-byte
# blake2b(key=mac_key, direction+seq+body) tag verified BEFORE pickle.loads,
# so injected or replayed bytes are dropped at the framing layer. No token
# in the process -> auth is off (bare RpcServer unit tests); cluster
# processes always inherit the token via RAY_TPU_AUTH_TOKEN / the 0600
# session file.
_AUTH_MAGIC = b"RTA" + bytes([PROTOCOL_VERSION])
_CHALLENGE_SIZE = 32
_MAC_SIZE = 16
_session_token: Optional[bytes] = None
_token_loaded = False


def set_session_token(token: Optional[bytes]) -> None:
    global _session_token, _token_loaded
    _session_token = token or None
    _token_loaded = True


def get_session_token() -> Optional[bytes]:
    global _session_token, _token_loaded
    if not _token_loaded:
        import os

        tok = os.environ.get("RAY_TPU_AUTH_TOKEN", "")
        if not tok:
            # Same-host attach without the env var: read the latest
            # session's token file (written 0600 by node.ensure_auth_token).
            # NOTE: with multiple live sessions on one host this can be the
            # WRONG session's token — attach paths that know the GCS
            # address call load_token_for_address() first, which resolves
            # by address and pins the token explicitly.
            base = os.environ.get("RAY_TPU_TMPDIR", "/tmp/ray_tpu")
            path = os.path.join(base, "session_latest", "auth_token")
            try:
                with open(path) as f:
                    tok = f.read().strip()
            except OSError:
                tok = ""
        try:
            _session_token = bytes.fromhex(tok) if tok else None
        except ValueError as e:
            raise AuthError(
                "RAY_TPU_AUTH_TOKEN must be a hex string (64 hex chars for "
                f"the standard 32-byte token); got {len(tok)} chars") from e
        _token_loaded = True
    return _session_token


def load_token_for_address(host: str, port: int) -> bool:
    """Resolve the auth token for the session that owns host:port.

    Scans session dirs for a gcs_address record matching the address being
    attached to, so an attacher on a host running several clusters gets the
    RIGHT token instead of whatever session_latest points at. An explicit
    RAY_TPU_AUTH_TOKEN always wins (operator override). Returns True if a
    token was pinned."""
    import glob
    import os

    if os.environ.get("RAY_TPU_AUTH_TOKEN"):
        return False
    want = {f"{host}:{port}"}
    if host in ("127.0.0.1", "localhost", "0.0.0.0"):
        want = {f"{h}:{port}" for h in ("127.0.0.1", "localhost", "0.0.0.0")}
    base = os.environ.get("RAY_TPU_TMPDIR", "/tmp/ray_tpu")
    candidates = sorted(glob.glob(os.path.join(base, "session_*")),
                        key=lambda p: -os.path.getmtime(p)
                        if os.path.exists(p) else 0)
    for session in candidates:
        if os.path.basename(session) == "session_latest":
            continue
        try:
            with open(os.path.join(session, "gcs_address")) as f:
                addr = f.read().strip()
            if addr not in want:
                continue
            with open(os.path.join(session, "auth_token")) as f:
                tok = f.read().strip()
        except OSError:
            continue
        try:
            set_session_token(bytes.fromhex(tok))
            return True
        except ValueError:
            continue
    return False


def _hmac_answer(token: bytes, challenge: bytes) -> bytes:
    import hashlib
    import hmac as hmac_mod

    return hmac_mod.new(token, challenge, hashlib.sha256).digest()


def _client_proof(token: bytes, sc: bytes, cc: bytes) -> bytes:
    return _hmac_answer(token, b"c" + sc + cc)


def _server_proof(token: bytes, sc: bytes, cc: bytes) -> bytes:
    return _hmac_answer(token, b"s" + sc + cc)


def _session_mac_key(token: bytes, sc: bytes, cc: bytes) -> bytes:
    return _hmac_answer(token, b"k" + sc + cc)


class _FrameMac:
    """Per-connection frame authenticator (one per direction pair).

    The tag binds direction + monotonically increasing sequence + body, so a
    frame can't be injected, replayed, reordered, or reflected back. blake2b
    keyed mode (RFC 7693) — faster than HMAC-SHA256 on the hot path."""

    __slots__ = ("key", "send_dir", "recv_dir", "send_seq", "recv_seq")

    def __init__(self, key: bytes, is_client: bool):
        import hashlib  # noqa: F401  (ensures module is loaded before use)

        self.key = key
        self.send_dir = b"C" if is_client else b"S"
        self.recv_dir = b"S" if is_client else b"C"
        self.send_seq = 0
        self.recv_seq = 0

    def _tag(self, direction: bytes, seq: int, *parts) -> bytes:
        import hashlib

        m = hashlib.blake2b(key=self.key, digest_size=_MAC_SIZE)
        m.update(direction)
        m.update(seq.to_bytes(8, "little"))
        for part in parts:
            m.update(part)
        return m.digest()

    def seal(self, body: bytes) -> bytes:
        tag = self._tag(self.send_dir, self.send_seq, body)
        self.send_seq += 1
        return tag

    def seal_parts(self, *parts) -> bytes:
        """Seal a body supplied as segments (raw frames: head + payload)
        without concatenating — blake2b streams over each part."""
        tag = self._tag(self.send_dir, self.send_seq, *parts)
        self.send_seq += 1
        return tag

    def verify(self, body: bytes, tag: bytes) -> bool:
        import hmac as hmac_mod

        want = self._tag(self.recv_dir, self.recv_seq, body)
        self.recv_seq += 1
        return hmac_mod.compare_digest(want, tag)


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


class ProtocolMismatch(RpcError):
    pass


class AuthError(RpcError):
    pass


async def _read_frame(reader: asyncio.StreamReader,
                      mac: Optional[_FrameMac] = None,
                      conn: Optional["ServerConnection"] = None):
    hdr = await reader.readexactly(_HDR.size)
    magic, length = _HDR.unpack(hdr)
    if magic == _X_MAGIC and conn is not None:
        # Cross-language peer (servers only — Python clients never get RTX
        # replies). MAC still verifies before any decoding.
        if length > MAX_FRAME:
            raise RpcError(f"frame too large: {length}")
        body = await reader.readexactly(length)
        if mac is not None:
            tag = await reader.readexactly(_MAC_SIZE)
            if not mac.verify(body, tag):
                raise AuthError("frame MAC verification failed")
        from ray_tpu.runtime import xlang

        conn.xlang = True
        try:
            return xlang.decode_envelope(body)
        except Exception as e:
            # Foreign implementations are where malformed frames are the
            # EXPECTED failure mode: drop via the clean protocol path.
            raise ProtocolMismatch(f"malformed xlang frame: "
                                   f"{type(e).__name__}: {e}")
    if magic == _R_MAGIC:
        # Zero-pickle raw frame: header fields are fixed-width structs, the
        # schema bytes + bulk payload come back as views over the receive
        # buffer. Nothing here can execute code.
        if length > MAX_FRAME:
            raise RpcError(f"frame too large: {length}")
        body = await reader.readexactly(length)
        if mac is not None:
            tag = await reader.readexactly(_MAC_SIZE)
            if not mac.verify(body, tag):
                raise AuthError("frame MAC verification failed")
        kind, msg_id, mlen = _R_PRE.unpack_from(body, 0)
        off = _R_PRE.size
        method = str(body[off:off + mlen], "utf-8")
        off += mlen
        (m_len,) = _R_MLEN.unpack_from(body, off)
        off += _R_MLEN.size
        data = Raw(bytes(body[off:off + m_len]),
                   memoryview(body)[off + m_len:])
        return kind, (msg_id if kind != KIND_PUSH else None), method, data
    if magic != _MAGIC:
        if magic[:3] == b"RTR":
            raise ProtocolMismatch(
                f"peer speaks raw wire v{magic[3]}, this process speaks "
                f"v{PROTOCOL_VERSION}")
        if magic[:3] == b"RTX":
            raise ProtocolMismatch(
                f"peer speaks xlang wire v{magic[3]}, this process speaks "
                f"v{PROTOCOL_VERSION}" if magic[3] != PROTOCOL_VERSION
                else "xlang frames are only accepted by servers")
        if magic[:3] == b"RTA":
            raise ProtocolMismatch(
                "server requires wire authentication but this process has "
                "no session token (RAY_TPU_AUTH_TOKEN unset)")
        if magic[:3] == b"RTP":
            raise ProtocolMismatch(
                f"peer speaks ray_tpu wire protocol v{magic[3]}, this "
                f"process speaks v{PROTOCOL_VERSION}")
        raise ProtocolMismatch(f"not a ray_tpu peer (bad magic {magic!r})")
    if length > MAX_FRAME:
        raise RpcError(f"frame too large: {length}")
    body = await reader.readexactly(length)
    if mac is not None:
        tag = await reader.readexactly(_MAC_SIZE)
        if not mac.verify(body, tag):
            # Injected/replayed bytes on an authenticated connection: drop
            # the connection WITHOUT unpickling the body.
            raise AuthError("frame MAC verification failed")
    # Bulk data rides call_raw's RTR segment path, never this decoder.
    # graftlint: allow[hot-pickle] legacy control-frame codec
    return pickle.loads(body)


def _frame(obj, mac: Optional[_FrameMac] = None) -> bytes:
    # Raw-path payloads go through _write_raw as unpickled segments.
    # graftlint: allow[hot-pickle] legacy control-frame codec
    body = pickle.dumps(obj, protocol=5)
    out = _HDR.pack(_MAGIC, len(body)) + body
    if mac is not None:
        out += mac.seal(body)
    return out


def _write_raw(writer, mac: Optional[_FrameMac], kind: int,
               msg_id: Optional[int], method: str, m, payload) -> None:
    """Queue one RTR frame on `writer` (caller drains under its send lock).

    The bulk payload is written as its own segment — never concatenated
    into an intermediate buffer, never pickled; the MAC streams over the
    segments via seal_parts."""
    mb = method.encode()
    head = (_R_PRE.pack(kind, msg_id or 0, len(mb)) + mb
            + _R_MLEN.pack(len(m)) + bytes(m))
    writer.write(_HDR.pack(_R_MAGIC, len(head) + len(payload)))
    writer.write(head)
    if len(payload):
        writer.write(payload)
    if mac is not None:
        writer.write(mac.seal_parts(head, payload))


class RpcServer:
    """Serves named async handlers; handler(conn, **data) -> reply data."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._handlers: Dict[str, Callable[..., Awaitable[Any]]] = {}
        self._server: Optional[asyncio.Server] = None
        self._conns: set = set()
        self.on_disconnect: Optional[Callable[["ServerConnection"], Awaitable[None]]] = None

    def register(self, method: str, handler: Callable[..., Awaitable[Any]]):
        self._handlers[method] = handler

    def register_all(self, obj, prefix: str = "handle_"):
        for name in dir(obj):
            if name.startswith(prefix):
                self.register(name[len(prefix):], getattr(obj, name))

    async def start(self):
        self._server = await asyncio.start_server(self._on_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    @property
    def address(self) -> Tuple[str, int]:
        return (self.host, self.port)

    async def _on_conn(self, reader, writer):
        token = get_session_token()
        mac: Optional[_FrameMac] = None
        if token is not None:
            # Mutual challenge-response BEFORE any frame is read: a peer
            # that cannot produce HMAC(token, ...) is dropped without a
            # single pickle.loads of its bytes, and we prove knowledge of
            # the token back so the client talks to no impostor.
            import hmac as _hmac
            import os as _os

            sc = _os.urandom(_CHALLENGE_SIZE)
            try:
                writer.write(_AUTH_MAGIC + sc)
                await writer.drain()
                answer = await asyncio.wait_for(
                    reader.readexactly(_CHALLENGE_SIZE + 32), 10.0)
            except Exception:
                answer = None
            if answer is None or not _hmac.compare_digest(
                    answer[_CHALLENGE_SIZE:],
                    _client_proof(token, sc, answer[:_CHALLENGE_SIZE])):
                logger.warning(
                    "dropping unauthenticated connection from %s",
                    writer.get_extra_info("peername"))
                try:
                    writer.close()
                except Exception:
                    pass
                return
            cc = answer[:_CHALLENGE_SIZE]
            try:
                writer.write(_server_proof(token, sc, cc))
                await writer.drain()
            except Exception:
                try:
                    writer.close()
                except Exception:
                    pass
                return
            mac = _FrameMac(_session_mac_key(token, sc, cc), is_client=False)
        conn = ServerConnection(reader, writer, mac=mac)
        self._conns.add(conn)
        try:
            while True:
                try:
                    kind, msg_id, method, data = await _read_frame(
                        reader, mac, conn=conn)
                except (asyncio.IncompleteReadError, ConnectionResetError, EOFError):
                    break
                except AuthError as e:
                    logger.warning("dropping connection from %s: %s",
                                   conn.peername, e)
                    break
                except ProtocolMismatch as e:
                    logger.warning("dropping connection: %s", e)
                    # Best-effort: answer with OUR magic so a version-skewed
                    # ray_tpu peer diagnoses the mismatch on its side too
                    # (its reader raises ProtocolMismatch naming versions)
                    # instead of seeing a bare EOF.
                    try:
                        writer.write(_frame((KIND_ERROR, None,
                                             "__protocol__", str(e)), mac))
                        await writer.drain()
                    except Exception:
                        pass
                    break
                if kind == KIND_REQUEST:
                    asyncio.ensure_future(self._dispatch(conn, msg_id, method, data))
                elif kind == KIND_PUSH:
                    asyncio.ensure_future(self._dispatch(conn, None, method, data))
        finally:
            self._conns.discard(conn)
            if self.on_disconnect is not None:
                try:
                    await self.on_disconnect(conn)
                except Exception:
                    logger.exception("on_disconnect handler failed")
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(self, conn, msg_id, method, data):
        handler = self._handlers.get(method)
        try:
            if handler is None:
                raise RpcError(f"no handler for method {method!r}")
            if _chaos_enabled():
                from ray_tpu.runtime.chaos import chaos

                if await chaos().intercept_server(method):
                    return  # injected drop: caller times out (rpc_chaos.cc)
            if isinstance(data, Raw):
                result = await handler(conn, data.m, data.payload)
            else:
                result = await handler(conn, **data)
            if msg_id is not None:
                await conn.send((KIND_REPLY, msg_id, method, result))
        except Exception as e:
            if msg_id is not None:
                try:
                    await conn.send((KIND_ERROR, msg_id, method, e))
                except Exception:
                    logger.exception("failed to send error reply for %s", method)
            else:
                logger.exception("push handler %s failed", method)

    async def close(self):
        # Close live connections BEFORE wait_closed(): since 3.12,
        # wait_closed() blocks until every connection handler returns, and
        # our handlers run until the peer disconnects — two processes
        # closing their servers while holding clients to each other would
        # deadlock (GCS <-> raylet shutdown did exactly that).
        if self._server is not None:
            self._server.close()
        for conn in list(self._conns):
            conn.close()
        if self._server is not None:
            try:
                await asyncio.wait_for(self._server.wait_closed(), 5)
            except asyncio.TimeoutError:
                pass


class ServerConnection:
    """Server side of one client connection (usable for pushes to client)."""

    def __init__(self, reader, writer, mac: Optional[_FrameMac] = None):
        self.reader = reader
        self.writer = writer
        self._mac = mac
        self._lock = asyncio.Lock()
        self.meta: Dict[str, Any] = {}  # handlers stash identity here
        self.xlang = False  # set by _read_frame on the first RTX frame

    async def send(self, payload):
        async with self._lock:
            # Sealing must happen under the lock: the MAC sequence number
            # must match the byte order frames hit the socket in.
            if isinstance(payload[3], Raw) and not self.xlang:
                kind, msg_id, method, pdata = payload
                _write_raw(self.writer, self._mac, kind, msg_id,
                           method or "", pdata.m, pdata.payload)
                await self.writer.drain()
                return
            if self.xlang:
                from ray_tpu.runtime import xlang

                kind, msg_id, method, pdata = payload
                try:
                    body = xlang.encode_envelope(
                        kind, msg_id, method, xlang.sanitize_reply(pdata))
                except xlang.XEncodeError as e:
                    # Strict wire: a reply outside the xlang vocabulary
                    # becomes a structured error, never a repr()-corrupted
                    # value and never a dead connection.
                    body = xlang.encode_envelope(
                        KIND_ERROR, msg_id, method,
                        f"reply not cross-language representable: {e}")
                data = _HDR.pack(_X_MAGIC, len(body)) + body
                if self._mac is not None:
                    data += self._mac.seal(body)
            else:
                data = _frame(payload, self._mac)
            self.writer.write(data)
            await self.writer.drain()

    async def push(self, method: str, data: dict):
        await self.send((KIND_PUSH, None, method, data))

    def close(self):
        try:
            self.writer.close()
        except Exception:
            pass

    @property
    def peername(self):
        try:
            return self.writer.get_extra_info("peername")
        except Exception:
            return None


class RpcClient:
    """Async client. Push frames from the server invoke `on_push`."""

    def __init__(self, host: str, port: int,
                 on_push: Optional[Callable[[str, dict], Awaitable[None]]] = None,
                 auto_reconnect: bool = False,
                 reconnect_timeout: float = 60.0,
                 on_reconnect: Optional[Callable[["RpcClient"],
                                                 Awaitable[None]]] = None):
        """auto_reconnect: on a lost connection, call() transparently redials
        (up to reconnect_timeout) and retries once — the
        retryable_grpc_client.cc analog for GCS restarts. on_reconnect runs
        after a successful redial (e.g. to resubscribe pubsub channels or
        re-register a node)."""
        self.host = host
        self.port = port
        self.on_push = on_push
        self.auto_reconnect = auto_reconnect
        self.reconnect_timeout = reconnect_timeout
        self.on_reconnect = on_reconnect
        self._reader = None
        self._writer = None
        self._mac: Optional[_FrameMac] = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._next_id = 0
        self._lock: Optional[asyncio.Lock] = None
        self._recv_task = None
        self._closed = False
        self._dead = False
        self._reconnecting: Optional[asyncio.Future] = None

    async def connect(self, timeout: float = 30.0):
        deadline = asyncio.get_event_loop().time() + timeout
        delay = 0.02
        while True:
            try:
                self._reader, self._writer = await asyncio.open_connection(self.host, self.port)
                break
            except OSError:
                if asyncio.get_event_loop().time() >= deadline:
                    raise
                await asyncio.sleep(delay)
                delay = min(delay * 2, 0.5)
        token = get_session_token()
        self._mac = None
        if token is not None:
            import hmac as _hmac
            import os as _os

            try:
                hello = await asyncio.wait_for(
                    self._reader.readexactly(len(_AUTH_MAGIC)
                                             + _CHALLENGE_SIZE), 10.0)
            except (asyncio.TimeoutError, asyncio.IncompleteReadError) as e:
                self._writer.close()
                raise AuthError(
                    "this process has a session token but the server did "
                    "not send an auth challenge (token/config mismatch)"
                ) from e
            if hello[:len(_AUTH_MAGIC)] != _AUTH_MAGIC:
                self._writer.close()
                raise AuthError(
                    f"expected auth challenge, got {hello[:4]!r}")
            sc = hello[len(_AUTH_MAGIC):]
            cc = _os.urandom(_CHALLENGE_SIZE)
            self._writer.write(cc + _client_proof(token, sc, cc))
            await self._writer.drain()
            # Mutual: the server must prove token knowledge BACK before we
            # parse a single frame from it — otherwise a spoofed endpoint
            # (port reuse after a raylet dies, TCP hijack) could feed this
            # process pickle frames.
            try:
                proof = await asyncio.wait_for(
                    self._reader.readexactly(32), 10.0)
            except (asyncio.TimeoutError, asyncio.IncompleteReadError) as e:
                self._writer.close()
                raise AuthError(
                    "server closed without proving token knowledge "
                    "(wrong token — multiple sessions on host? — or "
                    "impostor endpoint)") from e
            if not _hmac.compare_digest(proof,
                                        _server_proof(token, sc, cc)):
                self._writer.close()
                raise AuthError("server failed mutual authentication")
            self._mac = _FrameMac(_session_mac_key(token, sc, cc),
                                  is_client=True)
        self._lock = asyncio.Lock()
        self._recv_task = asyncio.ensure_future(self._recv_loop())
        return self

    async def _recv_loop(self):
        try:
            while True:
                kind, msg_id, method, data = await _read_frame(self._reader,
                                                               self._mac)
                if kind in (KIND_REPLY, KIND_ERROR):
                    fut = self._pending.pop(msg_id, None)
                    if fut is not None and not fut.done():
                        if kind == KIND_REPLY:
                            fut.set_result(data)
                        else:
                            fut.set_exception(data if isinstance(data, BaseException)
                                              else RpcError(str(data)))
                elif kind == KIND_PUSH and self.on_push is not None:
                    asyncio.ensure_future(self._run_push(method, data))
        except (asyncio.IncompleteReadError, ConnectionResetError, EOFError, OSError):
            pass
        except AuthError as e:
            # Injected bytes on the wire: poison the connection, never
            # unpickle. Reconnect (if enabled) re-runs the handshake.
            logger.error("dropping connection to %s:%s: %s",
                         self.host, self.port, e)
        except ProtocolMismatch as e:
            # Version skew is terminal and loud: no reconnect churn against
            # an incompatible peer, pending calls see the real reason.
            logger.error("wire protocol mismatch with %s:%s: %s",
                         self.host, self.port, e)
            self._closed = True
            self._dead = True
            self._fail_pending(e)
            return
        except Exception:
            logger.exception("rpc client recv loop error")
        finally:
            self._dead = True
            self._fail_pending(ConnectionLost(f"connection to {self.host}:{self.port} lost"))

    async def _run_push(self, method, data):
        try:
            await self.on_push(method, data)
        except Exception:
            logger.exception("push handler for %s failed", method)

    def _fail_pending(self, exc):
        for fut in self._pending.values():
            if not fut.done():
                try:
                    fut.set_exception(exc)
                    fut.exception()  # mark retrieved; avoid GC warnings
                except RuntimeError:
                    pass  # event loop already closed (interpreter shutdown)
        self._pending.clear()

    async def _reconnect(self):
        """Single-flight redial; concurrent callers share one attempt."""
        if self._reconnecting is not None:
            await asyncio.shield(self._reconnecting)
            if self._dead:
                raise ConnectionLost(
                    f"reconnect to {self.host}:{self.port} failed")
            return
        self._reconnecting = asyncio.get_event_loop().create_future()
        try:
            if self._recv_task is not None:
                self._recv_task.cancel()
            await self.connect(timeout=self.reconnect_timeout)
            self._dead = False
            if self.on_reconnect is not None:
                try:
                    await self.on_reconnect(self)
                except Exception:
                    logger.exception("on_reconnect callback failed")
            logger.info("reconnected to %s:%d", self.host, self.port)
        finally:
            fut, self._reconnecting = self._reconnecting, None
            if not fut.done():
                fut.set_result(None)

    async def call(self, method: str, timeout: Optional[float] = None, **data):
        attempts = 2 if self.auto_reconnect else 1
        for attempt in range(attempts):
            if self._dead and self.auto_reconnect and not self._closed:
                await self._reconnect()
            if self._closed or self._dead:
                raise ConnectionLost(
                    f"connection to {self.host}:{self.port} closed"
                    if self._closed
                    else f"connection to {self.host}:{self.port} lost")
            try:
                return await self._call_once(method, timeout, data)
            except ConnectionLost:
                if attempt == attempts - 1 or self._closed:
                    raise
                # Retry once after redial. GCS-side handlers are idempotent
                # (register/heartbeat/kv/publish); lease-protocol calls use
                # non-reconnecting clients so double-grants can't happen.

    async def call_send(self, method: str, **data) -> asyncio.Future:
        """Send a request NOW (write completes before this returns) and
        hand back the pending reply future without awaiting it. Callers
        that must guarantee wire order across many logical tasks (the
        actor-submission pump) send from ONE ordered coroutine via this
        and await replies concurrently elsewhere — spawning whole call
        coroutines per task lets late tasks overtake early ones that are
        still parked on a connection-setup lock."""
        if self._closed or self._dead:
            raise ConnectionLost(
                f"connection to {self.host}:{self.port} "
                + ("closed" if self._closed else "lost"))
        if _chaos_enabled():
            from ray_tpu.runtime.chaos import chaos

            await chaos().intercept_client(method)  # may raise/delay
        self._next_id += 1
        msg_id = self._next_id
        fut = asyncio.get_event_loop().create_future()
        self._pending[msg_id] = fut
        try:
            async with self._lock:
                # Seal under the lock: MAC sequence == socket byte order.
                payload = _frame((KIND_REQUEST, msg_id, method, data),
                                 self._mac)
                self._writer.write(payload)
                await self._writer.drain()
        except (ConnectionResetError, OSError) as e:
            self._pending.pop(msg_id, None)
            # Mark the transport dead so the retry loop in call() redials
            # instead of re-entering on the same broken writer (the recv
            # task may not have observed the failure yet).
            self._dead = True
            raise ConnectionLost(str(e))
        return fut

    async def _call_once(self, method: str, timeout: Optional[float], data):
        fut = await self.call_send(method, **data)
        if timeout is not None:
            return await asyncio.wait_for(fut, timeout)
        return await fut

    async def call_raw(self, method: str, m: bytes = b"", payload=b"",
                       timeout: Optional[float] = None):
        """Zero-pickle call: ships a schema'd header `m` (wire.Message
        bytes) plus an out-of-band bulk `payload` as one RTR frame and
        returns `(m_reply, payload_view)`. Neither direction runs pickle;
        the reply payload is a memoryview over the receive buffer. A
        handler error (including "no handler" on an old peer) surfaces as
        the usual pickled error reply — callers catch RpcError and fall
        back to the legacy method."""
        if self._closed or self._dead:
            raise ConnectionLost(
                f"connection to {self.host}:{self.port} "
                + ("closed" if self._closed else "lost"))
        if _chaos_enabled():
            from ray_tpu.runtime.chaos import chaos

            await chaos().intercept_client(method)
        self._next_id += 1
        msg_id = self._next_id
        fut = asyncio.get_event_loop().create_future()
        self._pending[msg_id] = fut
        try:
            async with self._lock:
                _write_raw(self._writer, self._mac, KIND_REQUEST, msg_id,
                           method, m, payload)
                await self._writer.drain()
        except (ConnectionResetError, OSError) as e:
            self._pending.pop(msg_id, None)
            self._dead = True
            raise ConnectionLost(str(e))
        data = await (asyncio.wait_for(fut, timeout)
                      if timeout is not None else fut)
        if isinstance(data, Raw):
            return data.m, data.payload
        return data, b""  # peer answered with a pickled reply: tolerate

    async def push(self, method: str, **data):
        async with self._lock:
            payload = _frame((KIND_PUSH, None, method, data), self._mac)
            self._writer.write(payload)
            await self._writer.drain()

    async def close(self):
        self._closed = True
        if self._recv_task is not None:
            self._recv_task.cancel()
            try:
                await self._recv_task
            except (asyncio.CancelledError, Exception):
                pass
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:
                pass


class EventLoopThread:
    """A dedicated asyncio loop in a daemon thread.

    Drivers and workers are synchronous Python; all RPC I/O runs on this loop
    (the asio io_context analog, reference:
    src/ray/common/asio/instrumented_io_context.h).
    """

    def __init__(self, name: str = "ray_tpu_io"):
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(target=self._run, name=name, daemon=True)
        self.thread.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def run(self, coro, timeout: Optional[float] = None):
        if threading.current_thread() is self.thread:
            # Blocking on our own loop can never complete — fail loudly
            # instead of deadlocking the whole process.
            coro.close()
            raise RuntimeError(
                "EventLoopThread.run() called from the loop thread itself; "
                "use spawn() or await the coroutine")
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def spawn(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self):
        self.loop.call_soon_threadsafe(self.loop.stop)
        self.thread.join(timeout=5)
