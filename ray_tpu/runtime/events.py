"""Structured cluster event bus: typed records for life-or-death decisions.

Reference analog: src/ray/gcs/gcs_server's event aggregation and
python/ray/_private/event/event_logger.py (Ray exports typed
RAY_EVENT records per component; the dashboard's "Events" tab reads
them back). Here the bus is deliberately small: an event is a plain
JSON-able dict (it rides the pickle RPC plane and the dashboard JSON
API unchanged), the GCS keeps a bounded ring of them behind
`report_events`/`list_events` RPCs, and emission is ALWAYS
best-effort — losing an event must never take down the component that
noticed the problem.

Emitters in-tree:
  * GCS        — NODE_DEAD (heartbeat timeout / drain), SLICE_LOST
                 (fate-sharing, records the whole failure domain),
                 NODE_DRAINING (a drain notice arrived: the node keeps
                 running until the deadline but takes no new work),
                 NODE_PREEMPTED (a draining node reached its deadline
                 and died — the planned-retirement flavor of NODE_DEAD)
  * raylet     — OOM_KILL (memory monitor victim selection)
  * collective — COLLECTIVE_ABORT (first local observation of a group
                 abort, before the KV flag fans out)
  * autoscaler — AUTOSCALER_SCALE (launch/terminate decisions)
  * train      — TRAIN_GANG_RESTART (gang failure -> restart from
                 latest checkpoint)
  * GCS        — TASK_STALLED (wait-graph edge blocked past the stall
                 threshold), DEADLOCK_DETECTED (cycle in the cluster
                 wait-graph) — emitted by the stall detector tick
  * llm router — LLM_REQUEST_SHED (SLO admission rejected a request;
                 labels carry the projected TTFT vs the SLO so
                 `scripts events` explains shedding during incidents),
                 LLM_REQUEST_FAILOVER (an in-flight request was replayed
                 on a surviving replica after its replica died; seeded
                 sampling makes the retry token-identical),
                 LLM_SESSION_MIGRATED (a draining replica exported live
                 sessions — KV pages + request state — to an adoptive
                 replica over the raw-frame wire; labels carry counts),
                 LLM_REPLICA_EJECTED (health tracking declared a replica
                 dead: affinity state pruned, no more picks land on it),
                 LLM_REPLICAS_SCALED (the serve-side replica policy
                 changed the LLM fleet size; scale-down drains first),
                 LLM_PREFIX_SPILLED (a replica published a cold prefix's
                 KV pages into the GCS cluster prefix store — the shared
                 working set now survives that replica's death),
                 LLM_PREFIX_ADOPTED (a replica adopted spilled prefix
                 pages from the cluster store instead of re-prefilling;
                 labels carry block counts)
  * rlhf       — RLHF_PLACEMENT_SWITCH (the adaptive placement policy
                 moved generator/learner between colocated and
                 disaggregated; labels carry from/to mode, the switch
                 epoch, and the goodput reason)
  * checkpoint — CHECKPOINT_SAVED (the manifest commit made a new
                 checkpoint real; emitted by exactly one rank — the
                 committer — with step, world, bytes, snapshot_ms and
                 persist_ms labels so dashboards attribute train-step
                 stall vs background persist cost)
  * GCS        — ALERT_FIRING / ALERT_RESOLVED (the alert evaluator
                 tick found a rule from runtime/alert_defs.py crossing /
                 leaving its windowed predicate over the metrics-history
                 rings; labels carry the rule, series, observed value,
                 threshold and the top contributing node — signature-
                 deduped, so an ongoing condition emits once and a
                 recovered one emits exactly one RESOLVED)

Read back via `state.list_cluster_events()`, the dashboard
`/api/events` route, or `python -m ray_tpu.scripts events`.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

# Severities (a deliberate subset of syslog: INFO = normal but notable
# control decisions, WARNING = degraded/retrying, ERROR = something died).
INFO = "INFO"
WARNING = "WARNING"
ERROR = "ERROR"
SEVERITIES = (INFO, WARNING, ERROR)

# Event types. Closed set so dashboards/tests can switch on them; add new
# types here rather than inventing strings at the call site.
NODE_DEAD = "NODE_DEAD"
NODE_DRAINING = "NODE_DRAINING"
NODE_PREEMPTED = "NODE_PREEMPTED"
SLICE_LOST = "SLICE_LOST"
OOM_KILL = "OOM_KILL"
COLLECTIVE_ABORT = "COLLECTIVE_ABORT"
AUTOSCALER_SCALE = "AUTOSCALER_SCALE"
TRAIN_GANG_RESTART = "TRAIN_GANG_RESTART"
TASK_STALLED = "TASK_STALLED"
DEADLOCK_DETECTED = "DEADLOCK_DETECTED"
LLM_REQUEST_SHED = "LLM_REQUEST_SHED"
LLM_REQUEST_FAILOVER = "LLM_REQUEST_FAILOVER"
LLM_SESSION_MIGRATED = "LLM_SESSION_MIGRATED"
LLM_REPLICA_EJECTED = "LLM_REPLICA_EJECTED"
LLM_REPLICAS_SCALED = "LLM_REPLICAS_SCALED"
LLM_PREFIX_SPILLED = "LLM_PREFIX_SPILLED"
LLM_PREFIX_ADOPTED = "LLM_PREFIX_ADOPTED"
RLHF_PLACEMENT_SWITCH = "RLHF_PLACEMENT_SWITCH"
CHECKPOINT_SAVED = "CHECKPOINT_SAVED"
ALERT_FIRING = "ALERT_FIRING"
ALERT_RESOLVED = "ALERT_RESOLVED"
EVENT_TYPES = (NODE_DEAD, NODE_DRAINING, NODE_PREEMPTED, SLICE_LOST,
               OOM_KILL, COLLECTIVE_ABORT,
               AUTOSCALER_SCALE, TRAIN_GANG_RESTART, TASK_STALLED,
               DEADLOCK_DETECTED, LLM_REQUEST_SHED, LLM_REQUEST_FAILOVER,
               LLM_SESSION_MIGRATED, LLM_REPLICA_EJECTED,
               LLM_REPLICAS_SCALED, LLM_PREFIX_SPILLED, LLM_PREFIX_ADOPTED,
               RLHF_PLACEMENT_SWITCH, CHECKPOINT_SAVED,
               ALERT_FIRING, ALERT_RESOLVED)


def make_event(event_type: str, message: str, *,
               severity: str = INFO, source: str = "",
               node_id=None, slice_name: Optional[str] = None,
               actor_id=None,
               labels: Optional[Dict[str, str]] = None) -> dict:
    """Build a typed event record.

    `node_id`/`actor_id` accept raw bytes ids or hex strings; they are
    stored as hex so the record stays JSON-able end to end.
    """
    if event_type not in EVENT_TYPES:
        raise ValueError(f"unknown event type {event_type!r} "
                         f"(known: {EVENT_TYPES})")
    if severity not in SEVERITIES:
        raise ValueError(f"unknown severity {severity!r} "
                         f"(known: {SEVERITIES})")
    return {
        "time": time.time(),
        "severity": severity,
        "type": event_type,
        "source": source,
        "message": str(message),
        "node_id": _hex(node_id),
        "slice_name": slice_name,
        "actor_id": _hex(actor_id),
        "labels": dict(labels) if labels else {},
    }


def _hex(id_or_none) -> Optional[str]:
    if id_or_none is None:
        return None
    if isinstance(id_or_none, (bytes, bytearray)):
        return bytes(id_or_none).hex()
    return str(id_or_none)


def emit(event_type: str, message: str, **kwargs) -> Optional[dict]:
    """Build an event and ship it to the GCS ring, best-effort.

    Usable from any process holding an initialized core worker (driver,
    task/actor workers — which covers the autoscaler, Train controller,
    and collective ranks). The send is fire-and-forget on the core IO
    loop (same path as the metrics flush), so it is thread-safe and
    adds no latency to the failure path that called it. Processes
    WITHOUT a core worker (GCS, raylet) append to the ring / call the
    RPC directly instead of going through here.

    Never raises: observability must not add failure modes.
    """
    try:
        ev = make_event(event_type, message, **kwargs)
    except Exception:
        return None
    try:
        from ray_tpu.core import worker as worker_mod
        if not worker_mod.is_initialized():
            return ev
        core = worker_mod.global_worker()
        core.io.spawn(core.gcs.call("report_events", events=[ev]))
    except Exception:
        pass
    return ev
