"""Python client for the native shared-memory object store.

Plasma-equivalent client API (reference: src/ray/object_manager/plasma/client.h)
over the serverless C++ store in store.cpp. Every process (driver, workers,
raylet) opens the same shared-memory file; `get` returns zero-copy memoryviews
over the mapping, so numpy/jax host arrays deserialize without copies.
"""

from __future__ import annotations

import ctypes
import mmap
import os
import threading
import time
from typing import Optional

from ray_tpu.runtime.object_store.build import ensure_built

ID_SIZE = 20


class StoreFullError(Exception):
    pass


class ObjectNotFoundError(Exception):
    pass


class _Lib:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            so = ensure_built()
            lib = ctypes.CDLL(so)
            lib.store_open.restype = ctypes.c_void_p
            lib.store_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int]
            lib.store_close.argtypes = [ctypes.c_void_p]
            lib.store_base.restype = ctypes.c_void_p
            lib.store_base.argtypes = [ctypes.c_void_p]
            for name in ("store_capacity", "store_used", "store_num_objects", "store_seal_count"):
                fn = getattr(lib, name)
                fn.restype = ctypes.c_uint64
                fn.argtypes = [ctypes.c_void_p]
            lib.store_create_object.restype = ctypes.c_int
            lib.store_create_object.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_uint64), ctypes.c_int]
            lib.store_lru_candidates.restype = ctypes.c_uint64
            lib.store_lru_candidates.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
            for name in ("store_seal", "store_release", "store_delete", "store_contains",
                         "store_abort"):
                fn = getattr(lib, name)
                fn.restype = ctypes.c_int
                fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            lib.store_get.restype = ctypes.c_int
            lib.store_get.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64)]
            lib.store_list.restype = ctypes.c_uint64
            lib.store_list.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
            lib.store_event_gen.restype = ctypes.c_uint32
            lib.store_event_gen.argtypes = [ctypes.c_void_p]
            lib.store_wait_event.restype = ctypes.c_int
            lib.store_wait_event.argtypes = [
                ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int]
            inst = object.__new__(cls)
            inst.lib = lib
            cls._instance = inst
        return cls._instance


class StoreBuffer:
    """A zero-copy view of a sealed object.

    Holds a read reference in the store for its lifetime: the object cannot be
    evicted while any StoreBuffer on any process is alive.
    """

    __slots__ = ("store", "object_id", "data", "metadata", "_released")

    def __init__(self, store: "ObjectStore", object_id: bytes, data: memoryview, metadata: bytes):
        self.store = store
        self.object_id = object_id
        self.data = data
        self.metadata = metadata
        self._released = False

    def release(self):
        if self._released:
            return
        try:
            self.data.release()
        except BufferError:
            # Exported views (e.g. numpy arrays) are still alive. Keep the
            # store refcount held: dropping it would let eviction reuse the
            # bytes under those views. The ref is retried at GC; if views
            # outlive us we deliberately leak the ref (pin > corruption).
            return
        self._released = True
        self.store._release(self.object_id)

    def __del__(self):
        try:
            self.release()
        except Exception:
            pass

    def __len__(self):
        return len(self.data)


class ObjectStore:
    """One per process; maps the node's shared-memory arena."""

    def __init__(self, path: str, capacity: int = 0, create: bool = False,
                 table_size: int = 0):
        self._lib = _Lib().lib
        self.path = path
        self.handle = self._lib.store_open(
            path.encode(), ctypes.c_uint64(capacity), ctypes.c_uint64(table_size),
            1 if create else 0)
        if not self.handle:
            raise RuntimeError(f"failed to open object store at {path} (create={create})")
        # Separate Python-level mapping of the same file for buffer views.
        self._fd = os.open(path, os.O_RDWR)
        size = os.fstat(self._fd).st_size
        self._mm = mmap.mmap(self._fd, size)
        self._view = memoryview(self._mm)
        self._size = size
        self._closed = False
        # Per-create POPULATE_WRITE (see create()): cheap to retry forever
        # on kernels that support it, disabled after the first EINVAL.
        self._populate_ok = True
        # Set by the prefault walk when this process's PTEs cover the
        # whole arena (per-create populate becomes redundant).
        self._warm = False
        self._prefault_started = False
        self._walk_inflight = False
        self._prefault_lock = threading.Lock()
        if create:
            # The creator walks at boot: one process's walk allocates the
            # tmpfs blocks arena-wide, so every other process's faults and
            # per-range populates skip block allocation. Non-creators walk
            # lazily (ensure_prefault) on their first large create —
            # workers that never touch big objects never pay the walk.
            self._start_prefault(create)

    def _start_prefault(self, create: bool):
        """Warm the arena from a background thread (creator at boot;
        other openers lazily via ensure_prefault on first large create).

        Two distinct costs otherwise land on the cold put path (together
        the r3 microbench's 86x put/get asymmetry):
          * page ALLOCATION — tmpfs blocks for the whole file. The
            creator's MADV_POPULATE_WRITE walk (posix_fallocate where
            unsupported) allocates + zeroes them without racing live
            allocator data.
          * per-process PTE population — PTEs are per process, so every
            opener (driver, each worker) takes ~256 minor faults per MiB
            the first time it writes a region (~2 GiB/s copies vs ~9 once
            PTEs are hot, measured on the dev box). The same
            POPULATE_WRITE walk in each opener installs writable PTEs in
            bulk; shmem pages never migrate, so they stay valid for the
            mapping's lifetime. Until the walk finishes, create()
            populates just the range it hands out (_populate_range);
            after it, that becomes a skip.

        RAY_TPU_STORE_PREFAULT=0 disables the walk (the per-create
        populate still applies); "full" is accepted as a legacy alias of
        the default.
        """
        self._prefault_started = True
        mode = os.environ.get("RAY_TPU_STORE_PREFAULT", "1")
        if mode == "0":
            return

        # The thread gets its OWN dup'd fd: close() recycling the main fd
        # number mid-walk must never let fallocate hit an unrelated file.
        fd = os.dup(self._fd)
        mm, size = self._mm, self._size

        # MADV_POPULATE_WRITE (Linux 5.14+): one syscall allocates tmpfs
        # blocks AND populates writable PTEs — the whole first-touch cost
        # moves off the put path in-kernel.
        MADV_POPULATE_WRITE = self._MADV_POPULATE_WRITE

        def warm():
            walked = True
            madvise_ok = True  # one failure: stop retrying madvise this walk
            # (_warm stays False then, so per-create populate — which has
            # its own errno-specific latch — keeps covering puts)
            try:
                chunk = 128 << 20
                for start in range(0, size, chunk):
                    if self._closed:
                        return
                    end = min(start + chunk, size)
                    if madvise_ok:
                        try:
                            mm.madvise(MADV_POPULATE_WRITE, start,
                                       end - start)
                            continue
                        except (OSError, ValueError):
                            madvise_ok = False
                            walked = False
                    if create:
                        os.posix_fallocate(fd, start, end - start)
                    if mode == "full":
                        # Pre-5.14 fallback: one read per page still
                        # installs (read) PTEs for this process.
                        mm[start:end:4096]
            except (OSError, ValueError, SystemError):
                walked = False  # best-effort (e.g. store closed mid-walk)
            finally:
                if walked and not self._closed:
                    self._warm = True
                self._walk_inflight = False
                try:
                    os.close(fd)
                except OSError:
                    pass

        self._walk_inflight = True
        threading.Thread(target=warm, name="store_prefault",
                         daemon=True).start()

    # -- lifecycle ---------------------------------------------------------
    def close(self):
        if self._closed:
            return
        self._closed = True
        try:
            self._view.release()
        except BufferError:
            pass
        try:
            self._mm.close()
        except BufferError:
            pass
        os.close(self._fd)
        self._lib.store_close(self.handle)

    # -- stats -------------------------------------------------------------
    @property
    def capacity(self) -> int:
        return self._lib.store_capacity(self.handle)

    @property
    def used(self) -> int:
        return self._lib.store_used(self.handle)

    @property
    def num_objects(self) -> int:
        return self._lib.store_num_objects(self.handle)

    # -- object ops --------------------------------------------------------
    def create(self, object_id: bytes, data_size: int, metadata: bytes = b"",
               allow_evict: bool = True) -> memoryview:
        """Allocate an unsealed object; returns writable view of its data area.

        allow_evict=False fails with StoreFullError instead of dropping LRU
        objects, letting the caller spill them to disk first (the
        local_object_manager spill-before-evict path)."""
        assert len(object_id) == ID_SIZE
        off = ctypes.c_uint64()
        rc = self._lib.store_create_object(
            self.handle, object_id, ctypes.c_uint64(data_size),
            ctypes.c_uint64(len(metadata)), ctypes.byref(off),
            ctypes.c_int(1 if allow_evict else 0))
        if rc == -1:
            raise ValueError(f"object {object_id.hex()} already exists")
        if rc == -2:
            raise StoreFullError(
                f"object store full: need {data_size}, capacity {self.capacity}, used {self.used}")
        if rc == -3:
            raise StoreFullError("object table full")
        o = off.value
        self._populate_range(o, data_size + len(metadata))
        if metadata:
            self._view[o + data_size:o + data_size + len(metadata)] = metadata
        return self._view[o:o + data_size]

    # MADV_POPULATE_WRITE (Linux 5.14+). The creator's arena walk
    # (_start_prefault) allocates tmpfs blocks, but PTEs are per PROCESS:
    # every other opener still takes ~256 minor faults per MiB the first
    # time it writes a region, capping a cold 1 MiB put at ~2 GiB/s on the
    # dev box vs ~9 GiB/s once PTEs are hot. One batched populate syscall
    # over exactly the range create() handed out installs writable PTEs
    # ~2.3x faster than faulting them one by one (measured 4.9 GiB/s cold,
    # and it is a no-op walk when the PTEs are already present).
    _MADV_POPULATE_WRITE = 23
    _POPULATE_MIN = 256 << 10  # below this, fault cost < syscall cost

    def _populate_range(self, off: int, length: int) -> None:
        if self._warm or not self._populate_ok or length < self._POPULATE_MIN:
            return
        self.ensure_prefault()
        page = mmap.PAGESIZE
        start = off & ~(page - 1)
        end = min((off + length + page - 1) & ~(page - 1), self._size)
        try:
            self._mm.madvise(self._MADV_POPULATE_WRITE, start, end - start)
        except ValueError:
            self._populate_ok = False
        except OSError as e:
            # Latch off only for "kernel lacks it" errnos; a transient
            # ENOMEM/EINTR must not disable the fast path for the
            # process lifetime (the copy just faults normally this once).
            import errno

            if e.errno in (errno.EINVAL, errno.ENOSYS):
                self._populate_ok = False

    def ensure_prefault(self) -> None:
        """Start this process's background arena walk if it hasn't run yet
        (idempotent). Called automatically on the first large create; until
        the walk finishes, per-range populate keeps each individual put at
        batch-fault speed.

        Deliberate tradeoff: on a host with many big-object writers the
        concurrent walks do compete for CPU (the reason the old design made
        per-process population opt-in), but laziness bounds that to
        processes that actually create >=256 KiB objects, where the walk
        pays for itself within a few dozen puts (~2-4x per cold put)."""
        if self._prefault_started:
            return
        with self._prefault_lock:
            if not self._prefault_started:
                self._start_prefault(False)

    def seal(self, object_id: bytes):
        rc = self._lib.store_seal(self.handle, object_id)
        if rc == -1:
            raise ValueError(f"seal: object {object_id.hex()} not found")
        if rc == -2:
            raise ValueError(
                f"seal: object {object_id.hex()} not in created state (double seal?)")

    def abort(self, object_id: bytes):
        """Abort an in-progress create (frees the unsealed buffer)."""
        self._lib.store_abort(self.handle, object_id)

    def put(self, object_id: bytes, data, metadata: bytes = b"") -> None:
        buf = self.create(object_id, len(data), metadata)
        try:
            buf[:] = data
        except BaseException:
            buf.release()
            self.abort(object_id)
            raise
        buf.release()
        self.seal(object_id)

    def get(self, object_id: bytes, timeout: Optional[float] = 0) -> StoreBuffer:
        """Get a sealed object; blocks up to `timeout` seconds for it to appear.

        Blocking rides the store's seal futex (plasma notification-socket
        analog, reference src/ray/object_manager/plasma/store.h:55): the
        event generation is sampled BEFORE the lookup, so a seal landing
        between lookup and wait wakes us immediately — no spin-poll. The
        wait is capped at 100 ms per lap purely as a liveness backstop."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            gen = self._lib.store_event_gen(self.handle)
            off = ctypes.c_uint64()
            dsz = ctypes.c_uint64()
            msz = ctypes.c_uint64()
            rc = self._lib.store_get(self.handle, object_id, ctypes.byref(off),
                                     ctypes.byref(dsz), ctypes.byref(msz))
            if rc == 0:
                o, d, m = off.value, dsz.value, msz.value
                data = self._view[o:o + d]
                metadata = bytes(self._view[o + d:o + d + m]) if m else b""
                return StoreBuffer(self, object_id, data, metadata)
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ObjectNotFoundError(object_id.hex())
                wait_ms = min(int(remaining * 1000) + 1, 100)
            else:
                wait_ms = 100
            self._lib.store_wait_event(self.handle, gen, wait_ms)

    def contains(self, object_id: bytes) -> bool:
        return bool(self._lib.store_contains(self.handle, object_id))

    @property
    def prefaulted(self) -> bool:
        """True once this process's background arena walk has installed
        writable PTEs for the whole mapping (puts run at memcpy speed)."""
        return self._warm

    @property
    def prefault_inflight(self) -> bool:
        """True while a background arena walk is running in this process.
        Distinguishes 'not warm yet, worth waiting' from 'will never be
        warm' (prefault disabled, or kernel without MADV_POPULATE_WRITE)."""
        return self._walk_inflight

    @property
    def event_gen(self) -> int:
        """Store-wide event generation (bumped on seal/delete/abort/evict)."""
        return self._lib.store_event_gen(self.handle)

    def wait_event(self, seen_gen: int, timeout_ms: int) -> bool:
        """Block until the generation moves past `seen_gen` (sampled before
        the caller's state check) or timeout. True if an event arrived."""
        return self._lib.store_wait_event(
            self.handle, ctypes.c_uint32(seen_gen), int(timeout_ms)) == 0

    def delete(self, object_id: bytes) -> bool:
        return self._lib.store_delete(self.handle, object_id) == 0

    def list_objects(self, max_objects: int = 1 << 16) -> list[bytes]:
        buf = ctypes.create_string_buffer(max_objects * ID_SIZE)
        n = self._lib.store_list(self.handle, buf, ctypes.c_uint64(max_objects))
        raw = buf.raw
        return [raw[i * ID_SIZE:(i + 1) * ID_SIZE] for i in range(n)]

    def lru_candidates(self, max_objects: int = 64) -> list[bytes]:
        """Sealed, unreferenced object ids in LRU order: spill candidates."""
        buf = ctypes.create_string_buffer(max_objects * ID_SIZE)
        n = self._lib.store_lru_candidates(
            self.handle, buf, ctypes.c_uint64(max_objects))
        raw = buf.raw
        return [raw[i * ID_SIZE:(i + 1) * ID_SIZE] for i in range(n)]

    def _release(self, object_id: bytes):
        if not self._closed:
            self._lib.store_release(self.handle, object_id)
