from ray_tpu.runtime.object_store.store import (  # noqa: F401
    ObjectStore,
    StoreFullError,
    ObjectNotFoundError,
)
