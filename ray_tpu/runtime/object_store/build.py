"""Build the native object-store extension on demand.

The .so is compiled once per machine into the package directory and reused;
rebuilds happen when store.cpp is newer than the cached binary.
"""

import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "store.cpp")
_SO = os.path.join(_DIR, "_object_store.so")
_lock = threading.Lock()


def ensure_built() -> str:
    with _lock:
        if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
            return _SO
        tmp = f"{_SO}.{os.getpid()}.tmp"  # pid-unique: concurrent builders race os.replace, which is atomic
        cmd = [
            "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
            "-o", tmp, _SRC, "-lpthread",
        ]
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp, _SO)
        return _SO
