// ray_tpu shared-memory object store.
//
// A plasma-equivalent (reference: /root/reference/src/ray/object_manager/plasma/
// store.h:55, object_lifecycle_manager.h:101, eviction_policy.h:105,
// plasma_allocator.h:44) redesigned serverless: instead of a store *server*
// process with fd-passing (fling.cc) and a flatbuffer wire protocol
// (plasma.fbs), every client maps one shared-memory file and coordinates
// through a process-shared robust mutex embedded in the mapping. This removes
// a per-operation IPC round-trip: create/seal/get are O(few hundred ns) of
// shared-memory work, and object payloads are zero-copy mmap views in every
// process. On TPU hosts the payloads feed jax.device_put directly (HBM
// staging), so the host store only needs to be a fast arena, not a transport.
//
// Layout of the mapping:
//   [StoreHeader][ObjectEntry x table_size][heap bytes ...]
//
// - Object table: open-addressing hash (linear probing, tombstones).
// - Heap: first-fit free list with boundary coalescing (plasma uses dlmalloc;
//   a bespoke allocator keeps us dependency-free and the access pattern --
//   few large buffers -- does not need size classes).
// - Eviction: LRU over sealed, refcount==0 objects (eviction_policy.h:160
//   LRUCache equivalent), triggered on allocation failure.
// - Crash-safety: pthread robust mutex; a died-holding-lock client leaves the
//   store usable (EOWNERDEAD -> consistency restore).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <vector>
#include <cstring>
#include <cstdio>
#include <cerrno>
#include <fcntl.h>
#include <pthread.h>
#include <linux/futex.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <ctime>
#include <unistd.h>

extern "C" {

static const uint64_t kMagic = 0x5241595F54505532ULL;  // "RAY_TPU2"
static const uint32_t kIdSize = 20;

enum EntryState : uint32_t {
  kFree = 0,
  kCreated = 1,   // allocated, not yet sealed (writer still filling)
  kSealed = 2,    // immutable, readable
  kTombstone = 3, // deleted slot (keeps probe chains intact)
};

struct ObjectEntry {
  uint8_t id[kIdSize];
  uint32_t state;
  uint64_t offset;      // into heap (absolute offset within mapping)
  uint64_t data_size;
  uint64_t meta_size;
  uint64_t alloc_size;  // actual bytes taken from the heap (may exceed
                        // align8(data+meta) when a whole free block was consumed)
  int32_t refcount;
  uint32_t _pad;
  uint64_t lru_tick;
};

// Free block header lives inside the heap at the block's offset.
struct FreeBlock {
  uint64_t size;        // total block size including header space usability
  uint64_t next;        // absolute offset of next free block, 0 = end
};

struct StoreHeader {
  uint64_t magic;
  uint64_t total_size;      // bytes of whole mapping
  uint64_t table_size;      // number of ObjectEntry slots (power of 2)
  uint64_t heap_start;      // absolute offset of heap
  uint64_t heap_size;
  uint64_t free_head;       // absolute offset of first free block, 0 = none
  uint64_t used_bytes;
  uint64_t lru_clock;
  uint64_t num_objects;
  uint64_t seal_count;      // bumped on every seal (cheap readiness signal)
  uint32_t event_gen;       // futex word: bumped on seal/delete/abort/evict so
                            // waiters (get, channel backpressure) block on a
                            // kernel futex instead of spin-polling. Plasma's
                            // analog is the per-client notification socket
                            // (reference: src/ray/object_manager/plasma/
                            // store.h:55); shared-memory futex needs no
                            // server round-trip.
  uint32_t _pad_ev;
  pthread_mutex_t mutex;
};

struct Handle {
  int fd;
  uint8_t* base;
  uint64_t map_size;
  StoreHeader* hdr;
  ObjectEntry* table;
};

static inline uint64_t align8(uint64_t v) { return (v + 7) & ~7ULL; }

static uint64_t hash_id(const uint8_t* id) {
  // FNV-1a over the 20-byte id.
  uint64_t h = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kIdSize; i++) { h ^= id[i]; h *= 1099511628211ULL; }
  return h;
}

static void lock(StoreHeader* hdr) {
  int rc = pthread_mutex_lock(&hdr->mutex);
  if (rc == EOWNERDEAD) {
    // Previous owner died mid-section. Data structures may be mid-update;
    // we accept the (tiny) window because all mutations are ordered to keep
    // the table scannable: mark consistent and continue.
    pthread_mutex_consistent(&hdr->mutex);
  }
}

static void unlock(StoreHeader* hdr) { pthread_mutex_unlock(&hdr->mutex); }

// Advance the event generation and wake every futex waiter. Called after any
// state change a waiter could be blocked on (seal makes an object readable;
// delete/abort/evict frees a channel ring slot). No FUTEX_PRIVATE_FLAG: the
// word is shared across processes.
static void bump_event(StoreHeader* hdr) {
  __atomic_fetch_add(&hdr->event_gen, 1, __ATOMIC_ACQ_REL);
  syscall(SYS_futex, &hdr->event_gen, FUTEX_WAKE, INT32_MAX,
          nullptr, nullptr, 0);
}

// Find entry slot for id. Returns slot index or (uint64_t)-1.
static uint64_t find_slot(Handle* h, const uint8_t* id) {
  StoreHeader* hdr = h->hdr;
  uint64_t mask = hdr->table_size - 1;
  uint64_t i = hash_id(id) & mask;
  for (uint64_t probes = 0; probes < hdr->table_size; probes++, i = (i + 1) & mask) {
    ObjectEntry* e = &h->table[i];
    if (e->state == kFree) return (uint64_t)-1;
    if (e->state != kTombstone && memcmp(e->id, id, kIdSize) == 0) return i;
  }
  return (uint64_t)-1;
}

// Find slot to insert id (first free/tombstone on probe path).
static uint64_t find_insert_slot(Handle* h, const uint8_t* id) {
  StoreHeader* hdr = h->hdr;
  uint64_t mask = hdr->table_size - 1;
  uint64_t i = hash_id(id) & mask;
  uint64_t first_tomb = (uint64_t)-1;
  for (uint64_t probes = 0; probes < hdr->table_size; probes++, i = (i + 1) & mask) {
    ObjectEntry* e = &h->table[i];
    if (e->state == kFree) return first_tomb != (uint64_t)-1 ? first_tomb : i;
    if (e->state == kTombstone && first_tomb == (uint64_t)-1) first_tomb = i;
  }
  return first_tomb;
}

// ---------- allocator ----------

static void free_insert(Handle* h, uint64_t off, uint64_t size) {
  // Insert block sorted by offset, coalescing with neighbours.
  StoreHeader* hdr = h->hdr;
  uint64_t prev = 0;
  uint64_t cur = hdr->free_head;
  while (cur != 0 && cur < off) {
    prev = cur;
    cur = ((FreeBlock*)(h->base + cur))->next;
  }
  FreeBlock* nb = (FreeBlock*)(h->base + off);
  nb->size = size;
  nb->next = cur;
  if (prev == 0) hdr->free_head = off; else ((FreeBlock*)(h->base + prev))->next = off;
  // Coalesce with next.
  if (cur != 0 && off + size == cur) {
    FreeBlock* cb = (FreeBlock*)(h->base + cur);
    nb->size += cb->size;
    nb->next = cb->next;
  }
  // Coalesce with prev.
  if (prev != 0) {
    FreeBlock* pb = (FreeBlock*)(h->base + prev);
    if (prev + pb->size == off) {
      pb->size += nb->size;
      pb->next = nb->next;
    }
  }
}

// First-fit allocation. Returns absolute offset or 0 on failure; the actual
// granted size (>= requested) is written to *granted.
static uint64_t heap_alloc(Handle* h, uint64_t size, uint64_t* granted) {
  StoreHeader* hdr = h->hdr;
  size = align8(size);
  if (size < sizeof(FreeBlock)) size = align8(sizeof(FreeBlock));
  uint64_t prev = 0, cur = hdr->free_head;
  while (cur != 0) {
    FreeBlock* b = (FreeBlock*)(h->base + cur);
    if (b->size >= size) {
      uint64_t remaining = b->size - size;
      if (remaining >= align8(sizeof(FreeBlock))) {
        uint64_t newoff = cur + size;
        FreeBlock* nb = (FreeBlock*)(h->base + newoff);
        nb->size = remaining;
        nb->next = b->next;
        if (prev == 0) hdr->free_head = newoff; else ((FreeBlock*)(h->base + prev))->next = newoff;
      } else {
        size = b->size;  // consume whole block
        if (prev == 0) hdr->free_head = b->next; else ((FreeBlock*)(h->base + prev))->next = b->next;
      }
      hdr->used_bytes += size;
      *granted = size;
      return cur;
    }
    prev = cur;
    cur = b->next;
  }
  return 0;
}

static void heap_free(Handle* h, uint64_t off, uint64_t size) {
  h->hdr->used_bytes -= size;
  free_insert(h, off, size);
}

// Tombstones keep probe chains intact, but left forever they degrade misses
// to full-table scans. When the slot after a new tombstone is kFree the chain
// demonstrably ends there, so the tombstone run ending at it can revert to
// kFree.
static void prune_tombstones(Handle* h, uint64_t slot) {
  uint64_t mask = h->hdr->table_size - 1;
  if (h->table[(slot + 1) & mask].state != kFree) return;
  uint64_t i = slot;
  while (h->table[i].state == kTombstone) {
    h->table[i].state = kFree;
    i = (i - 1) & mask;
    if (i == slot) break;  // table entirely tombstones
  }
}

static void remove_entry(Handle* h, uint64_t slot) {
  ObjectEntry* e = &h->table[slot];
  heap_free(h, e->offset, e->alloc_size);
  e->state = kTombstone;
  h->hdr->num_objects--;
  prune_tombstones(h, slot);
}

// Evict the single least-recently-used sealed refcount==0 object.
// Must hold lock. Returns 1 if something was evicted, 0 if no candidate.
static int evict_one(Handle* h) {
  StoreHeader* hdr = h->hdr;
  uint64_t best = (uint64_t)-1;
  uint64_t best_tick = ~0ULL;
  for (uint64_t i = 0; i < hdr->table_size; i++) {
    ObjectEntry* e = &h->table[i];
    if (e->state == kSealed && e->refcount == 0 && e->lru_tick < best_tick) {
      best_tick = e->lru_tick;
      best = i;
    }
  }
  if (best == (uint64_t)-1) return 0;
  remove_entry(h, best);
  return 1;
}

// ---------- public API ----------

void* store_open(const char* path, uint64_t capacity, uint64_t table_size, int create) {
  int fd;
  uint64_t total = 0;
  if (create) {
    fd = open(path, O_RDWR | O_CREAT | O_EXCL, 0600);
    if (fd < 0) return nullptr;
    if (table_size == 0) table_size = 1 << 16;
    // round table_size to power of two
    uint64_t ts = 1; while (ts < table_size) ts <<= 1; table_size = ts;
    uint64_t hdr_bytes = align8(sizeof(StoreHeader));
    uint64_t table_bytes = align8(table_size * sizeof(ObjectEntry));
    total = hdr_bytes + table_bytes + capacity;
    if (ftruncate(fd, (off_t)total) != 0) { close(fd); unlink(path); return nullptr; }
  } else {
    fd = open(path, O_RDWR);
    if (fd < 0) return nullptr;
    // Racing the creator: wait (bounded) for ftruncate to size the file.
    struct stat st;
    int waited_ms = 0;
    for (;;) {
      if (fstat(fd, &st) != 0) { close(fd); return nullptr; }
      if ((uint64_t)st.st_size > sizeof(StoreHeader)) break;
      if (waited_ms >= 10000) { close(fd); return nullptr; }
      usleep(2000);
      waited_ms += 2;
    }
    total = (uint64_t)st.st_size;
  }
  uint8_t* base = (uint8_t*)mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) { close(fd); return nullptr; }
  Handle* h = new Handle();
  h->fd = fd;
  h->base = base;
  h->map_size = total;
  h->hdr = (StoreHeader*)base;
  if (create) {
    StoreHeader* hdr = h->hdr;
    memset(base, 0, align8(sizeof(StoreHeader)) + align8(table_size * sizeof(ObjectEntry)));
    hdr->total_size = total;
    hdr->table_size = table_size;
    hdr->heap_start = align8(sizeof(StoreHeader)) + align8(table_size * sizeof(ObjectEntry));
    hdr->heap_size = capacity;
    hdr->used_bytes = 0;
    hdr->lru_clock = 1;
    hdr->num_objects = 0;
    hdr->seal_count = 0;
    // free list = one big block
    FreeBlock* fb = (FreeBlock*)(base + hdr->heap_start);
    fb->size = capacity;
    fb->next = 0;
    hdr->free_head = hdr->heap_start;
    pthread_mutexattr_t attr;
    pthread_mutexattr_init(&attr);
    pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&hdr->mutex, &attr);
    pthread_mutexattr_destroy(&attr);
    std::atomic_thread_fence(std::memory_order_seq_cst);
    hdr->magic = kMagic;
  } else {
    // The creator writes magic last (after a fence). A client racing the
    // creator's initialization waits bounded time for it to appear.
    int waited_ms = 0;
    while (((volatile StoreHeader*)h->hdr)->magic != kMagic) {
      if (waited_ms >= 10000) { munmap(base, total); close(fd); delete h; return nullptr; }
      usleep(2000);
      waited_ms += 2;
    }
  }
  h->table = (ObjectEntry*)(base + align8(sizeof(StoreHeader)));
  return h;
}

void store_close(void* vh) {
  Handle* h = (Handle*)vh;
  munmap(h->base, h->map_size);
  close(h->fd);
  delete h;
}

uint8_t* store_base(void* vh) { return ((Handle*)vh)->base; }
uint64_t store_capacity(void* vh) { return ((Handle*)vh)->hdr->heap_size; }
uint64_t store_used(void* vh) { return ((Handle*)vh)->hdr->used_bytes; }
uint64_t store_num_objects(void* vh) { return ((Handle*)vh)->hdr->num_objects; }
uint64_t store_seal_count(void* vh) { return ((Handle*)vh)->hdr->seal_count; }

// Current event generation; read it BEFORE a lookup, then pass it to
// store_wait_event so a state change between lookup and wait is never missed.
uint32_t store_event_gen(void* vh) {
  return __atomic_load_n(&((Handle*)vh)->hdr->event_gen, __ATOMIC_ACQUIRE);
}

// Block until the event generation differs from `seen` or timeout_ms elapses
// (timeout_ms < 0 = wait forever). rc: 0 = changed/woken, 1 = timed out.
int store_wait_event(void* vh, uint32_t seen, int timeout_ms) {
  StoreHeader* hdr = ((Handle*)vh)->hdr;
  struct timespec ts;
  struct timespec* tsp = nullptr;
  if (timeout_ms >= 0) {
    ts.tv_sec = timeout_ms / 1000;
    ts.tv_nsec = (long)(timeout_ms % 1000) * 1000000L;
    tsp = &ts;
  }
  if (__atomic_load_n(&hdr->event_gen, __ATOMIC_ACQUIRE) != seen) return 0;
  long rc = syscall(SYS_futex, &hdr->event_gen, FUTEX_WAIT, seen,
                    tsp, nullptr, 0);
  return (rc == -1 && errno == ETIMEDOUT) ? 1 : 0;
}

// rc: 0 ok; -1 already exists; -2 out of memory; -3 table full
// allow_evict=0 makes allocation failure return -2 immediately instead of
// dropping LRU objects, so the caller can spill them to disk first
// (local_object_manager.h:41 spill-before-evict semantics).
int store_create_object(void* vh, const uint8_t* id, uint64_t data_size,
                        uint64_t meta_size, uint64_t* offset_out,
                        int allow_evict) {
  Handle* h = (Handle*)vh;
  StoreHeader* hdr = h->hdr;
  uint64_t need = align8(data_size + meta_size);
  if (need == 0) need = 8;
  lock(hdr);
  if (find_slot(h, id) != (uint64_t)-1) { unlock(hdr); return -1; }
  // Evict one LRU object at a time until the (possibly fragmented) heap can
  // satisfy the request contiguously; freed neighbours coalesce as they go.
  uint64_t granted = 0;
  uint64_t off;
  int evicted_any = 0;
  for (;;) {
    off = heap_alloc(h, need, &granted);
    if (off != 0) break;
    if (!allow_evict || !evict_one(h)) { unlock(hdr); return -2; }
    evicted_any = 1;
  }
  uint64_t slot = find_insert_slot(h, id);
  if (slot == (uint64_t)-1) { heap_free(h, off, granted); unlock(hdr); return -3; }
  ObjectEntry* e = &h->table[slot];
  memcpy(e->id, id, kIdSize);
  e->state = kCreated;
  e->offset = off;
  e->data_size = data_size;
  e->meta_size = meta_size;
  e->alloc_size = granted;
  e->refcount = 1;  // creator holds a reference until seal+release
  e->lru_tick = hdr->lru_clock++;
  hdr->num_objects++;
  unlock(hdr);
  if (evicted_any) bump_event(hdr);
  *offset_out = off;
  return 0;
}

int store_seal(void* vh, const uint8_t* id) {
  Handle* h = (Handle*)vh;
  lock(h->hdr);
  uint64_t slot = find_slot(h, id);
  if (slot == (uint64_t)-1) { unlock(h->hdr); return -1; }
  ObjectEntry* e = &h->table[slot];
  if (e->state != kCreated) { unlock(h->hdr); return -2; }
  e->state = kSealed;
  e->refcount--;  // drop creator reference
  h->hdr->seal_count++;
  unlock(h->hdr);
  bump_event(h->hdr);
  return 0;
}

// Atomically look up a sealed object and take a read reference.
// rc: 0 ok; -1 not found; -2 exists but unsealed
int store_get(void* vh, const uint8_t* id, uint64_t* offset,
              uint64_t* data_size, uint64_t* meta_size) {
  Handle* h = (Handle*)vh;
  lock(h->hdr);
  uint64_t slot = find_slot(h, id);
  if (slot == (uint64_t)-1) { unlock(h->hdr); return -1; }
  ObjectEntry* e = &h->table[slot];
  if (e->state != kSealed) { unlock(h->hdr); return -2; }
  e->refcount++;
  e->lru_tick = h->hdr->lru_clock++;
  *offset = e->offset;
  *data_size = e->data_size;
  *meta_size = e->meta_size;
  unlock(h->hdr);
  return 0;
}

int store_contains(void* vh, const uint8_t* id) {
  Handle* h = (Handle*)vh;
  lock(h->hdr);
  uint64_t slot = find_slot(h, id);
  int rc = (slot != (uint64_t)-1 && h->table[slot].state == kSealed) ? 1 : 0;
  unlock(h->hdr);
  return rc;
}

int store_release(void* vh, const uint8_t* id) {
  Handle* h = (Handle*)vh;
  lock(h->hdr);
  uint64_t slot = find_slot(h, id);
  if (slot == (uint64_t)-1) { unlock(h->hdr); return -1; }
  ObjectEntry* e = &h->table[slot];
  if (e->refcount > 0) e->refcount--;
  unlock(h->hdr);
  return 0;
}

// Delete a sealed, unreferenced object.
// rc: 0 ok; -1 not found; -2 still referenced or not sealed
int store_delete(void* vh, const uint8_t* id) {
  Handle* h = (Handle*)vh;
  lock(h->hdr);
  uint64_t slot = find_slot(h, id);
  if (slot == (uint64_t)-1) { unlock(h->hdr); return -1; }
  ObjectEntry* e = &h->table[slot];
  if (e->refcount > 0 || e->state != kSealed) { unlock(h->hdr); return -2; }
  remove_entry(h, slot);
  unlock(h->hdr);
  bump_event(h->hdr);
  return 0;
}

// Abort an in-progress create (creator only: drops the creator reference and
// frees the buffer). rc: 0 ok; -1 not found; -2 not in created state
int store_abort(void* vh, const uint8_t* id) {
  Handle* h = (Handle*)vh;
  lock(h->hdr);
  uint64_t slot = find_slot(h, id);
  if (slot == (uint64_t)-1) { unlock(h->hdr); return -1; }
  ObjectEntry* e = &h->table[slot];
  if (e->state != kCreated) { unlock(h->hdr); return -2; }
  remove_entry(h, slot);
  unlock(h->hdr);
  bump_event(h->hdr);
  return 0;
}

// Fill out up to max ids (each kIdSize bytes) of sealed, unreferenced objects
// in LRU order (oldest tick first): the spill candidates. Returns count.
uint64_t store_lru_candidates(void* vh, uint8_t* ids_out, uint64_t max) {
  Handle* h = (Handle*)vh;
  lock(h->hdr);
  struct Cand { uint64_t tick; uint64_t slot; };
  std::vector<Cand> cands;
  for (uint64_t i = 0; i < h->hdr->table_size; i++) {
    ObjectEntry* e = &h->table[i];
    if (e->state == kSealed && e->refcount == 0)
      cands.push_back({e->lru_tick, i});
  }
  std::sort(cands.begin(), cands.end(),
            [](const Cand& a, const Cand& b) { return a.tick < b.tick; });
  uint64_t n = 0;
  for (const Cand& c : cands) {
    if (n >= max) break;
    memcpy(ids_out + n * kIdSize, h->table[c.slot].id, kIdSize);
    n++;
  }
  unlock(h->hdr);
  return n;
}

// Fill out up to max ids (each kIdSize bytes) of sealed objects. Returns count.
uint64_t store_list(void* vh, uint8_t* ids_out, uint64_t max) {
  Handle* h = (Handle*)vh;
  lock(h->hdr);
  uint64_t n = 0;
  for (uint64_t i = 0; i < h->hdr->table_size && n < max; i++) {
    ObjectEntry* e = &h->table[i];
    if (e->state == kSealed) {
      memcpy(ids_out + n * kIdSize, e->id, kIdSize);
      n++;
    }
  }
  unlock(h->hdr);
  return n;
}

}  // extern "C"
