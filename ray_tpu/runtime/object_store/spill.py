"""Spill/restore: move cold objects from the shm store to disk files.

Reference analog: src/ray/raylet/local_object_manager.{h,cc}
(local_object_manager.h:41, min_spilling_size batching) +
python/ray/_private/external_storage.py:72 (filesystem backend,
spill_objects:211). The TPU build spills in-process at the point of
allocation failure instead of via dedicated I/O workers: every worker shares
the node's store and spill directory, so whichever process hits the full
store spills LRU candidates to disk before retrying. File presence is the
spill record (no extra directory service); cross-process races are settled
by atomic rename.

File layout: <session>/spill/<oid.hex>  =  [u64 meta_len][metadata][data]
"""

from __future__ import annotations

import os
import struct
from typing import Optional

from ray_tpu.runtime.object_store.store import (
    ObjectStore,
    StoreFullError,
)

_HDR = struct.Struct("<Q")


class SpillManager:
    """Per-process handle on a node's shared spill directory."""

    def __init__(self, store: ObjectStore, spill_dir: str):
        self.store = store
        self.spill_dir = spill_dir
        os.makedirs(spill_dir, exist_ok=True)

    # -- spill -------------------------------------------------------------
    def _path(self, oid: bytes) -> str:
        return os.path.join(self.spill_dir, oid.hex())

    def contains(self, oid: bytes) -> bool:
        return os.path.exists(self._path(oid))

    def spilled_bytes(self) -> int:
        """Bytes currently resident in the node's spill directory (shared
        by every process on the node; feeds the per-node spill gauge and
        `node_stats`). Concurrently-deleted files are skipped."""
        total = 0
        try:
            with os.scandir(self.spill_dir) as it:
                for entry in it:
                    try:
                        total += entry.stat().st_size
                    except OSError:
                        pass
        except OSError:
            pass
        return total

    def spill_object(self, oid: bytes) -> bool:
        """Copy one sealed object out to disk, then drop it from the store."""
        try:
            buf = self.store.get(oid, timeout=0)
        except Exception:
            return False
        try:
            path = self._path(oid)
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "wb") as f:
                f.write(_HDR.pack(len(buf.metadata)))
                f.write(buf.metadata)
                f.write(buf.data)
            os.replace(tmp, path)
            from ray_tpu.runtime import metric_defs

            metric_defs.SPILLED_BYTES.inc(len(buf.data))
        finally:
            buf.release()
        self.store.delete(oid)
        return True

    def spill_until(self, need_bytes: int, exclude: Optional[set] = None) -> int:
        """Spill LRU candidates until ~need_bytes have been freed (or no
        candidates remain). Returns bytes freed."""
        freed = 0
        exclude = exclude or set()
        while freed < need_bytes:
            progress = False
            for oid in self.store.lru_candidates(max_objects=16):
                if oid in exclude:
                    continue
                try:
                    size = len(self.store.get(oid, timeout=0))
                except Exception:
                    continue
                if self.spill_object(oid):
                    freed += size
                    progress = True
                    if freed >= need_bytes:
                        break
            if not progress:
                break
        return freed

    # -- restore -----------------------------------------------------------
    def read_spilled(self, oid: bytes) -> Optional[tuple]:
        """Read a spilled object's (metadata, data) without restoring it."""
        path = self._path(oid)
        try:
            with open(path, "rb") as f:
                (meta_len,) = _HDR.unpack(f.read(_HDR.size))
                metadata = f.read(meta_len)
                data = f.read()
            return metadata, data
        except FileNotFoundError:
            return None

    def read_chunk(self, oid: bytes, offset: int, length: int
                   ) -> Optional[tuple]:
        """Read (total_data_size, metadata, chunk) from a spill file without
        restoring it — the raylet pull handler's cold path."""
        path = self._path(oid)
        try:
            with open(path, "rb") as f:
                (meta_len,) = _HDR.unpack(f.read(_HDR.size))
                metadata = f.read(meta_len)
                f.seek(0, os.SEEK_END)
                total = f.tell() - _HDR.size - meta_len
                f.seek(_HDR.size + meta_len + offset)
                chunk = f.read(length)
            return total, metadata, chunk
        except FileNotFoundError:
            return None

    def restore(self, oid: bytes) -> bool:
        """Restore a spilled object into the shm store (spilling others to
        make room if needed). Keeps the spill file as a cold copy until the
        object is deleted. Returns False if not spilled here."""
        if self.store.contains(oid):
            return True
        rec = self.read_spilled(oid)
        if rec is None:
            return False
        metadata, data = rec
        from ray_tpu.runtime import metric_defs

        metric_defs.RESTORED_BYTES.inc(len(data))
        try:
            self.create_with_spill(oid, len(data), metadata)[:] = data
            self.store.seal(oid)
        except ValueError:
            # Another process is restoring concurrently: wait for its seal.
            try:
                self.store.get(oid, timeout=10).release()
            except Exception:
                return False
        return True

    def create_with_spill(self, oid: bytes, data_size: int,
                          metadata: bytes = b"") -> memoryview:
        """store.create with spill-before-evict: on a full store, spill LRU
        objects to disk and retry, falling back to evicting restored-cold
        copies (which still live on disk) only as a last resort."""
        try:
            return self.store.create(oid, data_size, metadata,
                                     allow_evict=False)
        except ValueError:
            raise
        except StoreFullError:
            pass
        self.spill_until(data_size + len(metadata) + (1 << 20), exclude={oid})
        # Final attempt may evict: anything spillable has been spilled, so
        # eviction can only drop objects that already have a disk copy.
        return self.store.create(oid, data_size, metadata, allow_evict=True)

    def delete(self, oid: bytes):
        try:
            os.unlink(self._path(oid))
        except FileNotFoundError:
            pass
