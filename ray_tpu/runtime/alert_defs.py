"""Declarative alert table evaluated by the GCS alert tick.

Each rule is a plain dict literal (graftlint's ``alert-def`` pass parses
this file statically — keep rules literal, no computed fields):

* ``name`` — stable rule id; the firing/resolved event signature and the
  backticked row key in the docs/observability.md alert table.
* ``series`` — a metric name declared in ``runtime/metric_defs.py`` (the
  lint pass rejects rules referencing undeclared series).
* ``kind`` — ``"threshold"``: one windowed aggregate compared against a
  bound; ``"burn_rate"``: a multi-window SLO burn-rate rule over a
  latency histogram (short AND long window must both burn faster than
  ``threshold`` x the error budget — the classic two-window guard
  against both slow burns and single-tick blips).
* ``tags`` — optional subset filter on the series' tag sets.
* ``severity`` — one of the cluster-event severities.

Threshold rules add ``agg`` (``rate``/``delta``/``mean``/``pNN``),
``window_s``, ``op`` (``>``/``>=``/``<``/``<=``) and ``threshold``.
Burn-rate rules add ``slo_ms`` (an observation above this breaches the
SLO), ``objective`` (e.g. 0.99 -> 1% error budget), ``short_window_s``,
``long_window_s`` and ``threshold`` (the burn-rate multiple).

Evaluated every ``alert_eval_interval_s`` on the GCS health loop against
the metrics-history rings; state transitions emit signature-deduped
``ALERT_FIRING`` / ``ALERT_RESOLVED`` cluster events and surface in
``state.summary()["alerts"]``. See "Metric history, link utilization &
alerts" in docs/observability.md.
"""

ALERT_RULES = [
    {
        "name": "slo_burn_ttft",
        "series": "ray_tpu_llm_ttft_breakdown_ms",
        "kind": "burn_rate",
        "slo_ms": 1000.0,
        "objective": 0.99,
        "short_window_s": 30.0,
        "long_window_s": 300.0,
        "threshold": 10.0,
        "severity": "ERROR",
        "summary": "TTFT SLO error budget burning >=10x too fast",
    },
    {
        "name": "slo_burn_itl",
        "series": "ray_tpu_llm_itl_breakdown_ms",
        "kind": "burn_rate",
        "slo_ms": 200.0,
        "objective": 0.99,
        "short_window_s": 30.0,
        "long_window_s": 300.0,
        "threshold": 10.0,
        "severity": "WARNING",
        "summary": "inter-token latency SLO budget burning >=10x too fast",
    },
    {
        "name": "oom_kill_burst",
        "series": "ray_tpu_oom_kills_total",
        "kind": "threshold",
        "agg": "rate",
        "window_s": 120.0,
        "op": ">",
        "threshold": 0.0,
        "severity": "WARNING",
        "summary": "memory monitor is killing workers",
    },
    {
        "name": "llm_requests_shed",
        "series": "ray_tpu_llm_router_shed_total",
        "kind": "threshold",
        "agg": "rate",
        "window_s": 60.0,
        "op": ">",
        "threshold": 0.0,
        "severity": "WARNING",
        "summary": "SLO admission is rejecting requests (fleet saturated)",
    },
    {
        "name": "task_events_dropped",
        "series": "ray_tpu_task_events_dropped_total",
        "kind": "threshold",
        "agg": "rate",
        "window_s": 120.0,
        "op": ">",
        "threshold": 0.0,
        "severity": "WARNING",
        "summary": "task-event buffers overflowing before flush",
    },
]
