"""Resource math and scheduling policies.

Reference analog: src/ray/common/scheduling/ (cluster_resource_data.h fixed-
point resource vectors — we use floats with an epsilon) and
src/ray/raylet/scheduling/policy/ (hybrid_scheduling_policy.h:50 top-k
local-first, spread, node-affinity). Bundle (placement-group) policies live in
gcs/placement_groups.py.
"""

from __future__ import annotations

import random
from typing import Dict, Iterable, List, Optional

EPS = 1e-9


def fits(available: Dict[str, float], demand: Dict[str, float]) -> bool:
    for k, v in demand.items():
        if v > EPS and available.get(k, 0.0) + EPS < v:
            return False
    return True


def subtract(available: Dict[str, float], demand: Dict[str, float]) -> None:
    for k, v in demand.items():
        if v > EPS:
            available[k] = available.get(k, 0.0) - v


def add(available: Dict[str, float], demand: Dict[str, float]) -> None:
    for k, v in demand.items():
        if v > EPS:
            available[k] = available.get(k, 0.0) + v


def utilization_score(total: Dict[str, float], available: Dict[str, float],
                      demand: Dict[str, float]) -> float:
    """Lower is better: prefer nodes that stay least utilized after placement
    (the hybrid policy's critical-resource utilization measure)."""
    score = 0.0
    for k, v in total.items():
        if v <= EPS:
            continue
        would_use = v - available.get(k, 0.0) + demand.get(k, 0.0)
        score = max(score, would_use / v)
    return score


class SchedulingStrategy:
    pass


class DefaultStrategy(SchedulingStrategy):
    pass


class SpreadStrategy(SchedulingStrategy):
    """Round-robin across feasible nodes (spread_scheduling_policy)."""


class NodeAffinityStrategy(SchedulingStrategy):
    def __init__(self, node_id: bytes, soft: bool = False):
        self.node_id = node_id
        self.soft = soft


class NodeLabelStrategy(SchedulingStrategy):
    """Hard label constraints: {key: [allowed values...]}."""

    def __init__(self, hard: Dict[str, List[str]]):
        self.hard = dict(hard)


class PlacementGroupStrategy(SchedulingStrategy):
    def __init__(self, placement_group, bundle_index: int = -1,
                 capture_child_tasks: bool = False):
        self.placement_group = placement_group
        self.bundle_index = bundle_index
        self.capture_child_tasks = capture_child_tasks


def _labels_match(labels: Dict[str, str], hard: Dict[str, List[str]]) -> bool:
    return all(labels.get(k) in vals for k, vals in hard.items())


def rank_nodes_for_actor(nodes: Dict[bytes, "NodeRecord"], spec, pg_manager) -> List:
    """Order live nodes to try for actor placement (GcsActorScheduler policy).

    Placement-group constrained actors must go to the bundle's node; otherwise
    hybrid: feasible nodes sorted by post-placement utilization, ties randomized
    so uniform actors spread.
    """
    # Draining nodes are alive but retiring: never START anything there
    # (existing work runs to the drain deadline; PG-pinned placement below
    # still honors an already-committed bundle location).
    alive = [n for n in nodes.values()
             if n.alive and not getattr(n, "draining", False)]
    strategy = spec.scheduling_strategy
    if spec.placement_group_id is not None and pg_manager is not None:
        alive = [n for n in nodes.values() if n.alive]
        node_id = pg_manager.bundle_location(spec.placement_group_id,
                                             spec.placement_group_bundle_index)
        return [n for n in alive if node_id is not None and n.node_id == node_id]
    if isinstance(strategy, NodeAffinityStrategy):
        pinned = [n for n in alive if n.node_id == strategy.node_id]
        if pinned or not strategy.soft:
            return pinned
    if isinstance(strategy, NodeLabelStrategy):
        alive = [n for n in alive if _labels_match(n.labels, strategy.hard)]
    feasible = [n for n in alive if fits(n.available, spec.resources)
                and fits(n.resources, spec.resources)]
    infeasible_capacity = [n for n in alive if not fits(n.available, spec.resources)
                           and fits(n.resources, spec.resources)]
    random.shuffle(feasible)
    if isinstance(strategy, SpreadStrategy):
        feasible.sort(key=lambda n: utilization_score(n.resources, n.available, {}))
    else:
        feasible.sort(key=lambda n: utilization_score(n.resources, n.available,
                                                      spec.resources))
    # Nodes whose *total* capacity fits but currently busy go last: the lease
    # request will queue at that raylet until resources free up.
    random.shuffle(infeasible_capacity)
    return feasible + infeasible_capacity
