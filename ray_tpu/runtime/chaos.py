"""RPC fault injection: delay/drop/fail calls by method pattern.

Reference analog: src/ray/rpc/rpc_chaos.{h,cc} (RAY_testing_rpc_failure —
inject request/response failures into gRPC methods by name) plus the chaos
release harness (release/nightly_tests/setup_chaos.py). Ours hooks the
framed-pickle RPC layer (runtime/rpc.py): every client call and server
dispatch consults the process-local `RpcChaos` table.

Config is a spec string, programmatic or via the RAY_TPU_CHAOS env var (so
spawned raylets/workers inherit it):

    "method_glob=mode:prob[:param][,...]"

  modes:  fail    — raise ConnectionLost before sending (prob)
          timeout — swallow the reply: caller sees ConnectionLost after
                    param seconds (default 1.0)
          delay   — sleep param seconds (default 0.05) before dispatch
  e.g. RAY_TPU_CHAOS="lease_worker=fail:0.2,pull_object=delay:0.3:0.1"

Determinism: draws come from a dedicated RNG seeded from RAY_TPU_CHAOS_SEED
(default 0) + the process id, so multi-process runs differ but a whole-test
rerun with a fixed pid layout is reproducible in practice; tests assert on
behavior (retries succeed), not on exact draw sequences.
"""

from __future__ import annotations

import asyncio
import fnmatch
import os
import random
from typing import List, Optional, Tuple

FAIL, TIMEOUT, DELAY = "fail", "timeout", "delay"


class ChaosRule:
    __slots__ = ("pattern", "mode", "prob", "param", "max_hits", "hits")

    def __init__(self, pattern: str, mode: str, prob: float,
                 param: float = 0.0, max_hits: Optional[int] = None):
        if mode not in (FAIL, TIMEOUT, DELAY):
            raise ValueError(f"unknown chaos mode {mode!r}")
        self.pattern = pattern
        self.mode = mode
        self.prob = prob
        self.param = param
        self.max_hits = max_hits   # stop injecting after N hits (None = inf)
        self.hits = 0

    def matches(self, method: str) -> bool:
        if self.max_hits is not None and self.hits >= self.max_hits:
            return False
        return fnmatch.fnmatch(method, self.pattern)


class RpcChaos:
    """Process-local chaos table; disabled (zero overhead) unless rules
    exist."""

    def __init__(self):
        self._rules: List[ChaosRule] = []
        seed = int(os.environ.get("RAY_TPU_CHAOS_SEED", "0"))
        self._rng = random.Random(seed ^ os.getpid())
        spec = os.environ.get("RAY_TPU_CHAOS", "")
        if spec:
            self.configure(spec)

    @property
    def enabled(self) -> bool:
        return bool(self._rules)

    def configure(self, spec: str):
        """Parse and append rules from a spec string (see module doc).

        Each rule is validated independently; a malformed fragment raises
        ValueError naming the offending fragment (an RAY_TPU_CHAOS typo must
        fail the run loudly, not silently change which faults get injected).
        Rules parsed before the bad fragment are NOT added — the spec is
        applied all-or-nothing."""
        rules = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            try:
                pattern, rhs = part.split("=", 1)
                if not pattern:
                    raise ValueError("empty method pattern")
                fields = rhs.split(":")
                mode = fields[0]
                if mode not in (FAIL, TIMEOUT, DELAY):
                    raise ValueError(
                        f"unknown mode {mode!r} (expected one of "
                        f"{FAIL!r}, {TIMEOUT!r}, {DELAY!r})")
                prob = float(fields[1]) if len(fields) > 1 else 1.0
                if not 0.0 <= prob <= 1.0:
                    raise ValueError(f"probability {prob!r} not in [0, 1]")
                param = float(fields[2]) if len(fields) > 2 else (
                    1.0 if mode == TIMEOUT else 0.05)
                if param < 0:
                    raise ValueError(f"negative param {param!r}")
                max_hits = int(fields[3]) if len(fields) > 3 else None
                if max_hits is not None and max_hits < 0:
                    raise ValueError(f"negative max_hits {max_hits!r}")
                if len(fields) > 4:
                    raise ValueError(
                        f"too many ':' fields ({len(fields)}, max 4)")
            except ValueError as e:
                raise ValueError(
                    f"bad RAY_TPU_CHAOS rule {part!r}: {e} "
                    f"(expected 'method_glob=mode:prob[:param[:max_hits]]')"
                ) from e
            rules.append((pattern, mode, prob, param, max_hits))
        for pattern, mode, prob, param, max_hits in rules:
            self.add_rule(pattern, mode, prob, param, max_hits)

    def add_rule(self, pattern: str, mode: str, prob: float = 1.0,
                 param: float = 0.0, max_hits: Optional[int] = None
                 ) -> ChaosRule:
        rule = ChaosRule(pattern, mode, prob, param, max_hits)
        self._rules.append(rule)
        return rule

    def clear(self):
        self._rules.clear()

    def _draw(self, method: str) -> Optional[ChaosRule]:
        for rule in self._rules:
            if rule.matches(method) and self._rng.random() < rule.prob:
                rule.hits += 1
                return rule
        return None

    async def intercept_client(self, method: str):
        """Runs before a client sends a request. May raise ConnectionLost
        (fail mode) or sleep (delay mode). timeout mode is handled server
        side."""
        if not self._rules:
            return
        rule = self._draw(method)
        if rule is None:
            return
        if rule.mode == FAIL:
            from ray_tpu.runtime.rpc import ConnectionLost

            raise ConnectionLost(
                f"chaos: injected failure for {method!r}")
        if rule.mode == DELAY:
            await asyncio.sleep(rule.param)

    async def intercept_server(self, method: str) -> bool:
        """Runs before a server dispatches a request. Returns True if the
        request should be silently dropped (timeout mode — the caller's
        await then times out / sees the connection close later), after an
        optional delay."""
        if not self._rules:
            return False
        rule = self._draw(method)
        if rule is None:
            return False
        if rule.mode == DELAY:
            await asyncio.sleep(rule.param)
            return False
        if rule.mode == TIMEOUT:
            await asyncio.sleep(rule.param)
            return True
        return False   # FAIL is a client-side mode


_instance: Optional[RpcChaos] = None


def chaos() -> RpcChaos:
    global _instance
    if _instance is None:
        _instance = RpcChaos()
    return _instance


def reset():
    """Drop all rules AND the instance (tests)."""
    global _instance
    _instance = None
