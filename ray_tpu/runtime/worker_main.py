"""Worker process: executes tasks and hosts actors.

Reference analog: the worker side of src/ray/core_worker/ — HandlePushTask
(core_worker.cc:3810) -> TaskReceiver -> ExecuteTask (:3229), actor creation
(:2556 target side), with the Python function loading of
python/ray/_private/function_manager.py (pickled defs from GCS KV).

The process runs two halves:
  * an asyncio RPC server (this module) that receives pushed tasks, and
  * a CoreWorker (ray_tpu.core.worker) so user code inside tasks can submit
    nested tasks / use the object store — the full API works in workers.
Execution happens on a thread pool (serial by default; actors can raise
max_concurrency), keeping the IO loop responsive.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import inspect
import logging
import os
import sys
import threading
import traceback
from typing import Any, Dict, Optional

import cloudpickle

from ray_tpu.core import serialization
from ray_tpu.core.exceptions import TaskError
from ray_tpu.core.task_spec import ActorSpec, TaskSpec
from ray_tpu.config import cfg
from ray_tpu.core.worker import CoreWorker, set_global_worker
from ray_tpu.runtime.rpc import RpcClient, RpcServer
from ray_tpu.utils.ids import ObjectID, TaskID

logger = logging.getLogger(__name__)


class WorkerRuntime:
    def __init__(self):
        self.worker_id = bytes.fromhex(os.environ["RAY_TPU_WORKER_ID"])
        self.node_id = bytes.fromhex(os.environ["RAY_TPU_NODE_ID"])
        raylet = os.environ["RAY_TPU_RAYLET_ADDR"].rsplit(":", 1)
        gcs = os.environ["RAY_TPU_GCS_ADDR"].rsplit(":", 1)
        self.raylet_addr = (raylet[0], int(raylet[1]))
        self.gcs_addr = (gcs[0], int(gcs[1]))
        self.store_path = os.environ["RAY_TPU_STORE_PATH"]
        self.session_dir = os.environ["RAY_TPU_SESSION_DIR"]
        self.server = RpcServer("127.0.0.1", 0)
        self.server.register_all(self)
        self.core: Optional[CoreWorker] = None
        self.exec_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="task_exec")
        self.fn_cache: Dict[bytes, Any] = {}
        self._running_threads: Dict[bytes, int] = {}  # task_id -> thread id
        self._running_async: Dict[bytes, "asyncio.Task"] = {}
        self.actor_instance = None
        self.actor_spec: Optional[ActorSpec] = None
        self._raylet_client: Optional[RpcClient] = None
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        self._async_sem: Optional[asyncio.Semaphore] = None
        self._tasks_pending = 0   # pushed, not yet finished (queued + running)

    async def start(self):
        self.loop = asyncio.get_event_loop()
        # CoreWorker first: user code needs the full API during tasks.
        self.core = CoreWorker(
            mode="worker", gcs_address=self.gcs_addr,
            raylet_address=self.raylet_addr, store_path=self.store_path,
            session_dir=self.session_dir, node_id=self.node_id)
        set_global_worker(self.core)
        await self.server.start()
        self._raylet_client = RpcClient(*self.raylet_addr)
        await self._raylet_client.connect(timeout=30)
        await self._raylet_client.call(
            "worker_ready", worker_id=self.worker_id, address=self.server.address)
        asyncio.ensure_future(self._orphan_watchdog())
        logger.info("worker %s ready at %s", self.worker_id.hex()[:12],
                    self.server.address)

    async def _orphan_watchdog(self):
        """Exit when our raylet goes away (worker processes must not outlive
        their node, even when the raylet is SIGKILLed)."""
        while not self._raylet_client._dead:
            await asyncio.sleep(1.0)
        logger.warning("raylet connection lost; worker exiting")
        os._exit(1)

    # ---- function/class loading (function_manager.py analog) -------------

    def _load_function(self, fn_id: bytes):
        fn = self.fn_cache.get(fn_id)
        if fn is None:
            reply = self.core.io.run(self.core.gcs.call("kv_get", key=b"fn:" + fn_id))
            blob = reply["value"]
            if blob is None:
                raise RuntimeError(f"function {fn_id.hex()[:12]} not found in GCS")
            fn = cloudpickle.loads(blob)
            self.fn_cache[fn_id] = fn
        return fn

    def _load_class(self, class_id: bytes):
        cls = self.fn_cache.get(class_id)
        if cls is None:
            reply = self.core.io.run(self.core.gcs.call("kv_get", key=b"cls:" + class_id))
            blob = reply["value"]
            if blob is None:
                raise RuntimeError(f"class {class_id.hex()[:12]} not found in GCS")
            cls = cloudpickle.loads(blob)
            self.fn_cache[class_id] = cls
        return cls

    # ---- task execution ---------------------------------------------------

    STREAMING = -1  # num_returns sentinel (see CoreWorker.STREAMING)

    def _seal_return(self, oid: bytes, segments, total: int) -> None:
        """Write one large return value into the local plasma store."""
        store = self.core.store
        if store.contains(oid):
            # Retry of a task whose previous attempt already sealed this
            # return: reuse it (ids are deterministic).
            return
        # A crashed previous attempt may have left an unsealed create
        # behind; reclaim the id.
        store.abort(oid)
        buf = self.core.spill_create(oid, total)
        try:
            serialization.write_segments(buf, segments)
        except BaseException:
            buf.release()
            store.abort(oid)
            raise
        buf.release()
        store.seal(oid)

    def _package_returns(self, spec: TaskSpec, result) -> list:
        returns = []
        values = (result,) if spec.num_returns == 1 else tuple(result)
        if spec.num_returns > 1 and len(values) != spec.num_returns:
            raise ValueError(
                f"task declared num_returns={spec.num_returns} but returned "
                f"{len(values)} values")
        for i, value in enumerate(values):
            segments, total, contained = serialization.serialize_with_refs(
                value)
            oid = ObjectID.for_task_return(TaskID(spec.task_id), i).binary()
            # Nested refs in the return value: pin them with their owners
            # NOW (while this worker still holds borrows), keyed by the
            # return oid; the caller records the children and unpins when it
            # frees the return (reference_count.h nested-ref invariant).
            children = self._pin_return_children(oid, contained)
            if total <= cfg().inline_result_max:
                returns.append(("v", serialization.join_segments(segments),
                                children))
            else:
                self._seal_return(oid, segments, total)
                returns.append(("r", oid, children))
        return returns

    def _pin_return_children(self, container_oid: bytes, contained) -> list:
        children = []
        for ref in contained:
            child = ref.binary()
            addr = ref.owner_addr
            children.append((child, addr))
            if addr is None or tuple(addr) == tuple(self.core.owner_addr):
                with self.core._mem_lock:
                    rec = self.core._owned.get(child)
                    if rec is not None:
                        rec["containers"].add(container_oid)
            else:
                # Synchronous on the io loop caller context: we are on the
                # exec thread, so round-trip through the loop and WAIT — the
                # pin must land before the reply releases our borrows.
                asyncio.run_coroutine_threadsafe(
                    self.core._owner_call(tuple(addr), "pin_container",
                                          oid=child, container=container_oid),
                    self.core.io.loop).result(timeout=30)
        return children

    def _push_gen_item(self, conn, spec: TaskSpec, index: int, value) -> None:
        """Report one yielded item to the submitter (blocking, from the exec
        thread): small values ride the push inline; large values seal to the
        local plasma store and only the location is pushed.
        ReportGeneratorItemReturns analog (core_worker.proto:462)."""
        segments, total = serialization.serialize(value)
        msg = {"task_id": spec.task_id, "index": index,
               "node_id": self.node_id}
        if total <= cfg().inline_result_max or self.core.store is None:
            msg["payload"] = serialization.join_segments(segments)
        else:
            oid = ObjectID.for_task_return(TaskID(spec.task_id), index).binary()
            self._seal_return(oid, segments, total)
        asyncio.run_coroutine_threadsafe(
            conn.push("gen_item", msg), self.loop).result(timeout=60)

    def _stream_generator(self, conn, spec: TaskSpec, gen) -> dict:
        """Drain a sync generator, pushing each item; the final reply carries
        the item count (also on error, so the caller drains then raises)."""
        count = 0
        try:
            for item in gen:
                self._push_gen_item(conn, spec, count, item)
                count += 1
            return {"status": "ok", "streamed": count,
                    "node_id": self.node_id}
        except Exception as e:
            tb = traceback.format_exc()
            logger.error("streaming task %s failed at item %d:\n%s",
                         spec.name, count, tb)
            return {"status": "error", "streamed": count,
                    "error": TaskError(spec.name, tb, cause=_safe_cause(e))}

    def _execute(self, fn, spec: TaskSpec, conn=None) -> dict:
        """Runs on the exec thread; returns the RPC reply."""
        import threading

        from ray_tpu import runtime_env as renv_mod
        from ray_tpu.util import tracing

        applied = None
        # Cancellation registry: cancel_task injects TaskCancelledError
        # into this thread by id (ray.cancel analog; best-effort — a
        # blocking C call won't notice until it returns to Python).
        self._running_threads[spec.task_id] = threading.get_ident()
        from ray_tpu.core import blocked as blocked_mod

        # Thread -> task attribution for stack dumps and wait-graph edges:
        # anything this thread blocks on is charged to this task/actor.
        blocked_mod.set_task_context(threading.get_ident(), {
            "task_id": spec.task_id.hex(),
            "name": spec.name,
            "actor_id": spec.actor_id.hex() if spec.actor_id else None,
        })
        try:
            applied = renv_mod.apply_runtime_env(
                self.core, spec.runtime_env, self.core.session_dir)
            args, kwargs = self.core.resolve_args(spec)
            self.core.current_task_name = spec.name
            # RUNNING is recorded by the EXECUTING worker (the driver only
            # sees SUBMITTED/FINISHED), giving the dashboard timeline its
            # per-worker execution bars (task_event_buffer.h analog).
            self.core._record_task_event(spec, "RUNNING")
            # Adopt the submitter's trace context (TaskSpec wire fields
            # 17/18) so this execute span — and any nested submits the
            # task body makes — stitch under the driver's span by id.
            with tracing.trace_context(spec.trace_id, spec.parent_span_id), \
                    tracing.span(spec.name, "task:execute",
                                 task_id=spec.task_id.hex()[:12]):
                result = fn(*args, **kwargs)
                if inspect.iscoroutine(result):
                    # Sync-invoked coroutine (async def run through the
                    # thread-pool path): run it to completion on a private
                    # loop in this thread.
                    result = asyncio.run(result)
                if spec.num_returns == self.STREAMING:
                    if not inspect.isgenerator(result):
                        raise TypeError(
                            'num_returns="streaming" requires the task to '
                            "return a generator")
                    return self._stream_generator(conn, spec, result)
            returns = self._package_returns(spec, result)
            return {"status": "ok", "returns": returns, "node_id": self.node_id}
        except Exception as e:
            from ray_tpu.core.exceptions import TaskCancelledError

            if isinstance(e, TaskCancelledError):
                logger.info("task %s cancelled", spec.name)
                return {"status": "error", "error": e}
            tb = traceback.format_exc()
            logger.error("task %s failed:\n%s", spec.name, tb)
            return {"status": "error",
                    "error": TaskError(spec.name, tb, cause=_safe_cause(e))}
        finally:
            self._running_threads.pop(spec.task_id, None)
            blocked_mod.set_task_context(threading.get_ident(), None)
            if applied is not None:
                applied.undo()
            self.core.current_task_name = None

    async def _execute_async(self, fn, spec: TaskSpec, conn=None) -> dict:
        """Async execution path: coroutine and async-generator functions run
        directly on the worker's event loop (concurrency-group analog —
        reference: core_worker/transport/concurrency_group_manager.h with
        fibers; ours are asyncio tasks bounded by a semaphore). Blocking prep
        (arg resolution from plasma) stays off-loop."""
        from ray_tpu import runtime_env as renv_mod

        loop = asyncio.get_event_loop()
        sem = self._async_sem
        if sem is None:
            sem = self._async_sem = asyncio.Semaphore(100)
        async with sem:
            applied = None
            try:
                def _prep():
                    a = renv_mod.apply_runtime_env(
                        self.core, spec.runtime_env, self.core.session_dir)
                    args, kwargs = self.core.resolve_args(spec)
                    return a, args, kwargs

                applied, args, kwargs = await loop.run_in_executor(None, _prep)
                self.core.current_task_name = spec.name
                self.core._record_task_event(spec, "RUNNING")
                if inspect.isasyncgenfunction(getattr(fn, "__func__", fn)):
                    if spec.num_returns != self.STREAMING:
                        raise TypeError(
                            "async generator methods require "
                            'num_returns="streaming"')
                    count = 0
                    try:
                        async for item in fn(*args, **kwargs):
                            await loop.run_in_executor(
                                None, self._push_gen_item_sealed, spec, count,
                                item, conn)
                            count += 1
                        return {"status": "ok", "streamed": count,
                                "node_id": self.node_id}
                    except Exception as e:
                        tb = traceback.format_exc()
                        logger.error("async streaming %s failed:\n%s",
                                     spec.name, tb)
                        return {"status": "error", "streamed": count,
                                "error": TaskError(spec.name, tb,
                                                   cause=_safe_cause(e))}
                result = await fn(*args, **kwargs)
                if spec.num_returns == self.STREAMING:
                    if not inspect.isgenerator(result):
                        raise TypeError(
                            'num_returns="streaming" requires a generator')
                    return await loop.run_in_executor(
                        None, self._stream_generator, conn, spec, result)
                returns = await loop.run_in_executor(
                    None, self._package_returns, spec, result)
                return {"status": "ok", "returns": returns,
                        "node_id": self.node_id}
            except Exception as e:
                tb = traceback.format_exc()
                logger.error("async task %s failed:\n%s", spec.name, tb)
                return {"status": "error",
                        "error": TaskError(spec.name, tb,
                                           cause=_safe_cause(e))}
            finally:
                if applied is not None:
                    applied.undo()
                self.core.current_task_name = None

    def _push_gen_item_sealed(self, spec, index, item, conn):
        """Executor-thread shim so async generators reuse the blocking push
        (which itself round-trips through the loop for the socket write)."""
        self._push_gen_item(conn, spec, index, item)

    @staticmethod
    def _is_async_callable(fn) -> bool:
        target = getattr(fn, "__func__", fn)
        return (inspect.iscoroutinefunction(target)
                or inspect.isasyncgenfunction(target))

    async def _tracked(self, awaitable):
        """Count in-flight executions (queued + running) for actor_stats."""
        self._tasks_pending += 1
        try:
            return await awaitable
        finally:
            self._tasks_pending -= 1

    async def _drain_borrows(self):
        """Borrow RPCs spawned while deserializing args/results must land
        before the reply releases the submitter's pins (use-after-free
        window otherwise — see CoreWorker.register_ref)."""
        futs = self.core.take_pending_borrows()
        if futs:
            await asyncio.gather(
                *[asyncio.wrap_future(f) for f in futs],
                return_exceptions=True)

    async def handle_push_task2(self, conn, m: bytes):
        """Typed-schema task push (wire.TaskSpecMsg in, TaskReplyMsg out):
        the envelope evolves per-field across versions; args/returns stay
        pickled payloads. Old workers lack this handler and the submitter
        falls back to the legacy pickled-spec push."""
        from ray_tpu.runtime import wire

        reply = await self.handle_push_task(conn, TaskSpec.from_wire(m))
        return wire.TaskReplyMsg.from_reply(reply).encode()

    async def handle_push_actor_task2(self, conn, m: bytes):
        """Typed-schema actor call (same envelope: TaskSpecMsg carries
        actor_id/method_name/seq_no for the ordered actor send path)."""
        from ray_tpu.runtime import wire

        reply = await self.handle_push_actor_task(conn, TaskSpec.from_wire(m))
        return wire.TaskReplyMsg.from_reply(reply).encode()

    async def handle_push_task(self, conn, spec: TaskSpec):
        fn = self._load_function(spec.fn_id)
        loop = asyncio.get_event_loop()
        if self._is_async_callable(fn):
            exec_task = asyncio.ensure_future(
                self._execute_async(fn, spec, conn))
            self._running_async[spec.task_id] = exec_task
            try:
                reply = await self._tracked(exec_task)
            except asyncio.CancelledError:
                from ray_tpu.core.exceptions import TaskCancelledError

                reply = {"status": "error", "error": TaskCancelledError(
                    f"task {spec.name} was cancelled")}
            finally:
                self._running_async.pop(spec.task_id, None)
        else:
            reply = await self._tracked(loop.run_in_executor(
                self.exec_pool, self._execute, fn, spec, conn))
        await self._drain_borrows()
        return reply

    async def handle_cancel_task(self, conn, task_id: bytes,
                                 force: bool = False):
        """Best-effort in-flight cancellation (ray.cancel analog).

        Sync tasks: TaskCancelledError is raised asynchronously in the
        executing thread (PyThreadState_SetAsyncExc — takes effect at the
        next Python bytecode; a blocking C call defers it). Async tasks:
        the asyncio task is cancelled. force=True exits the worker
        process after replying — the owner maps the resulting connection
        loss to TaskCancelledError, never a retry."""
        import ctypes

        from ray_tpu.core.exceptions import TaskCancelledError

        delivered = False
        atask = self._running_async.get(task_id)
        if atask is not None and not atask.done():
            atask.cancel()
            delivered = True
        tid = self._running_threads.get(task_id)
        if not delivered and tid is not None:
            n = ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(tid), ctypes.py_object(TaskCancelledError))
            delivered = n == 1
            if delivered and self._running_threads.get(task_id) != tid:
                # TOCTOU: the target finished and the reused pool thread
                # started a DIFFERENT task between lookup and injection —
                # revoke before the pending exception fires in it.
                # bare None ctypes-converts to NULL = "clear pending exc"
                ctypes.pythonapi.PyThreadState_SetAsyncExc(
                    ctypes.c_ulong(tid), None)
                delivered = False
        if force and (delivered or tid is not None or atask is not None):
            loop = asyncio.get_event_loop()
            loop.call_later(0.05, os._exit, 1)
        return {"ok": delivered, "force": force}

    # ---- actor lifecycle --------------------------------------------------

    async def handle_create_actor(self, conn, spec: ActorSpec):
        logger.debug("create_actor %s (%s) max_concurrency=%d",
                     spec.actor_id.hex()[:12], spec.class_name,
                     spec.max_concurrency)

        def _create():
            from ray_tpu import runtime_env as renv_mod

            # Actor envs persist for the actor's lifetime (no undo).
            renv_mod.apply_runtime_env(
                self.core, spec.runtime_env, self.core.session_dir)
            cls = self._load_class(spec.class_id)
            args, kwargs = self.core.resolve_args(
                TaskSpec(task_id=b"\0" * 20, fn_id=b"", name="__init__",
                         args=spec.args, kwarg_names=spec.kwarg_names))
            self.actor_instance = cls(*args, **kwargs)
            self.actor_spec = spec
            self.core.current_actor_id = spec.actor_id
            return {"ok": True}

        if spec.max_concurrency > 1:
            self.exec_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=spec.max_concurrency, thread_name_prefix="actor_exec")
            self._async_sem = asyncio.Semaphore(spec.max_concurrency)
        else:
            # Async actors default to high concurrency unless the user caps
            # it (reference: async actors' max_concurrency defaults to 1000).
            self._async_sem = asyncio.Semaphore(1000)
        loop = asyncio.get_event_loop()
        try:
            result = await loop.run_in_executor(self.exec_pool, _create)
            logger.debug("create_actor %s: instance constructed",
                         spec.actor_id.hex()[:12])
            # Borrow RPCs for ObjectRefs deserialized in constructor args
            # must land before the creator sees the reply and unpins them
            # (same window handle_push_task closes).
            await self._drain_borrows()
            logger.debug("create_actor %s: borrows drained",
                         spec.actor_id.hex()[:12])
            await self._raylet_client.call("mark_actor", worker_id=self.worker_id,
                                           actor_id=spec.actor_id)
            logger.debug("create_actor %s: marked", spec.actor_id.hex()[:12])
            return result
        except Exception as e:
            tb = traceback.format_exc()
            logger.error("actor creation failed:\n%s", tb)
            return {"ok": False, "error": f"{e!r}\n{tb}"}

    async def handle_push_actor_task(self, conn, spec: TaskSpec):
        if self.actor_instance is None:
            return {"status": "error",
                    "error": TaskError(spec.name, "no actor instance on this worker")}
        if spec.method_name == "__ray_dag_loop__":
            # Compiled-graph loop (ray_tpu/dag/executor.py): runs READ ->
            # COMPUTE -> WRITE iterations against this actor instance until
            # the input channel delivers a close token.
            from ray_tpu.dag import executor as dag_executor

            instance = self.actor_instance

            def method(plan):
                return dag_executor.run_loop(instance, plan)
        else:
            method = getattr(self.actor_instance, spec.method_name, None)
        if method is None:
            return {"status": "error",
                    "error": TaskError(
                        spec.name,
                        f"actor has no method {spec.method_name!r}")}
        loop = asyncio.get_event_loop()
        if self._is_async_callable(method):
            reply = await self._tracked(self._execute_async(method, spec, conn))
        else:
            reply = await self._tracked(loop.run_in_executor(
                self.exec_pool, self._execute, method, spec, conn))
        await self._drain_borrows()
        return reply

    async def handle_actor_stats(self, conn):
        """Execution-queue stats, served directly on the IO loop so callers
        (serve autoscaling) never queue behind user code."""
        return {"pending": self._tasks_pending,
                "max_concurrency": (self.actor_spec.max_concurrency
                                    if self.actor_spec else 1)}

    async def handle_ping(self, conn):
        return {"ok": True}

    async def handle_dump_spans(self, conn):
        """Cluster trace aggregation: hand this process's span ring to the
        raylet fan-in (`scripts timeline --cluster`). Served on the IO loop
        — the ring is a lock-guarded deque, so a busy task never blocks
        the dump."""
        from ray_tpu.util import tracing

        return tracing.get_spans()

    async def handle_dump_stacks(self, conn):
        """Hang diagnosis: every thread's stack annotated with task/actor
        context and blocked-on records (see utils/debug.render_stacks).
        Served on the IO loop — works precisely when the exec threads are
        wedged, which is the whole point."""
        from ray_tpu.utils import debug

        label = f"worker:{os.environ.get('RAY_TPU_WORKER_ID', os.getpid())}"
        if self.actor_spec is not None:
            label += f" actor:{self.actor_spec.actor_id.hex()[:12]}"
        return debug.render_stacks(label)

    async def handle_list_objects(self, conn, limit: int = 1000):
        """Owner-side object table of this worker process (fanned in by the
        raylet for `state.summarize_objects()` / `scripts memory
        --cluster`)."""
        return self.core.object_table(limit=limit)

    async def handle_exit(self, conn):
        asyncio.get_event_loop().call_later(0.05, sys.exit, 0)
        return {"ok": True}


def _safe_cause(e: BaseException):
    """Exceptions must survive pickling across the wire; fall back to repr."""
    try:
        cloudpickle.dumps(e)
        return e
    except Exception:
        return None


def main():
    logging.basicConfig(
        level=os.environ.get("RAY_TPU_LOG_LEVEL", "INFO"),
        format="[worker %(asctime)s %(levelname)s %(name)s] %(message)s")
    from ray_tpu.utils.debug import register_stack_dump_signal

    register_stack_dump_signal()
    runtime = WorkerRuntime()

    async def run():
        await runtime.start()
        await asyncio.Event().wait()  # serve until killed

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
