"""Worker process: executes tasks and hosts actors.

Reference analog: the worker side of src/ray/core_worker/ — HandlePushTask
(core_worker.cc:3810) -> TaskReceiver -> ExecuteTask (:3229), actor creation
(:2556 target side), with the Python function loading of
python/ray/_private/function_manager.py (pickled defs from GCS KV).

The process runs two halves:
  * an asyncio RPC server (this module) that receives pushed tasks, and
  * a CoreWorker (ray_tpu.core.worker) so user code inside tasks can submit
    nested tasks / use the object store — the full API works in workers.
Execution happens on a thread pool (serial by default; actors can raise
max_concurrency), keeping the IO loop responsive.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import logging
import os
import sys
import threading
import traceback
from typing import Any, Dict, Optional

import cloudpickle

from ray_tpu.core import serialization
from ray_tpu.core.exceptions import TaskError
from ray_tpu.core.task_spec import ActorSpec, TaskSpec
from ray_tpu.core.worker import CoreWorker, INLINE_RESULT_MAX, set_global_worker
from ray_tpu.runtime.rpc import RpcClient, RpcServer
from ray_tpu.utils.ids import ObjectID, TaskID

logger = logging.getLogger(__name__)


class WorkerRuntime:
    def __init__(self):
        self.worker_id = bytes.fromhex(os.environ["RAY_TPU_WORKER_ID"])
        self.node_id = bytes.fromhex(os.environ["RAY_TPU_NODE_ID"])
        raylet = os.environ["RAY_TPU_RAYLET_ADDR"].rsplit(":", 1)
        gcs = os.environ["RAY_TPU_GCS_ADDR"].rsplit(":", 1)
        self.raylet_addr = (raylet[0], int(raylet[1]))
        self.gcs_addr = (gcs[0], int(gcs[1]))
        self.store_path = os.environ["RAY_TPU_STORE_PATH"]
        self.session_dir = os.environ["RAY_TPU_SESSION_DIR"]
        self.server = RpcServer("127.0.0.1", 0)
        self.server.register_all(self)
        self.core: Optional[CoreWorker] = None
        self.exec_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="task_exec")
        self.fn_cache: Dict[bytes, Any] = {}
        self.actor_instance = None
        self.actor_spec: Optional[ActorSpec] = None
        self._raylet_client: Optional[RpcClient] = None

    async def start(self):
        # CoreWorker first: user code needs the full API during tasks.
        self.core = CoreWorker(
            mode="worker", gcs_address=self.gcs_addr,
            raylet_address=self.raylet_addr, store_path=self.store_path,
            session_dir=self.session_dir, node_id=self.node_id)
        set_global_worker(self.core)
        await self.server.start()
        self._raylet_client = RpcClient(*self.raylet_addr)
        await self._raylet_client.connect(timeout=30)
        await self._raylet_client.call(
            "worker_ready", worker_id=self.worker_id, address=self.server.address)
        asyncio.ensure_future(self._orphan_watchdog())
        logger.info("worker %s ready at %s", self.worker_id.hex()[:12],
                    self.server.address)

    async def _orphan_watchdog(self):
        """Exit when our raylet goes away (worker processes must not outlive
        their node, even when the raylet is SIGKILLed)."""
        while not self._raylet_client._dead:
            await asyncio.sleep(1.0)
        logger.warning("raylet connection lost; worker exiting")
        os._exit(1)

    # ---- function/class loading (function_manager.py analog) -------------

    def _load_function(self, fn_id: bytes):
        fn = self.fn_cache.get(fn_id)
        if fn is None:
            reply = self.core.io.run(self.core.gcs.call("kv_get", key=b"fn:" + fn_id))
            blob = reply["value"]
            if blob is None:
                raise RuntimeError(f"function {fn_id.hex()[:12]} not found in GCS")
            fn = cloudpickle.loads(blob)
            self.fn_cache[fn_id] = fn
        return fn

    def _load_class(self, class_id: bytes):
        cls = self.fn_cache.get(class_id)
        if cls is None:
            reply = self.core.io.run(self.core.gcs.call("kv_get", key=b"cls:" + class_id))
            blob = reply["value"]
            if blob is None:
                raise RuntimeError(f"class {class_id.hex()[:12]} not found in GCS")
            cls = cloudpickle.loads(blob)
            self.fn_cache[class_id] = cls
        return cls

    # ---- task execution ---------------------------------------------------

    def _execute(self, fn, spec: TaskSpec) -> dict:
        """Runs on the exec thread; returns the RPC reply."""
        from ray_tpu import runtime_env as renv_mod
        from ray_tpu.util import tracing

        applied = None
        try:
            applied = renv_mod.apply_runtime_env(
                self.core, spec.runtime_env, self.core.session_dir)
            args, kwargs = self.core.resolve_args(spec)
            self.core.current_task_name = spec.name
            with tracing.span(spec.name, "task:execute",
                              task_id=spec.task_id.hex()[:12]):
                result = fn(*args, **kwargs)
            returns = []
            values = (result,) if spec.num_returns == 1 else tuple(result)
            if spec.num_returns > 1 and len(values) != spec.num_returns:
                raise ValueError(
                    f"task declared num_returns={spec.num_returns} but returned "
                    f"{len(values)} values")
            for i, value in enumerate(values):
                segments, total = serialization.serialize(value)
                oid = ObjectID.for_task_return(TaskID(spec.task_id), i).binary()
                if total <= INLINE_RESULT_MAX:
                    returns.append(("v", serialization.join_segments(segments)))
                else:
                    store = self.core.store
                    if store.contains(oid):
                        # Retry of a task whose previous attempt already sealed
                        # this return: reuse it (ids are deterministic).
                        returns.append(("r", oid))
                        continue
                    # A crashed previous attempt may have left an unsealed
                    # create behind; reclaim the id.
                    store.abort(oid)
                    buf = self.core.spill_create(oid, total)
                    try:
                        serialization.write_segments(buf, segments)
                    except BaseException:
                        buf.release()
                        store.abort(oid)
                        raise
                    buf.release()
                    store.seal(oid)
                    returns.append(("r", oid))
            return {"status": "ok", "returns": returns, "node_id": self.node_id}
        except Exception as e:
            tb = traceback.format_exc()
            logger.error("task %s failed:\n%s", spec.name, tb)
            return {"status": "error",
                    "error": TaskError(spec.name, tb, cause=_safe_cause(e))}
        finally:
            if applied is not None:
                applied.undo()
            self.core.current_task_name = None

    async def handle_push_task(self, conn, spec: TaskSpec):
        fn = self._load_function(spec.fn_id)
        loop = asyncio.get_event_loop()
        return await loop.run_in_executor(self.exec_pool, self._execute, fn, spec)

    # ---- actor lifecycle --------------------------------------------------

    async def handle_create_actor(self, conn, spec: ActorSpec):
        def _create():
            from ray_tpu import runtime_env as renv_mod

            # Actor envs persist for the actor's lifetime (no undo).
            renv_mod.apply_runtime_env(
                self.core, spec.runtime_env, self.core.session_dir)
            cls = self._load_class(spec.class_id)
            args, kwargs = self.core.resolve_args(
                TaskSpec(task_id=b"\0" * 20, fn_id=b"", name="__init__",
                         args=spec.args, kwarg_names=spec.kwarg_names))
            self.actor_instance = cls(*args, **kwargs)
            self.actor_spec = spec
            self.core.current_actor_id = spec.actor_id
            return {"ok": True}

        if spec.max_concurrency > 1:
            self.exec_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=spec.max_concurrency, thread_name_prefix="actor_exec")
        loop = asyncio.get_event_loop()
        try:
            result = await loop.run_in_executor(self.exec_pool, _create)
            await self._raylet_client.call("mark_actor", worker_id=self.worker_id,
                                           actor_id=spec.actor_id)
            return result
        except Exception as e:
            tb = traceback.format_exc()
            logger.error("actor creation failed:\n%s", tb)
            return {"ok": False, "error": f"{e!r}\n{tb}"}

    async def handle_push_actor_task(self, conn, spec: TaskSpec):
        if self.actor_instance is None:
            return {"status": "error",
                    "error": TaskError(spec.name, "no actor instance on this worker")}
        if spec.method_name == "__ray_dag_loop__":
            # Compiled-graph loop (ray_tpu/dag/executor.py): runs READ ->
            # COMPUTE -> WRITE iterations against this actor instance until
            # the input channel delivers a close token.
            from ray_tpu.dag import executor as dag_executor

            instance = self.actor_instance

            def method(plan):
                return dag_executor.run_loop(instance, plan)
        else:
            method = getattr(self.actor_instance, spec.method_name, None)
        if method is None:
            return {"status": "error",
                    "error": TaskError(
                        spec.name,
                        f"actor has no method {spec.method_name!r}")}
        loop = asyncio.get_event_loop()
        return await loop.run_in_executor(self.exec_pool, self._execute, method, spec)

    async def handle_ping(self, conn):
        return {"ok": True}

    async def handle_exit(self, conn):
        asyncio.get_event_loop().call_later(0.05, sys.exit, 0)
        return {"ok": True}


def _safe_cause(e: BaseException):
    """Exceptions must survive pickling across the wire; fall back to repr."""
    try:
        cloudpickle.dumps(e)
        return e
    except Exception:
        return None


def main():
    logging.basicConfig(
        level=os.environ.get("RAY_TPU_LOG_LEVEL", "INFO"),
        format="[worker %(asctime)s %(levelname)s %(name)s] %(message)s")
    runtime = WorkerRuntime()

    async def run():
        await runtime.start()
        await asyncio.Event().wait()  # serve until killed

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
