"""Node memory monitor + OOM worker-killing policy.

Reference analog: src/ray/common/memory_monitor.{h,cc} (memory_monitor.h:52,
usage_threshold callback) and src/ray/raylet/worker_killing_policy*.{h,cc}
(retriable-FIFO: prefer killing the most recently started retriable work so
long-running tasks survive). The raylet polls usage and, above the threshold,
kills one worker per tick; the lease/retry machinery resubmits its task.

Test hook: RAY_TPU_MEMORY_MONITOR_TEST_FILE names a file whose content is a
fake usage fraction — lets OOM tests run without real memory pressure.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger(__name__)

DEFAULT_THRESHOLD = float(os.environ.get("RAY_TPU_MEMORY_USAGE_THRESHOLD", "0.95"))


def node_memory_usage_fraction() -> Optional[float]:
    """Used/total from /proc/meminfo (MemAvailable-based, like the
    reference's cgroup-aware path); None if unreadable."""
    test_file = os.environ.get("RAY_TPU_MEMORY_MONITOR_TEST_FILE")
    if test_file:
        try:
            with open(test_file) as f:
                return float(f.read().strip())
        except Exception:
            return None
    try:
        info = {}
        with open("/proc/meminfo") as f:
            for line in f:
                key, _, rest = line.partition(":")
                info[key] = int(rest.split()[0])  # kB
        total = info["MemTotal"]
        avail = info.get("MemAvailable", info.get("MemFree", 0))
        return 1.0 - avail / total
    except Exception:
        return None


def process_rss_bytes(pid: int) -> int:
    try:
        with open(f"/proc/{pid}/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except Exception:
        return 0


class MemoryMonitor:
    """Polled by the raylet; picks the kill victim when over threshold."""

    def __init__(self, threshold: float = DEFAULT_THRESHOLD):
        self.threshold = threshold

    def over_threshold(self) -> bool:
        frac = node_memory_usage_fraction()
        return frac is not None and frac >= self.threshold

    def pick_victim(self, workers: list) -> Optional[object]:
        """Retriable-FIFO policy: among busy workers, kill the one whose task
        started most recently (preferring non-actor workers — actor state is
        lost on kill; tasks just retry)."""
        candidates = [w for w in workers if getattr(w, "busy_since", None)]
        if not candidates:
            return None
        non_actors = [w for w in candidates
                      if not getattr(w, "actor_id", None)]
        pool = non_actors or candidates
        return max(pool, key=lambda w: w.busy_since)
