"""Node bootstrap: start/stop the head-node process tree.

Reference analog: python/ray/_private/node.py (:1117-1429) and services.py
(start_gcs_server:1445, start_raylet:1529): the driver spawns the GCS and a
raylet as subprocesses and connects to them.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import uuid
from typing import Dict, Optional, Tuple


class NodeProcesses:
    def __init__(self, session_dir: str):
        self.session_dir = session_dir
        self.gcs_proc: Optional[subprocess.Popen] = None
        self.raylet_proc: Optional[subprocess.Popen] = None
        self.dashboard_proc: Optional[subprocess.Popen] = None
        self.dashboard_url: Optional[str] = None
        self.gcs_address: Optional[Tuple[str, int]] = None
        self.raylet_address: Optional[Tuple[str, int]] = None
        self.node_id: Optional[bytes] = None
        self.store_path: Optional[str] = None


def new_session_dir() -> str:
    base = os.environ.get("RAY_TPU_TMPDIR", "/tmp/ray_tpu")
    session = os.path.join(base, f"session_{int(time.time())}_{uuid.uuid4().hex[:8]}")
    os.makedirs(os.path.join(session, "logs"), exist_ok=True)
    # session_latest lets same-host attachers (CLI status/join, driver
    # init(address=...)) find the auth token without an env var (reference
    # analog: /tmp/ray/session_latest).
    latest = os.path.join(base, "session_latest")
    tmp = f"{latest}.{os.getpid()}.tmp"
    try:
        os.symlink(session, tmp)
        os.replace(tmp, latest)
    except OSError:
        pass
    return session


def _wait_file(path: str, timeout: float, proc: subprocess.Popen, what: str) -> str:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            with open(path) as f:
                return f.read()
        if proc.poll() is not None:
            raise RuntimeError(f"{what} exited with code {proc.returncode} during startup "
                               f"(logs in {os.path.dirname(path)})")
        time.sleep(0.02)
    raise RuntimeError(f"timed out waiting for {what} to start")


def ensure_auth_token(session_dir: str) -> None:
    """Mint the per-session wire-auth token (rpc.py challenge-response).

    Every cluster process descends from the process that starts the GCS, so
    setting RAY_TPU_AUTH_TOKEN here propagates to GCS/raylet/worker/driver
    children via env inheritance; the 0600 session file lets a same-host
    operator attach out-of-band. An already-set env token is kept (attach
    to an existing cluster / explicit operator-provided token)."""
    if os.environ.get("RAY_TPU_AUTH_TOKEN"):
        token_hex = os.environ["RAY_TPU_AUTH_TOKEN"]
        try:
            bytes.fromhex(token_hex)
        except ValueError:
            raise RuntimeError(
                "RAY_TPU_AUTH_TOKEN must be a hex string; "
                f"got {len(token_hex)} chars of non-hex")
    else:
        token_hex = os.urandom(32).hex()
        os.environ["RAY_TPU_AUTH_TOKEN"] = token_hex
    path = os.path.join(session_dir, "auth_token")
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "w") as f:
        f.write(token_hex)
    from ray_tpu.runtime import rpc

    rpc.set_session_token(bytes.fromhex(token_hex))


def start_gcs(session_dir: str, port: int = 0,
              storage: Optional[str] = None
              ) -> Tuple[subprocess.Popen, Tuple[str, int]]:
    """storage defaults to <session>/gcs.db — GCS restarts recover state
    (pass storage="" to run purely in-memory)."""
    ensure_auth_token(session_dir)
    if storage is None:
        storage = os.path.join(session_dir, "gcs.db")
    ready = os.path.join(session_dir, f"gcs_ready_{os.getpid()}_{port}")
    try:
        os.unlink(ready)
    except OSError:
        pass
    log = open(os.path.join(session_dir, "logs", "gcs.log"), "ab")
    cmd = [sys.executable, "-m", "ray_tpu.runtime.gcs.main",
           "--ready-file", ready, "--port", str(port)]
    if storage:
        cmd += ["--storage", storage]
    proc = subprocess.Popen(
        cmd, stdout=log, stderr=subprocess.STDOUT, start_new_session=True)
    log.close()
    addr = _wait_file(ready, 60, proc, "GCS")
    host, port = addr.rsplit(":", 1)
    # Record the address in the session dir so same-host attachers can
    # resolve the RIGHT session's auth token by the address they attach to
    # (session_latest alone mis-resolves when two clusters share a host —
    # rpc.load_token_for_address scans these files).
    with open(os.path.join(session_dir, "gcs_address"), "w") as f:
        f.write(f"{host}:{port}")
    return proc, (host, int(port))


def start_raylet(session_dir: str, gcs_address: Tuple[str, int],
                 resources: Dict[str, float], labels: Dict[str, str],
                 object_store_memory: int, is_head: bool = False,
                 worker_env: Optional[Dict[str, str]] = None,
                 name: str = "raylet") -> Tuple[subprocess.Popen, dict]:
    ready = os.path.join(session_dir, f"{name}_ready_{uuid.uuid4().hex[:6]}")
    log = open(os.path.join(session_dir, "logs", f"{name}.log"), "ab")
    cmd = [sys.executable, "-m", "ray_tpu.runtime.raylet.main",
           "--gcs-address", f"{gcs_address[0]}:{gcs_address[1]}",
           "--session-dir", session_dir,
           "--resources", json.dumps(resources),
           "--labels", json.dumps(labels),
           "--object-store-memory", str(object_store_memory),
           "--worker-env", json.dumps(worker_env or {}),
           "--ready-file", ready]
    if is_head:
        cmd.append("--is-head")
    proc = subprocess.Popen(cmd, stdout=log, stderr=subprocess.STDOUT,
                            start_new_session=True)
    log.close()
    info = json.loads(_wait_file(ready, 60, proc, "raylet"))
    return proc, info


def start_dashboard(session_dir: str, gcs_address: Tuple[str, int],
                    host: str = "127.0.0.1", port: int = 0
                    ) -> Tuple[subprocess.Popen, str]:
    """Start the dashboard head (REST/metrics/job API) as a subprocess.

    Reference analog: _private/services.py start_dashboard -> dashboard/head.py.
    Returns (proc, url). The child prints a {"port": N} JSON line once bound.
    """
    import json

    log_path = os.path.join(session_dir, "logs", "dashboard.log")
    log = open(log_path, "ab")
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.dashboard.head",
         "--gcs-address", f"{gcs_address[0]}:{gcs_address[1]}",
         "--session-dir", session_dir, "--host", host, "--port", str(port)],
        stdout=subprocess.PIPE, stderr=log, start_new_session=True)
    log.close()
    # Non-blocking read of the child's {"port": N} announce line: readline()
    # would ignore the deadline if the child hangs before printing.
    import select

    fd = proc.stdout.fileno()
    os.set_blocking(fd, False)
    buf = b""
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"dashboard exited with code {proc.returncode}; see {log_path}")
        if select.select([fd], [], [], 0.2)[0]:
            chunk = os.read(fd, 4096)
            if chunk:
                buf += chunk
            if b"\n" in buf:
                break
    line = buf.split(b"\n", 1)[0].strip()
    if not line:
        proc.kill()
        raise RuntimeError(
            f"dashboard did not announce its port within 30s; see {log_path}")
    bound = json.loads(line)["port"]
    return proc, f"http://{host}:{bound}"
