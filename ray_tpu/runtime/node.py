"""Node bootstrap: start/stop the head-node process tree.

Reference analog: python/ray/_private/node.py (:1117-1429) and services.py
(start_gcs_server:1445, start_raylet:1529): the driver spawns the GCS and a
raylet as subprocesses and connects to them.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import uuid
from typing import Dict, Optional, Tuple


class NodeProcesses:
    def __init__(self, session_dir: str):
        self.session_dir = session_dir
        self.gcs_proc: Optional[subprocess.Popen] = None
        self.raylet_proc: Optional[subprocess.Popen] = None
        self.gcs_address: Optional[Tuple[str, int]] = None
        self.raylet_address: Optional[Tuple[str, int]] = None
        self.node_id: Optional[bytes] = None
        self.store_path: Optional[str] = None


def new_session_dir() -> str:
    base = os.environ.get("RAY_TPU_TMPDIR", "/tmp/ray_tpu")
    session = os.path.join(base, f"session_{int(time.time())}_{uuid.uuid4().hex[:8]}")
    os.makedirs(os.path.join(session, "logs"), exist_ok=True)
    return session


def _wait_file(path: str, timeout: float, proc: subprocess.Popen, what: str) -> str:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            with open(path) as f:
                return f.read()
        if proc.poll() is not None:
            raise RuntimeError(f"{what} exited with code {proc.returncode} during startup "
                               f"(logs in {os.path.dirname(path)})")
        time.sleep(0.02)
    raise RuntimeError(f"timed out waiting for {what} to start")


def start_gcs(session_dir: str) -> Tuple[subprocess.Popen, Tuple[str, int]]:
    ready = os.path.join(session_dir, "gcs_ready")
    log = open(os.path.join(session_dir, "logs", "gcs.log"), "ab")
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.runtime.gcs.main", "--ready-file", ready],
        stdout=log, stderr=subprocess.STDOUT, start_new_session=True)
    log.close()
    addr = _wait_file(ready, 60, proc, "GCS")
    host, port = addr.rsplit(":", 1)
    return proc, (host, int(port))


def start_raylet(session_dir: str, gcs_address: Tuple[str, int],
                 resources: Dict[str, float], labels: Dict[str, str],
                 object_store_memory: int, is_head: bool = False,
                 worker_env: Optional[Dict[str, str]] = None,
                 name: str = "raylet") -> Tuple[subprocess.Popen, dict]:
    ready = os.path.join(session_dir, f"{name}_ready_{uuid.uuid4().hex[:6]}")
    log = open(os.path.join(session_dir, "logs", f"{name}.log"), "ab")
    cmd = [sys.executable, "-m", "ray_tpu.runtime.raylet.main",
           "--gcs-address", f"{gcs_address[0]}:{gcs_address[1]}",
           "--session-dir", session_dir,
           "--resources", json.dumps(resources),
           "--labels", json.dumps(labels),
           "--object-store-memory", str(object_store_memory),
           "--worker-env", json.dumps(worker_env or {}),
           "--ready-file", ready]
    if is_head:
        cmd.append("--is-head")
    proc = subprocess.Popen(cmd, stdout=log, stderr=subprocess.STDOUT,
                            start_new_session=True)
    log.close()
    info = json.loads(_wait_file(ready, 60, proc, "raylet"))
    return proc, info
