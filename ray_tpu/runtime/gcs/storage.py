"""GCS persistence: the StoreClient seam.

Reference analog: src/ray/gcs/store_client/ — InMemoryStoreClient (default)
and RedisStoreClient (fault-tolerant mode; gcs restarts and reloads its
tables, clients resubscribe — redis_store_client.h). The TPU build's durable
backend is sqlite (WAL mode): one file next to the session, no external
service, safe across GCS process crashes.

Tables are generic (table, key) -> value-bytes maps; the GcsServer decides
what goes in them (kv, nodes, actors, named_actors, jobs, placement_groups).
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Dict, Iterable, List, Optional, Tuple


class InMemoryStoreClient:
    """Default: no durability (in_memory_store_client.h analog)."""

    def __init__(self):
        self._tables: Dict[str, Dict[bytes, bytes]] = {}

    def put(self, table: str, key: bytes, value: bytes):
        self._tables.setdefault(table, {})[key] = value

    def get(self, table: str, key: bytes) -> Optional[bytes]:
        return self._tables.get(table, {}).get(key)

    def delete(self, table: str, key: bytes):
        self._tables.get(table, {}).pop(key, None)

    def keys(self, table: str, prefix: bytes = b"") -> List[bytes]:
        return [k for k in self._tables.get(table, {}) if k.startswith(prefix)]

    def load_all(self, table: str) -> Iterable[Tuple[bytes, bytes]]:
        return list(self._tables.get(table, {}).items())

    def close(self):
        pass


class SqliteStoreClient:
    """Durable backend (RedisStoreClient analog). WAL journal so readers
    don't block the single writer; NORMAL sync keeps mutation latency low
    while surviving process crashes (a host power loss may drop the last
    transactions — same durability class as default Redis AOF everysec)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS gcs (tbl TEXT, key BLOB, value BLOB, "
            "PRIMARY KEY (tbl, key))")
        self._conn.commit()

    def put(self, table: str, key: bytes, value: bytes):
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO gcs (tbl, key, value) VALUES (?,?,?)",
                (table, key, value))
            self._conn.commit()

    def get(self, table: str, key: bytes) -> Optional[bytes]:
        with self._lock:
            row = self._conn.execute(
                "SELECT value FROM gcs WHERE tbl=? AND key=?",
                (table, key)).fetchone()
        return row[0] if row else None

    def delete(self, table: str, key: bytes):
        with self._lock:
            self._conn.execute("DELETE FROM gcs WHERE tbl=? AND key=?",
                               (table, key))
            self._conn.commit()

    def keys(self, table: str, prefix: bytes = b"") -> List[bytes]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT key FROM gcs WHERE tbl=?", (table,)).fetchall()
        return [r[0] for r in rows if bytes(r[0]).startswith(prefix)]

    def load_all(self, table: str) -> Iterable[Tuple[bytes, bytes]]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT key, value FROM gcs WHERE tbl=?", (table,)).fetchall()
        return [(bytes(k), bytes(v)) for k, v in rows]

    def close(self):
        with self._lock:
            self._conn.close()
