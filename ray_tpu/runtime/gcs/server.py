"""GCS: the cluster-global control service.

Reference analog: src/ray/gcs/gcs_server/ (GcsServer gcs_server.h:89). One per
cluster. Owns: internal KV (function/class table lives here —
gcs_function_manager.h:32), node table (gcs_node_manager), actor directory +
lifecycle state machine (gcs_actor_manager.h:291), named actors, placement
groups (gcs_placement_group_manager, 2-phase Prepare/Commit), and cluster
pubsub (InternalPubSubHandler). Persistence is the in-memory store client
(in_memory_store_client.h); the StoreClient seam for a Redis-backed version
is `self._kv` + the table dicts.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
import zlib
from typing import Any, Dict, List, Optional, Set, Tuple

from ray_tpu.core.task_spec import ActorSpec
from ray_tpu.runtime.rpc import RpcClient, RpcServer, ServerConnection
from ray_tpu.runtime import scheduling

logger = logging.getLogger(__name__)

# Actor lifecycle states (gcs_actor_manager.h state machine)
PENDING_CREATION = "PENDING_CREATION"
ALIVE = "ALIVE"
RESTARTING = "RESTARTING"
DEAD = "DEAD"


def _find_cycles(graph: dict) -> list:
    """Distinct elementary cycles of a small digraph (iterative DFS; the
    wait-graph has one node per blocked actor/process, so tiny). Each
    cycle is reported once regardless of entry point."""
    cycles, seen = [], set()
    for start in graph:
        stack = [(start, iter(graph.get(start, ())))]
        path, onpath = [start], {start}
        while stack:
            node, it = stack[-1]
            advanced = False
            for nxt in it:
                if nxt in onpath:
                    i = path.index(nxt)
                    cyc = tuple(path[i:])
                    key = frozenset(cyc)
                    if key not in seen:
                        seen.add(key)
                        cycles.append(list(cyc))
                    continue
                if nxt in graph:
                    stack.append((nxt, iter(graph.get(nxt, ()))))
                    path.append(nxt)
                    onpath.add(nxt)
                    advanced = True
                    break
            if not advanced:
                stack.pop()
                onpath.discard(path.pop())
    return cycles


class NodeRecord:
    def __init__(self, node_id: bytes, address: Tuple[str, int], resources: Dict[str, float],
                 object_store_path: str, is_head: bool, labels: Dict[str, str]):
        self.node_id = node_id
        self.address = address
        self.resources = dict(resources)
        self.available = dict(resources)  # updated by resource reports
        self.object_store_path = object_store_path
        self.is_head = is_head
        self.labels = dict(labels)
        self.alive = True
        self.last_heartbeat = time.monotonic()
        self.client: Optional[RpcClient] = None
        # Latest per-scheduling-class lease backlog reported by heartbeat.
        self.backlog: List[dict] = []
        # Two-phase drain (DrainNode analog, node_manager.proto): the node
        # is still ALIVE — running work finishes, objects stay readable —
        # but the scheduler/PGs route around it until drain_deadline
        # (wall-clock; drain_deadline_mono is the GCS-local enforcement
        # clock), when it is killed for real.
        self.draining = False
        self.drain_reason = ""
        self.drain_deadline = 0.0          # unix seconds (advisory, wire)
        self.drain_deadline_mono = 0.0     # monotonic (enforcement)
        # Why the node died (kept in the view so workers deciding whether a
        # death consumes retry budget can classify it — death_cause()).
        self.death_reason = ""

    def view(self) -> dict:
        return {
            "node_id": self.node_id,
            "address": self.address,
            "resources": dict(self.resources),
            "available": dict(self.available),
            "object_store_path": self.object_store_path,
            "is_head": self.is_head,
            "labels": dict(self.labels),
            "alive": self.alive,
            "draining": self.draining,
            "drain_reason": self.drain_reason,
            "drain_deadline": self.drain_deadline,
            "death_reason": self.death_reason,
        }


class ActorRecord:
    def __init__(self, spec: ActorSpec):
        self.spec = spec
        self.state = PENDING_CREATION
        self.address: Optional[Tuple[str, int]] = None
        self.node_id: Optional[bytes] = None
        self.worker_id: Optional[bytes] = None
        self.restarts_used = 0
        self.death_reason = ""

    def view(self) -> dict:
        return {
            "actor_id": self.spec.actor_id,
            "name": self.spec.name,
            "class_name": self.spec.class_name,
            "state": self.state,
            "address": self.address,
            "node_id": self.node_id,
            "restarts_used": self.restarts_used,
            "max_restarts": self.spec.max_restarts,
            "death_reason": self.death_reason,
        }


class GcsServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 storage_path: Optional[str] = None):
        from ray_tpu.runtime.gcs.storage import (
            InMemoryStoreClient,
            SqliteStoreClient,
        )

        # StoreClient seam (store_client/: in-memory vs Redis-analog sqlite).
        self._store = (SqliteStoreClient(storage_path) if storage_path
                       else InMemoryStoreClient())
        self.server = RpcServer(host, port)
        self.server.register_all(self)
        self.server.on_disconnect = self._on_disconnect
        self._kv: Dict[bytes, bytes] = {}
        self._nodes: Dict[bytes, NodeRecord] = {}
        self._actors: Dict[bytes, ActorRecord] = {}
        self._named_actors: Dict[Tuple[str, str], bytes] = {}  # (namespace, name) -> actor_id
        self._subscribers: Dict[str, Set[ServerConnection]] = {}
        self._actor_locks: Dict[bytes, asyncio.Lock] = {}
        self._pg_manager = None  # installed in M4 (placement groups)
        self._health_task = None
        self._shutdown = asyncio.Event()
        # Job/task event tables (state API)
        self._job_counter = 0
        self._jobs: Dict[int, dict] = {}
        # Strong refs to fire-and-forget tasks: asyncio holds only weak
        # refs, so an unpinned background task (e.g. the owner-death
        # shutdown) can be garbage-collected mid-await and silently vanish.
        self._bg_tasks: Set[asyncio.Task] = set()
        # Resource-view change log (ray_syncer analog; see _bump_view).
        import collections

        self._view_version = 0
        self._view_log: "collections.deque" = collections.deque(maxlen=1024)
        # Epoch/instance id: version numbers are meaningless across GCS
        # restarts (a restored raylet's old-epoch version can be <= the new
        # epoch's current version and silently skip restore-seeded entries),
        # so every view reply carries this id and a mismatch forces a full
        # snapshot.
        import uuid

        self._view_epoch = uuid.uuid4().hex

    def _spawn_bg(self, coro) -> "asyncio.Task":
        task = asyncio.ensure_future(coro)
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)
        return task

    async def start(self):
        await self.server.start()
        from ray_tpu.runtime.gcs.placement_groups import PlacementGroupManager
        self._pg_manager = PlacementGroupManager(self)
        await self._restore()
        self._health_task = asyncio.ensure_future(self._health_check_loop())
        logger.info("GCS listening on %s:%d", self.server.host, self.server.port)
        return self

    @property
    def address(self):
        return self.server.address

    # ---- persistence (gcs FT: restart + reload, redis_store_client.h) ----

    def _persist_actor(self, rec: "ActorRecord"):
        import pickle

        try:
            self._store.put("actors", rec.spec.actor_id, pickle.dumps({
                "spec": rec.spec, "state": rec.state, "address": rec.address,
                "node_id": rec.node_id, "worker_id": rec.worker_id,
                "restarts_used": rec.restarts_used,
                "death_reason": rec.death_reason}))
        except Exception:
            logger.exception("actor persist failed")

    def _persist_node(self, rec: "NodeRecord"):
        import pickle

        try:
            self._store.put("nodes", rec.node_id, pickle.dumps({
                "node_id": rec.node_id, "address": rec.address,
                "resources": rec.resources, "available": rec.available,
                "object_store_path": rec.object_store_path,
                "is_head": rec.is_head, "labels": rec.labels,
                "alive": rec.alive, "draining": rec.draining,
                "drain_reason": rec.drain_reason,
                "drain_deadline": rec.drain_deadline}))
        except Exception:
            logger.exception("node persist failed")

    def persist_pg(self, rec):
        import pickle

        try:
            self._store.put("placement_groups", rec.pg_id, pickle.dumps({
                "pg_id": rec.pg_id, "bundles": rec.bundles,
                "strategy": rec.strategy, "name": rec.name,
                "state": rec.state, "locations": rec.locations}))
        except Exception:
            logger.exception("pg persist failed")

    async def _restore(self):
        """Reload tables after a GCS restart. Raylets and workers keep
        running while the GCS is down (only control-plane ops stall); their
        reconnecting clients re-register/resubscribe when we come back
        (NotifyGCSRestart analog, node_manager.proto:401)."""
        import pickle

        for key, value in self._store.load_all("kv"):
            self._kv[key] = value
        for key, blob in self._store.load_all("autoscaler"):
            if key == b"requested_resources":
                self._requested_resources = pickle.loads(blob)
        for _, blob in self._store.load_all("jobs"):
            job = pickle.loads(blob)
            self._jobs[job["job_id"]] = job
            self._job_counter = max(self._job_counter, job["job_id"])
        restored_nodes = 0
        for _, blob in self._store.load_all("nodes"):
            d = pickle.loads(blob)
            if not d["alive"]:
                continue
            rec = NodeRecord(d["node_id"], tuple(d["address"]), d["resources"],
                             d["object_store_path"], d["is_head"], d["labels"])
            rec.available = d["available"]
            if d.get("draining"):
                # Monotonic deadlines don't survive the restart: re-derive
                # remaining notice from the persisted wall-clock deadline.
                rec.draining = True
                rec.drain_reason = d.get("drain_reason", "")
                rec.drain_deadline = d.get("drain_deadline", 0.0)
                rec.drain_deadline_mono = (
                    time.monotonic()
                    + max(0.0, rec.drain_deadline - time.time()))
            self._nodes[d["node_id"]] = rec
            restored_nodes += 1
            # Seed the view log so delta-synced raylets learn restored
            # (possibly idle, never-bumping) nodes.
            self._bump_view(rec)
            # Reconnect to the raylet in the background; health checks reap
            # it if it's truly gone.
            asyncio.ensure_future(self._reconnect_node(rec))
        for _, blob in self._store.load_all("actors"):
            d = pickle.loads(blob)
            rec = ActorRecord(d["spec"])
            rec.state = d["state"]
            rec.address = tuple(d["address"]) if d["address"] else None
            rec.node_id = d["node_id"]
            rec.worker_id = d["worker_id"]
            rec.restarts_used = d["restarts_used"]
            rec.death_reason = d["death_reason"]
            self._actors[rec.spec.actor_id] = rec
            self._actor_locks[rec.spec.actor_id] = asyncio.Lock()
            if rec.spec.name and rec.state != DEAD:
                self._named_actors[(rec.spec.namespace, rec.spec.name)] = \
                    rec.spec.actor_id
        for _, blob in self._store.load_all("placement_groups"):
            d = pickle.loads(blob)
            self._pg_manager.restore_record(d)
        # Restored PENDING groups need the retry loop running again or
        # they would only re-place on the next unrelated create/remove.
        self._pg_manager.kick()
        if restored_nodes or self._actors or self._kv:
            logger.info("GCS restored: %d nodes, %d actors, %d kv keys",
                        restored_nodes, len(self._actors), len(self._kv))

    async def _reconnect_node(self, rec: "NodeRecord"):
        try:
            client = RpcClient(*rec.address)
            await client.connect(timeout=10)
            rec.client = client
            rec.last_heartbeat = time.monotonic()
        except Exception:
            await self._mark_node_dead(rec.node_id,
                                       "unreachable after GCS restart")

    # ---- node management -------------------------------------------------

    async def handle_register_node(self, conn, node_id, address, resources,
                                   object_store_path, is_head=False, labels=None):
        rec = NodeRecord(node_id, tuple(address), resources, object_store_path,
                         is_head, labels or {})
        client = RpcClient(*rec.address)
        await client.connect(timeout=10)
        rec.client = client
        self._nodes[node_id] = rec
        conn.meta["node_id"] = node_id
        self._persist_node(rec)
        self._bump_view(rec)
        await self.publish("node", {"event": "added", "node": rec.view()})
        logger.info("node %s registered at %s resources=%s",
                    node_id.hex()[:12], rec.address, resources)
        return {"ok": True, "nodes": [n.view() for n in self._nodes.values()]}

    # ---- resource-view sync (ray_syncer analog) --------------------------
    #
    # Reference: src/ray/common/ray_syncer/ — every raylet needs an
    # eventually-consistent view of cluster resources for spillback routing.
    # Instead of each raylet pulling the FULL node table every heartbeat
    # (O(N^2) bytes/sec cluster-wide), the GCS keeps a versioned change log
    # and piggybacks only the deltas since the raylet's known version on the
    # heartbeat reply; an idle cluster exchanges empty deltas.

    def _bump_view(self, rec: "NodeRecord"):
        self._view_version += 1
        self._view_log.append((self._view_version, rec.view()))

    def _view_deltas(self, known_version: int,
                     known_epoch: Optional[str] = None):
        if (known_epoch != self._view_epoch
                or known_version > self._view_version
                or (self._view_log
                    and known_version < self._view_log[0][0] - 1)):
            # Different GCS epoch (restart — raw version numbers don't
            # compare across epochs), behind the capped log, or AHEAD of us:
            # full snapshot either way — delta-matching would silently drop
            # changes.
            return {"version": self._view_version,
                    "epoch": self._view_epoch,
                    "full": [n.view() for n in self._nodes.values()]}
        latest: Dict[bytes, dict] = {}
        for ver, view in self._view_log:
            if ver > known_version:
                latest[view["node_id"]] = view
        return {"version": self._view_version,
                "epoch": self._view_epoch,
                "deltas": list(latest.values())}

    async def handle_node_heartbeat(self, conn, node_id, available=None,
                                    backlog=None,
                                    known_version: Optional[int] = None,
                                    known_epoch: Optional[str] = None):
        rec = self._nodes.get(node_id)
        if rec is None:
            return {"ok": False, "unknown": True}
        rec.last_heartbeat = time.monotonic()
        if backlog is not None:
            # Per-scheduling-class lease backlog (autoscaler demand feed,
            # gcs_autoscaler_state_manager.cc analog). Not part of the
            # versioned view — demand is advisory, not routing state.
            rec.backlog = backlog
        if available is not None and rec.available != available:
            rec.available = dict(available)
            self._bump_view(rec)
        reply = {"ok": True}
        if known_version is not None:
            reply["view"] = self._view_deltas(known_version, known_epoch)
        return reply

    async def handle_node_heartbeat2(self, conn, m: bytes):
        """Typed-schema heartbeat (runtime/wire.py HeartbeatMsg in,
        ViewDeltaMsg out): the cross-version-evolvable twin of
        node_heartbeat. New fields on either message are invisible to old
        peers (unknown field numbers skip on decode); removed ones decode
        to defaults — protobuf evolution rules without the compiler."""
        from ray_tpu.runtime import wire

        hb = wire.HeartbeatMsg.decode(m)
        reply = await self.handle_node_heartbeat(
            conn, hb.node_id, available=hb.available or None,
            backlog=hb.backlog,
            known_version=hb.known_version if hb.known_version >= 0 else None,
            known_epoch=hb.known_epoch or None)
        view = reply.pop("view", None)
        if view is not None:
            nodes_key = "full" if "full" in view else "deltas"
            msg = wire.ViewDeltaMsg(
                version=view["version"], epoch=view.get("epoch") or "",
                is_full=nodes_key == "full")
            encoded = [wire.NodeInfoMsg(
                node_id=n["node_id"], host=n["address"][0],
                port=int(n["address"][1]), resources=n["resources"],
                available=n["available"], labels=n["labels"],
                is_head=n["is_head"], alive=n["alive"],
                object_store_path=n["object_store_path"],
                draining=bool(n.get("draining")),
                drain_deadline=float(n.get("drain_deadline") or 0.0))
                for n in view[nodes_key]]
            if nodes_key == "full":
                msg.full = encoded
            else:
                msg.deltas = encoded
            reply["view"] = msg.encode()
        return reply

    async def handle_get_nodes(self, conn, only_alive=True):
        return [n.view() for n in self._nodes.values() if n.alive or not only_alive]

    async def handle_cluster_demand(self, conn):
        """Heartbeat-aggregated per-node lease backlog (autoscaler demand
        feed — GcsAutoscalerStateManager analog): one RPC instead of a
        node_stats fan-out to every raylet."""
        return [{"node_id": n.node_id, "backlog": n.backlog}
                for n in self._nodes.values() if n.alive and n.backlog]

    async def handle_request_resources(self, conn, bundles):
        """Explicit demand floor (autoscaler/sdk request_resources analog):
        the autoscaler scales to hold these bundles EVEN WITHOUT queued
        work. Each call REPLACES the previous request (the reference
        semantics); an empty list clears it. Persisted: the floor must
        survive a GCS restart or the pre-scaled nodes idle out right
        before the burst the operator scaled for."""
        import pickle

        self._requested_resources = [dict(b) for b in (bundles or [])]
        try:
            self._store.put("autoscaler", b"requested_resources",
                            pickle.dumps(self._requested_resources))
        except Exception:
            logger.exception("persisting requested_resources failed")
        return {"ok": True, "count": len(self._requested_resources)}

    async def handle_get_requested_resources(self, conn):
        return list(getattr(self, "_requested_resources", []))

    async def handle_drain_node(self, conn, node_id, reason: str = "drained",
                                deadline_s: Optional[float] = None):
        """Two-phase node retirement (DrainNode analog, node_manager.proto).

        With a positive `deadline_s` (advance notice — the spot-preemption
        shape) the node enters DRAINING: it stays alive, the scheduler and
        placement groups stop leasing onto it, its raylet migrates primary
        object copies to live peers, and drain-aware consumers (Train,
        RLHF) checkpoint and re-form proactively. At the deadline the
        health loop kills it for real with the preempted marker so
        whatever didn't make it falls back to the reactive paths without
        consuming retry budgets.

        `deadline_s` None/<=0 keeps the legacy immediate-kill semantics —
        this IS the 0-notice reactive path."""
        if deadline_s is None or deadline_s <= 0:
            rec = self._nodes.get(node_id)
            if rec is not None and rec.alive:
                # Even a 0-notice drain is an ANNOUNCED retirement: flag it
                # so _mark_node_dead stamps the preemption marker (typed
                # cause, retry-budget exemption) and records NODE_PREEMPTED.
                rec.draining = True
                if not rec.drain_reason:
                    rec.drain_reason = reason
            await self._mark_node_dead(node_id, reason)
            return {"ok": True, "draining": False}
        rec = self._nodes.get(node_id)
        if rec is None or not rec.alive:
            return {"ok": False, "unknown": True}
        if not rec.draining:
            rec.draining = True
            rec.drain_reason = reason
        # Repeated notices tighten (never extend) the window: the cloud's
        # second notice is always sooner than the first.
        new_mono = time.monotonic() + deadline_s
        if rec.drain_deadline_mono <= 0 or new_mono < rec.drain_deadline_mono:
            rec.drain_deadline_mono = new_mono
            rec.drain_deadline = time.time() + deadline_s
        self._persist_node(rec)
        self._bump_view(rec)
        logger.warning("node %s DRAINING (%s): deadline in %.1fs",
                       node_id.hex()[:12], reason, deadline_s)
        from ray_tpu.runtime import events as events_mod

        self._record_event(events_mod.make_event(
            events_mod.NODE_DRAINING,
            f"node {node_id.hex()[:12]} draining ({reason}): "
            f"deadline in {deadline_s:.1f}s",
            severity=events_mod.WARNING, source="gcs", node_id=node_id,
            slice_name=rec.labels.get("tpu-slice-name"),
            labels={"deadline_s": f"{deadline_s:.1f}", "reason": reason}))
        await self.publish("node", {"event": "draining", "node": rec.view(),
                                    "reason": reason,
                                    "deadline_s": deadline_s})
        # Tell the raylet so it stops granting leases and starts migrating
        # its primary object copies (best-effort: the view delta is the
        # backup signal).
        if rec.client is not None:
            self._spawn_bg(self._notify_drain(rec, reason, deadline_s))
        return {"ok": True, "draining": True,
                "deadline": rec.drain_deadline}

    async def _notify_drain(self, rec: "NodeRecord", reason: str,
                            deadline_s: float):
        try:
            await rec.client.call("drain_self", reason=reason,
                                  deadline_s=deadline_s, timeout=5)
        except Exception as e:
            logger.debug("drain_self notify to %s failed: %r",
                         rec.node_id.hex()[:12], e)

    # ---- object relocation (drain-time primary-copy migration) -----------
    #
    # While a node drains, its raylet pushes primary object copies to live
    # peers and reports the new homes here. Workers that later hit
    # ObjectLostError for an oid ask `locate_object` BEFORE falling back to
    # lineage reconstruction, so objects that had time to move survive the
    # preemption without re-execution.

    async def handle_report_object_locations(self, conn, node_id,
                                             oids) -> dict:
        table = getattr(self, "_object_relocations", None)
        if table is None:
            table = self._object_relocations = {}
        for oid in oids:
            table[bytes(oid)] = node_id
        return {"ok": True, "count": len(oids)}

    async def handle_locate_object(self, conn, oid: bytes) -> dict:
        table = getattr(self, "_object_relocations", None)
        holder = table.get(oid) if table else None
        if holder is None:
            return {"found": False}
        rec = self._nodes.get(holder)
        if rec is None or not rec.alive:
            return {"found": False}
        return {"found": True, "node_id": holder,
                "address": list(rec.address)}

    # ---- checkpoint shard registry (checkpoint/plane.py replication) -----
    #
    # A completed checkpoint shard that was broadcast to peer object stores
    # registers here: the shard row records where the durable file lives,
    # and each replica oid lands in the drain relocation table homed on a
    # live PEER of the reporting node (the broadcast placed a copy on every
    # node) — so when the writer's node drains and dies at its deadline,
    # `locate_object` already points somewhere that survives it.

    async def handle_register_checkpoint_shards(self, conn, path: str,
                                                name: str, shard: int,
                                                world: int, step=None,
                                                nbytes: int = 0,
                                                oids=(), node_id=None
                                                ) -> dict:
        shards = getattr(self, "_ckpt_shards", None)
        if shards is None:
            shards = self._ckpt_shards = {}
        shards[(path, name, int(shard), int(world))] = {
            "path": path, "name": name, "shard": int(shard),
            "world": int(world), "step": step, "nbytes": int(nbytes),
            "oids": [bytes(o) for o in oids],
            "node_id": node_id, "time": time.time()}
        table = getattr(self, "_object_relocations", None)
        if table is None:
            table = self._object_relocations = {}
        peer = None
        for nid, rec in self._nodes.items():
            if rec.alive and not rec.draining and nid != node_id:
                peer = nid
                break
        home = peer if peer is not None else node_id
        relocated = 0
        if home is not None:
            for oid in oids:
                table[bytes(oid)] = home
                relocated += 1
        return {"ok": True, "relocated": relocated,
                "home": home.hex() if isinstance(home, bytes) else home}

    async def handle_list_checkpoint_shards(self, conn,
                                            path: Optional[str] = None
                                            ) -> list:
        shards = getattr(self, "_ckpt_shards", None) or {}
        rows = [dict(v, oids=[o.hex() for o in v["oids"]],
                     node_id=(v["node_id"].hex()
                              if isinstance(v["node_id"], bytes)
                              else v["node_id"]))
                for v in shards.values()
                if path is None or v["path"] == path]
        rows.sort(key=lambda r: (r["path"], r["name"], r["shard"]))
        return rows

    # ---- cluster prefix store (llm/prefix_store.py) -----------------------
    #
    # digest -> spilled KV prefix pages + adoption metadata, modeled on the
    # checkpoint shard registry above, with one deliberate difference: the
    # page bytes are homed HERE (the GCS byte plane), not in a worker's
    # object store — worker-owned objects ride the owner-addressed
    # ownership protocol and are reaped when their owner dies, which is
    # the exact event a spilled prefix must survive. Traffic is raw-frame
    # RPC both directions (rpc.py call_raw): the handlers below never
    # pickle a page byte. Byte-capacity LRU so replicas can't flood the
    # head node's RAM.

    PREFIX_STORE_CAPACITY = 256 << 20

    def _prefix_table(self):
        tbl = getattr(self, "_prefix_entries", None)
        if tbl is None:
            from collections import OrderedDict

            tbl = self._prefix_entries = OrderedDict()
            self._prefix_bytes = 0
        return tbl

    @staticmethod
    def _prefix_row_msg(key: bytes, row: dict):
        from ray_tpu.runtime import wire

        return wire.PrefixEntryMsg(
            digest=key, lora_id=row["lora_id"],
            weights_version=row["weights_version"],
            block_size=row["block_size"], n_tokens=row["n_tokens"],
            token_ids=row["token_ids"], nbytes=len(row["payload"]),
            owner_replica=row["owner_replica"], node_id=row["node_id"],
            deployment=row["deployment"])

    async def handle_prefix_upsert(self, conn, m, payload):
        from ray_tpu.runtime.rpc import RawReply
        from ray_tpu.runtime import wire

        ent = wire.PrefixEntryMsg.decode(bytes(m))
        buf = bytes(payload)
        if not ent.digest or not buf or not ent.token_ids:
            return RawReply(wire.AckMsg(
                ok=False, error="empty prefix upsert").encode())
        tbl = self._prefix_table()
        key = bytes(ent.digest)
        old = tbl.pop(key, None)
        if old is not None:
            self._prefix_bytes -= len(old["payload"])
        tbl[key] = {
            "lora_id": ent.lora_id, "weights_version": ent.weights_version,
            "block_size": ent.block_size, "n_tokens": ent.n_tokens,
            "token_ids": list(ent.token_ids),
            "owner_replica": ent.owner_replica,
            "node_id": bytes(ent.node_id), "deployment": ent.deployment,
            "payload": buf, "time": time.time()}
        self._prefix_bytes += len(buf)
        while tbl and self._prefix_bytes > self.PREFIX_STORE_CAPACITY:
            _, victim = tbl.popitem(last=False)
            self._prefix_bytes -= len(victim["payload"])
        return RawReply(wire.AckMsg(ok=True,
                                    existed=old is not None).encode())

    async def handle_prefix_lookup(self, conn, m, payload):
        """Answer with the CONTIGUOUS run of entries held from digests[0]
        upward (the caller lists its missing chain longest-last); the
        reply payload is the matching spill buffers concatenated — frames
        are self-delimiting, so the adopter decodes them back apart."""
        from ray_tpu.runtime.rpc import RawReply
        from ray_tpu.runtime import wire

        q = wire.PrefixLookupMsg.decode(bytes(m))
        tbl = self._prefix_table()
        entries, bufs = [], []
        for d in (q.digests or ()):
            key = bytes(d)
            row = tbl.get(key)
            # weights_version <= 0 in the query means "any": the router's
            # metadata-only owner probe doesn't know the fleet's weights
            # version. Adopters always pass their exact version AND
            # re-verify it per entry client-side, so a relaxed probe can
            # never smuggle stale KV into an engine.
            if (row is None or row["lora_id"] != q.lora_id
                    or (q.weights_version > 0
                        and row["weights_version"] != q.weights_version)
                    or row["block_size"] != q.block_size):
                break
            tbl.move_to_end(key)
            if q.replica:
                # The adopter is about to hold these pages hot: it becomes
                # the live-owner hint the router's fallback routes to.
                row["owner_replica"] = q.replica
            entries.append(self._prefix_row_msg(key, row))
            if q.want_payload:
                bufs.append(row["payload"])
        reply = wire.PrefixLookupReplyMsg(found=bool(entries),
                                          entries=entries)
        return RawReply(reply.encode(), payload=b"".join(bufs))

    async def handle_prefix_purge(self, conn, m, payload):
        from ray_tpu.runtime.rpc import RawReply
        from ray_tpu.runtime import wire

        q = wire.PrefixPurgeMsg.decode(bytes(m))
        purged, cleared = self._purge_prefix_entries(
            owner_replica=q.owner_replica, node_id=bytes(q.node_id),
            deployment=q.deployment,
            digests=[bytes(d) for d in (q.digests or ())],
            below_weights_version=q.below_weights_version,
            clear_owner_only=q.clear_owner_only)
        return RawReply(wire.PrefixPurgeReplyMsg(
            ok=True, purged=purged, owners_cleared=cleared).encode())

    def _purge_prefix_entries(self, *, owner_replica: str = "",
                              node_id: bytes = b"", deployment: str = "",
                              digests=(), below_weights_version: int = 0,
                              clear_owner_only: bool = False):
        """Prune the prefix table (OR across the given selectors; no
        selector matches nothing). clear_owner_only blanks the live-owner
        hint but keeps the row adoptable — the replica-death path, where
        the pages (GCS-homed) are still valid but a routing hint naming a
        dead or re-registered replica would serve a stale owner hit."""
        tbl = getattr(self, "_prefix_entries", None)
        if not tbl:
            return 0, 0
        digest_set = set(digests)

        def match(key, row):
            if key in digest_set:
                return True
            if owner_replica and row["owner_replica"] == owner_replica:
                return True
            if node_id and row["node_id"] == node_id:
                return True
            if deployment and row["deployment"] == deployment:
                return True
            return bool(below_weights_version
                        and row["weights_version"] < below_weights_version)

        purged = cleared = 0
        for key in [k for k, r in tbl.items() if match(k, r)]:
            if clear_owner_only:
                tbl[key]["owner_replica"] = ""
                tbl[key]["node_id"] = b""
                cleared += 1
            else:
                row = tbl.pop(key)
                self._prefix_bytes -= len(row["payload"])
                purged += 1
        return purged, cleared

    async def _on_disconnect(self, conn: ServerConnection):
        for subs in self._subscribers.values():
            subs.discard(conn)
        node_id = conn.meta.get("node_id")
        if node_id is not None and node_id in self._nodes and self._nodes[node_id].alive:
            # A draining node's disconnect IS the announced preemption —
            # don't overwrite the cause with a generic "disconnected" (the
            # typed-cause plumbing downstream keys off the reason string).
            rec = self._nodes[node_id]
            if rec.draining:
                reason = (f"node preempted at end of drain "
                          f"({rec.drain_reason})")
            else:
                reason = "raylet disconnected"
            await self._mark_node_dead(node_id, reason)
        job_id = conn.meta.get("job_id")
        if job_id is not None and job_id in self._jobs:
            self._jobs[job_id]["alive"] = False
            self._persist_job(self._jobs[job_id])
        if conn.meta.get("owns_cluster") and not self._shutdown.is_set():
            self._spawn_bg(self._shutdown_if_owner_gone(job_id))

    async def _shutdown_if_owner_gone(self, job_id, grace_s: float = 10.0):
        """Tear the cluster down unless the owning driver reconnects and
        re-claims its job within the grace period (a transient socket drop
        of an auto_reconnect client must not kill the cluster — the driver
        heartbeats its job every couple of seconds, so a live driver always
        re-claims well inside the grace)."""
        await asyncio.sleep(grace_s)
        job = self._jobs.get(job_id)
        if job is not None and job.get("alive"):
            return
        if self._shutdown.is_set():
            return
        logger.warning("cluster-owning driver (job %s) disconnected; "
                       "shutting the cluster down", job_id)
        await self._do_shutdown()

    async def handle_claim_job(self, conn, job_id, owns_cluster: bool = False):
        """Re-attach a driver connection to its job (register_job docstring).
        Doubles as the driver's job heartbeat: called periodically so even
        an otherwise-idle driver re-claims after a transparent reconnect."""
        conn.meta["job_id"] = job_id
        if owns_cluster:
            conn.meta["owns_cluster"] = True
        job = self._jobs.get(job_id)
        if job is not None and not job.get("alive"):
            job["alive"] = True
            self._persist_job(job)
        return {"ok": True}

    async def _mark_node_dead(self, node_id: bytes, reason: str,
                              _slice_cascade: bool = True):
        rec = self._nodes.get(node_id)
        if rec is None or not rec.alive:
            return
        from ray_tpu.core.exceptions import NODE_PREEMPTED_MARKER

        # A drained node's death is a PLANNED retirement: stamp the typed
        # preemption marker into the reason (it survives the string-shaped
        # death plumbing to actors/tasks/objects, where `death_cause`
        # recovers it) and record the paired NODE_PREEMPTED event.
        was_draining = rec.draining
        if was_draining and NODE_PREEMPTED_MARKER not in reason:
            reason = f"{NODE_PREEMPTED_MARKER}: {reason}"
        rec.alive = False
        rec.draining = False
        rec.death_reason = reason
        self._persist_node(rec)
        self._bump_view(rec)
        logger.warning("node %s marked dead: %s", node_id.hex()[:12], reason)
        from ray_tpu.runtime import events as events_mod

        self._record_event(events_mod.make_event(
            events_mod.NODE_DEAD, f"node {node_id.hex()[:12]} dead: {reason}",
            severity=events_mod.ERROR, source="gcs", node_id=node_id,
            slice_name=rec.labels.get("tpu-slice-name")))
        if was_draining:
            self._record_event(events_mod.make_event(
                events_mod.NODE_PREEMPTED,
                f"node {node_id.hex()[:12]} preempted at drain deadline "
                f"({rec.drain_reason})",
                severity=events_mod.WARNING, source="gcs", node_id=node_id,
                slice_name=rec.labels.get("tpu-slice-name"),
                labels={"reason": rec.drain_reason}))
        # Relocation entries pointing AT the dead node are stale; entries
        # migrated OFF it (to live peers) stay valid. Checkpoint-shard
        # replicas are special: the broadcast placed a copy on EVERY node,
        # so their entries re-home to a surviving peer instead of dropping.
        table = getattr(self, "_object_relocations", None)
        if table:
            ckpt_oids = {bytes(o) for row in
                         (getattr(self, "_ckpt_shards", None) or {}).values()
                         for o in row["oids"]}
            new_home = next((nid for nid, r in self._nodes.items()
                             if r.alive and not r.draining
                             and nid != node_id), None)
            for oid in [o for o, holder in table.items()
                        if holder == node_id]:
                if oid in ckpt_oids and new_home is not None:
                    table[oid] = new_home
                else:
                    table.pop(oid, None)
        # A dead node never flushes metrics again — drop its
        # `metrics:<node>:<pid>` KV snapshots so the dashboard /metrics
        # aggregation stops counting ghost processes forever.
        stale_prefix = f"metrics:{node_id.hex()}:".encode()
        for key in [k for k in self._kv if k.startswith(stale_prefix)]:
            self._kv.pop(key, None)
            try:
                self._store.delete("kv", key)
            except Exception:
                pass
        # ... and its time-series rings: a dead node's history would only
        # pin ring budget that live reporters need.
        self._mh_purge_reporter(f"{node_id.hex()}:")
        # Same hygiene for the cluster prefix table, in the SAME tick: a
        # dead node's replicas never touch their spilled prefixes again,
        # so their live-owner hints must not survive to misroute a router
        # fallback (a later re-registered node could otherwise serve a
        # stale owner hit). The pages themselves are GCS-homed and stay
        # adoptable by any survivor — that is the point of the store.
        self._purge_prefix_entries(node_id=node_id, clear_owner_only=True)
        await self.publish("node", {"event": "removed", "node": rec.view(), "reason": reason})
        # Slice fate-sharing: a multi-host ICI slice is ONE failure domain.
        # Losing any host breaks the slice's collectives, so every sibling
        # is marked dead in the SAME tick (not after its own heartbeat
        # timeout) and actors on the slice die with the slice-lost marker.
        from ray_tpu.core.exceptions import TPU_SLICE_LOST_MARKER

        slice_name = rec.labels.get("tpu-slice-name")
        if slice_name and TPU_SLICE_LOST_MARKER not in reason:
            reason = (f"{TPU_SLICE_LOST_MARKER}: slice {slice_name!r} "
                      f"lost ({reason})")
        if _slice_cascade and slice_name:
            await self._fate_share_slice(slice_name, node_id, reason)
        # Fail/restart actors that lived on that node.
        for actor in list(self._actors.values()):
            if actor.node_id == node_id and actor.state in (ALIVE, PENDING_CREATION):
                asyncio.ensure_future(
                    self._handle_actor_failure(actor.spec.actor_id, f"node died: {reason}"))
        if self._pg_manager is not None:
            await self._pg_manager.on_node_dead(node_id)

    async def _fate_share_slice(self, slice_name: str, origin: bytes,
                                reason: str):
        """Mark every sibling host of a lost slice dead NOW, notify their
        raylets (they kill local workers and shut down — nothing may keep
        running against a broken ICI domain), and publish a typed
        `slice_lost` event. Also recorded in the KV so pollers (tests,
        dashboards) can observe slice loss without a subscription."""
        from ray_tpu.runtime import wire

        siblings = [n for n in self._nodes.values()
                    if n.alive and n.node_id != origin
                    and n.labels.get("tpu-slice-name") == slice_name]
        members = [origin] + [n.node_id for n in siblings]
        msg = wire.SliceLostMsg(slice_name=slice_name, nodes=members,
                                origin_node=origin, reason=reason)
        encoded = msg.encode()
        for sib in siblings:
            if sib.client is not None:
                self._spawn_bg(self._notify_slice_lost(sib, encoded))
            await self._mark_node_dead(sib.node_id, reason,
                                       _slice_cascade=False)
        logger.warning("slice %r lost (%d host(s) fate-shared): %s",
                       slice_name, len(siblings), reason)
        from ray_tpu.runtime import events as events_mod

        self._record_event(events_mod.make_event(
            events_mod.SLICE_LOST,
            f"slice {slice_name!r} lost ({len(members)} host(s) "
            f"fate-shared): {reason}",
            severity=events_mod.ERROR, source="gcs", node_id=origin,
            slice_name=slice_name,
            labels={"hosts": str(len(members)),
                    "members": ",".join(m.hex()[:12] for m in members)}))
        key = f"slice_lost:{slice_name}".encode()
        self._kv[key] = reason.encode()
        try:
            self._store.put("kv", key, self._kv[key])
        except Exception:
            logger.exception("slice_lost kv persist failed")
        await self.publish("slice_lost", {
            "slice_name": slice_name, "reason": reason, "m": encoded})

    async def _notify_slice_lost(self, rec: "NodeRecord", encoded: bytes):
        try:
            await rec.client.call("slice_lost", m=encoded, timeout=5)
        except Exception as e:
            # Best effort: the sibling may already be unreachable (it is
            # marked dead regardless).
            logger.debug("slice_lost notify to %s failed: %r",
                         rec.node_id.hex()[:12], e)

    async def _health_check_loop(self):
        # gcs_health_check_manager analog: periodic liveness by heartbeat age.
        from ray_tpu.config import cfg

        while not self._shutdown.is_set():
            await asyncio.sleep(1.0)
            now = time.monotonic()
            for rec in list(self._nodes.values()):
                if rec.alive and now - rec.last_heartbeat > 30.0:
                    await self._mark_node_dead(rec.node_id, "heartbeat timeout")
                elif (rec.alive and rec.draining
                        and rec.drain_deadline_mono > 0
                        and now >= rec.drain_deadline_mono):
                    # Drain window expired: the retirement happens NOW even
                    # if the cloud hasn't actually revoked the VM yet —
                    # deadline semantics must be deterministic for callers.
                    await self._mark_node_dead(
                        rec.node_id,
                        f"node preempted at end of drain "
                        f"({rec.drain_reason})")
            # Wait-graph detector rides the same loop at its own cadence.
            last = getattr(self, "_last_stall_tick", 0.0)
            if now - last >= cfg().stall_detector_interval_s:
                self._last_stall_tick = now
                try:
                    self._stall_detector_tick()
                except Exception:
                    logger.exception("stall detector tick failed")
            # So does the alert evaluator (rules over the history rings).
            last = getattr(self, "_last_alert_tick", 0.0)
            if now - last >= cfg().alert_eval_interval_s:
                self._last_alert_tick = now
                try:
                    self._alert_eval_tick()
                except Exception:
                    logger.exception("alert evaluator tick failed")

    # ---- KV (function/class table, runtime metadata) ---------------------

    async def handle_kv_put(self, conn, key: bytes, value: bytes, overwrite=True):
        if not overwrite and key in self._kv:
            return {"ok": False, "exists": True}
        self._kv[key] = value
        try:
            self._store.put("kv", key, value)
        except Exception:
            logger.exception("kv persist failed")
        return {"ok": True}

    async def handle_report_metrics2(self, conn, m: bytes):
        """Typed metrics flush (MetricsReportMsg): one schema'd frame per
        reporter per tick, filed under the same metrics:<node>:<pid> KV key
        the legacy kv_put path used, so every reader (dashboard /metrics,
        state.metrics_snapshot) is oblivious to the transport change.
        Skips the persistence write — metrics snapshots are ephemeral."""
        from ray_tpu.runtime import wire

        msg = wire.MetricsReportMsg.decode(m)
        self._kv[f"metrics:{msg.node}:{msg.pid}".encode()] = msg.payload
        try:
            self._ingest_metrics_history(msg.node, msg.pid, msg.payload)
        except Exception:
            # History is an overlay on the snapshot plane; a malformed
            # payload must not fail the flush the snapshot path accepted.
            logger.exception("metrics history ingest failed")
        return {"ok": True}

    async def handle_kv_get(self, conn, key: bytes):
        return {"value": self._kv.get(key)}

    async def handle_kv_del(self, conn, key: bytes):
        self._store.delete("kv", key)
        return {"ok": self._kv.pop(key, None) is not None}

    async def handle_kv_keys(self, conn, prefix: bytes = b""):
        return {"keys": [k for k in self._kv if k.startswith(prefix)]}

    # ---- metrics history plane -------------------------------------------
    #
    # Every MetricsReportMsg flush is additionally folded into crc32-sharded
    # fixed-budget time-series rings (the task-event `gcs_ring_shards`
    # pattern): counters/gauges store (ts, cumulative value) points per
    # (series, tag set, reporter), histograms store per-flush bucket DELTAS
    # so any window's distribution — and therefore any quantile — can be
    # reconstructed by summing deltas. The whole structure is byte-capped
    # (`metrics_history_max_bytes`), evicting oldest points first. Zero new
    # wire frames: the payload is the same JSON the snapshot plane already
    # ships; history only changes what the GCS *keeps*.

    _MH_POINT_COST = 32          # rough bytes per scalar (ts, value) point

    def _metrics_history_shards(self) -> list:
        shards = getattr(self, "_mh_shards", None)
        if shards is None:
            from ray_tpu.config import cfg

            n = max(1, cfg().gcs_ring_shards)
            per = max(4096, cfg().metrics_history_max_bytes // n)
            shards = self._mh_shards = [
                {"series": {}, "bytes": 0, "budget": per} for _ in range(n)]
            self._mh_prev_hist = {}   # reporter -> {series key: cumulative}
            self._mh_flushes = 0
            self._mh_evicted_points = 0
        return shards

    def _mh_shard_for(self, skey: str) -> dict:
        shards = self._metrics_history_shards()
        return shards[zlib.crc32(skey.encode()) % len(shards)]

    def _ingest_metrics_history(self, node: str, pid: int, payload: bytes,
                                now: float = None):
        from ray_tpu.config import cfg

        if not cfg().metrics_history_enabled:
            return
        snaps = json.loads(payload)
        if now is None:
            now = time.time()
        reporter = f"{node}:{pid}"
        self._metrics_history_shards()
        self._mh_flushes += 1
        prev_hist = self._mh_prev_hist.setdefault(reporter, {})
        touched = set()
        for snap in snaps:
            name, typ = snap.get("name"), snap.get("type")
            if not name:
                continue
            if typ == "histogram":
                boundaries = snap.get("boundaries") or []
                for tkey, h in (snap.get("histograms") or {}).items():
                    skey = f"{name}|{tkey}|{reporter}"
                    cur = (list(h.get("buckets") or []),
                           float(h.get("sum", 0.0)), int(h.get("count", 0)))
                    last = prev_hist.get(skey)
                    prev_hist[skey] = cur
                    if last is not None and cur[2] >= last[2] \
                            and len(cur[0]) == len(last[0]):
                        dcount = cur[2] - last[2]
                        if dcount == 0:
                            continue      # idle flush: store nothing
                        delta = ([max(0, c - p)
                                  for c, p in zip(cur[0], last[0])],
                                 max(0.0, cur[1] - last[1]), dcount)
                    else:
                        # First sight, or the reporter restarted (pid
                        # reuse): the whole cumulative state is the delta.
                        delta = cur
                        if delta[2] == 0:
                            continue
                    rec = self._mh_series(skey, name, tkey, reporter,
                                          "histogram", boundaries)
                    rec["points"].append(
                        (now, tuple(delta[0]), delta[1], delta[2]))
                    shard = self._mh_shard_for(skey)
                    shard["bytes"] += rec["psize"]
                    touched.add(id(shard))
            elif typ in ("counter", "gauge"):
                for tkey, v in (snap.get("values") or {}).items():
                    skey = f"{name}|{tkey}|{reporter}"
                    rec = self._mh_series(skey, name, tkey, reporter, typ)
                    pts = rec["points"]
                    # An idle counter repeats its cumulative value every
                    # flush; storing the repeats buys nothing (rate/delta
                    # fold consecutive differences). Gauges keep every
                    # sample — a flat gauge is data, "no samples" is not.
                    if typ == "counter" and pts and pts[-1][1] == v:
                        continue
                    pts.append((now, float(v)))
                    shard = self._mh_shard_for(skey)
                    shard["bytes"] += rec["psize"]
                    touched.add(id(shard))
        for shard in self._mh_shards:
            if id(shard) in touched and shard["bytes"] > shard["budget"]:
                self._mh_evict(shard)

    def _mh_series(self, skey: str, name: str, tkey: str, reporter: str,
                   kind: str, boundaries=None) -> dict:
        from collections import deque

        shard = self._mh_shard_for(skey)
        rec = shard["series"].get(skey)
        if rec is None:
            psize = (self._MH_POINT_COST if boundaries is None
                     else 48 + 8 * (len(boundaries) + 1))
            try:
                tagmap = dict(json.loads(tkey))
            except Exception:
                tagmap = {}
            rec = shard["series"][skey] = {
                "name": name, "tags": tagmap, "reporter": reporter,
                "kind": kind, "boundaries": list(boundaries or ()),
                "points": deque(), "psize": psize}
        return rec

    def _mh_evict(self, shard: dict):
        """Oldest-window eviction: while the shard is over budget, drop
        points from the head of whichever series currently holds the
        oldest one (batched so a large overshoot is not O(n) min-scans)."""
        series = shard["series"]
        while shard["bytes"] > shard["budget"] and series:
            rec = min(series.values(), key=lambda r: r["points"][0][0])
            pts = rec["points"]
            drop = max(8, len(pts) // 16)
            while drop and pts and shard["bytes"] > shard["budget"]:
                pts.popleft()
                shard["bytes"] -= rec["psize"]
                self._mh_evicted_points += 1
                drop -= 1
            if not pts:
                for k, r in list(series.items()):
                    if r is rec:
                        del series[k]
                        break

    def _mh_purge_reporter(self, who: str):
        """Drop every history series for one reporter — an exact
        `node:pid` (worker death) or a `node:` prefix (node death; the
        trailing colon keeps pid 123 from shadowing pid 1234)."""
        def match(reporter: str) -> bool:
            return (reporter == who
                    or (who.endswith(":") and reporter.startswith(who)))

        for shard in getattr(self, "_mh_shards", None) or ():
            stale = [k for k, r in shard["series"].items()
                     if match(r["reporter"])]
            for k in stale:
                rec = shard["series"].pop(k)
                shard["bytes"] -= rec["psize"] * len(rec["points"])
        prev = getattr(self, "_mh_prev_hist", None) or {}
        for reporter in [r for r in prev if match(r)]:
            del prev[reporter]

    def _mh_match(self, name: str, tags=None) -> list:
        """Every series record for `name` whose tag set contains `tags`."""
        out = []
        for shard in self._metrics_history_shards():
            for rec in shard["series"].values():
                if rec["name"] != name:
                    continue
                if tags and any(rec["tags"].get(k) != v
                                for k, v in tags.items()):
                    continue
                out.append(rec)
        return out

    @staticmethod
    def _mh_counter_delta(points, cutoff: float) -> float:
        """Sum of positive increments landing inside the window. The last
        pre-window point is the baseline, so an increment that *crossed*
        the window edge counts; resets (process restart) clamp to 0
        instead of going negative."""
        total, prev = 0.0, None
        for ts, v in points:
            if prev is not None and ts >= cutoff:
                total += max(0.0, v - prev)
            prev = v
        return total

    def _mh_window(self, name: str, tags=None, window_s: float = 60.0,
                   agg: str = None, now: float = None):
        """One windowed aggregate over every matching series, plus the
        per-node contribution split (alert attribution, link matrix).

        agg: counters `rate` (default) / `delta`; gauges `mean` (default)
        / `last`; histograms `pNN` (p99 default) / `mean` / `rate`
        (observations per second). Returns (value_or_None, by_node dict,
        extras dict)."""
        if now is None:
            now = time.time()
        cutoff = now - max(window_s, 1e-9)
        recs = self._mh_match(name, tags)
        if not recs:
            return None, {}, {"series": 0}
        kind = recs[0]["kind"]
        by_node: Dict[str, float] = {}

        def book(rec, amount):
            node = rec["reporter"].split(":", 1)[0]
            by_node[node] = by_node.get(node, 0.0) + amount

        if kind == "histogram":
            boundaries, buckets = [], []
            total_sum = total_count = 0.0
            for rec in recs:
                if not boundaries and rec["boundaries"]:
                    boundaries = rec["boundaries"]
                    buckets = [0.0] * (len(boundaries) + 1)
                contrib = 0.0
                for ts, db, dsum, dcount in rec["points"]:
                    if ts < cutoff:
                        continue
                    if len(db) == len(buckets):
                        for i, c in enumerate(db):
                            buckets[i] += c
                    total_sum += dsum
                    total_count += dcount
                    contrib += dcount
                book(rec, contrib)
            extras = {"series": len(recs), "count": total_count,
                      "sum": total_sum, "boundaries": boundaries,
                      "buckets": buckets}
            if total_count <= 0:
                return None, by_node, extras
            agg = agg or "p99"
            if agg == "mean":
                return total_sum / total_count, by_node, extras
            if agg in ("rate", "delta"):
                val = (total_count if agg == "delta"
                       else total_count / window_s)
                return val, by_node, extras
            if agg.startswith("p"):
                from ray_tpu.util.metrics import histogram_quantile

                q = float(agg[1:]) / 100.0
                return (histogram_quantile(boundaries, buckets, q),
                        by_node, extras)
            raise ValueError(f"unknown histogram agg {agg!r}")
        if kind == "counter":
            agg = agg or "rate"
            if agg not in ("rate", "delta"):
                raise ValueError(f"unknown counter agg {agg!r}")
            total = 0.0
            for rec in recs:
                d = self._mh_counter_delta(rec["points"], cutoff)
                book(rec, d)
                total += d
            value = total if agg == "delta" else total / window_s
            return value, by_node, {"series": len(recs)}
        # gauge
        agg = agg or "mean"
        if agg not in ("mean", "last"):
            raise ValueError(f"unknown gauge agg {agg!r}")
        vals = []
        for rec in recs:
            pts = [v for ts, v in rec["points"] if ts >= cutoff]
            if not pts and rec["points"]:
                # A quiet gauge still has a current value: fall back to
                # its most recent sample so `mean` reflects level, not
                # flush cadence.
                pts = [rec["points"][-1][1]]
            if pts:
                per = pts[-1] if agg == "last" else sum(pts) / len(pts)
                vals.append(per)
                book(rec, per)
        if not vals:
            return None, by_node, {"series": len(recs)}
        return sum(vals) / len(vals), by_node, {"series": len(recs)}

    async def handle_metrics_history(self, conn, name: str, tags=None,
                                     window_s: float = 60.0, agg=None,
                                     points_limit: int = 240):
        """Windowed query over the history rings (`state.metrics_history`
        / `scripts metrics` / dashboard sparklines). Returns the aggregate
        plus the raw per-series point tails for plotting."""
        value, by_node, extras = self._mh_window(
            name, tags=tags, window_s=window_s, agg=agg)
        series = []
        for rec in self._mh_match(name, tags):
            pts = list(rec["points"])[-max(1, points_limit):]
            if rec["kind"] == "histogram":
                # Per-flush mean: the plottable scalar a bucket-delta
                # point reduces to.
                plotted = [[ts, (dsum / dcount) if dcount else 0.0]
                           for ts, _db, dsum, dcount in pts]
            else:
                plotted = [[ts, v] for ts, v in pts]
            series.append({"name": rec["name"], "tags": rec["tags"],
                           "reporter": rec["reporter"], "kind": rec["kind"],
                           "points": plotted})
        return {"name": name, "window_s": window_s, "agg": agg,
                "value": value, "by_node": by_node, "series": series,
                **{k: v for k, v in extras.items()
                   if k in ("count", "sum")}}

    async def handle_metrics_history_stats(self, conn):
        """Ingest-side health of the history plane (budget pressure,
        eviction churn) — `handle_task_event_stats` symmetry."""
        shards = getattr(self, "_mh_shards", None) or []
        return {
            "shards": len(shards),
            "series": sum(len(s["series"]) for s in shards),
            "points": sum(len(r["points"]) for s in shards
                          for r in s["series"].values()),
            "bytes": sum(s["bytes"] for s in shards),
            "budget_bytes": sum(s["budget"] for s in shards),
            "evicted_points": getattr(self, "_mh_evicted_points", 0),
            "flushes_ingested": getattr(self, "_mh_flushes", 0),
        }

    async def handle_link_utilization(self, conn, window_s: float = 30.0):
        """Observed per-link bandwidth matrix, derived from the (op, algo)-
        tagged collective byte counters in the history rings and attributed
        to topology links: a slice-labeled node's traffic rides the ICI
        ring link toward its worker-id successor (rx from its predecessor),
        an unlabeled node's traffic is host/DCN egress. This is the feed
        for the ROADMAP-3 contention model — schedulers act on measured
        goodput per link, not instantaneous readings."""
        now = time.time()
        cutoff = now - max(window_s, 1e-9)
        # node hex -> (slice, worker index) from the live node table.
        slices: Dict[str, list] = {}
        place: Dict[str, tuple] = {}
        for nid, rec in self._nodes.items():
            if not rec.alive:
                continue
            sl = rec.labels.get("tpu-slice-name")
            if sl is None:
                continue
            try:
                w = int(rec.labels.get("tpu-worker-id", -1))
            except (TypeError, ValueError):
                w = -1
            if w >= 0:
                place[nid.hex()] = (sl, w)
                slices.setdefault(sl, []).append(w)
        for sl in slices:
            slices[sl] = sorted(set(slices[sl]))
        links: Dict[str, dict] = {}
        nodes: Dict[str, dict] = {}

        def link_rec(key, kind, slice_name=None):
            return links.setdefault(key, {
                "link": key, "kind": kind, "slice": slice_name,
                "tx_bytes_per_s": 0.0, "rx_bytes_per_s": 0.0, "by_op": {}})

        for direction, metric in (
                ("tx", "ray_tpu_collective_bytes_sent_total"),
                ("rx", "ray_tpu_collective_bytes_recv_total")):
            for rec in self._mh_match(metric):
                rate = self._mh_counter_delta(
                    rec["points"], cutoff) / window_s
                if rate <= 0:
                    continue
                node = rec["reporter"].split(":", 1)[0]
                nrec = nodes.setdefault(node, {"tx_bytes_per_s": 0.0,
                                               "rx_bytes_per_s": 0.0})
                nrec[f"{direction}_bytes_per_s"] += rate
                sl_w = place.get(node)
                if sl_w and len(slices.get(sl_w[0], ())) > 1:
                    sl, w = sl_w
                    ring = slices[sl]
                    pos = ring.index(w)
                    peer = (ring[(pos + 1) % len(ring)] if direction == "tx"
                            else ring[(pos - 1) % len(ring)])
                    lo, hi = (w, peer) if direction == "tx" else (peer, w)
                    key = f"ici:{sl}:{lo}->{hi}"
                    lrec = link_rec(key, "ici", sl)
                else:
                    key = f"host:{node[:12]}"
                    lrec = link_rec(key, "host")
                lrec[f"{direction}_bytes_per_s"] += rate
                op = "/".join(str(rec["tags"].get(k, "?"))
                              for k in ("op", "algo"))
                lrec["by_op"][op] = lrec["by_op"].get(op, 0.0) + rate
        return {"window_s": window_s,
                "links": sorted(links.values(), key=lambda l: l["link"]),
                "nodes": nodes}

    # ---- alert evaluator (runtime/alert_defs.py) -------------------------

    def _alert_eval_tick(self, now: float = None):
        """Walk the declarative alert table against the history rings.
        Signature-dedup mirrors the stall detector — an ongoing condition
        emits ALERT_FIRING once — but a signature LEAVING the active set
        additionally emits ALERT_RESOLVED (the stall detector retires
        silently; an alert's all-clear is itself a signal)."""
        from ray_tpu.runtime import alert_defs
        from ray_tpu.runtime import events as events_mod

        if now is None:
            now = time.time()
        sigs = getattr(self, "_alert_sigs", None)
        if sigs is None:
            sigs = self._alert_sigs = set()
        state = getattr(self, "_alert_state", None)
        if state is None:
            state = self._alert_state = {}
        active = set()
        for rule in alert_defs.ALERT_RULES:
            name = rule["name"]
            try:
                firing, value, by_node = self._alert_eval_rule(rule, now)
            except Exception:
                logger.exception("alert rule %s evaluation failed", name)
                continue
            st = state.setdefault(name, {"state": "ok", "since": None})
            st.update({"value": value, "severity": rule["severity"],
                       "series": rule["series"], "summary":
                       rule.get("summary", ""), "checked": now})
            if not firing:
                st["state"], st["since"] = "ok", None
                continue
            active.add(name)
            if st["state"] != "firing":
                st["since"] = now
            st["state"] = "firing"
            if name in sigs:
                continue
            sigs.add(name)
            top_node = max(by_node, key=by_node.get) if by_node else None
            labels = {"rule": name, "series": rule["series"],
                      "value": f"{value:.6g}" if value is not None else "",
                      "threshold": str(rule.get("threshold", "")),
                      "kind": rule.get("kind", "threshold")}
            if rule.get("tags"):
                labels.update({f"tag_{k}": str(v)
                               for k, v in rule["tags"].items()})
            self._record_event(events_mod.make_event(
                events_mod.ALERT_FIRING,
                f"alert {name}: {rule.get('summary', rule['series'])} "
                f"(value {value:.6g} vs threshold "
                f"{rule.get('threshold')})" if value is not None else
                f"alert {name}: {rule.get('summary', rule['series'])}",
                severity=rule["severity"], source="gcs",
                node_id=top_node, labels=labels))
            logger.warning("ALERT_FIRING %s value=%s", name, value)
        for name in sorted(sigs - active):
            st = state.get(name, {})
            self._record_event(events_mod.make_event(
                events_mod.ALERT_RESOLVED,
                f"alert {name} resolved",
                severity=events_mod.INFO, source="gcs",
                labels={"rule": name, "series": st.get("series", "")}))
            logger.info("ALERT_RESOLVED %s", name)
        sigs.intersection_update(active)

    def _alert_eval_rule(self, rule: dict, now: float):
        """Evaluate one rule. Returns (firing, observed value, by_node)."""
        tags = rule.get("tags")
        if rule.get("kind") == "burn_rate":
            short, s_node = self._mh_burn_rate(
                rule["series"], tags, rule["slo_ms"], rule["objective"],
                rule["short_window_s"], now)
            long, _ = self._mh_burn_rate(
                rule["series"], tags, rule["slo_ms"], rule["objective"],
                rule["long_window_s"], now)
            # Both windows must burn: the long window filters single-tick
            # blips, the short one makes recovery resolve promptly.
            if short is None or long is None:
                return False, short, s_node
            thr = rule["threshold"]
            return (short >= thr and long >= thr), short, s_node
        value, by_node, _ = self._mh_window(
            rule["series"], tags=tags, window_s=rule["window_s"],
            agg=rule.get("agg"), now=now)
        if value is None:
            return False, None, by_node
        op = rule.get("op", ">")
        thr = rule["threshold"]
        firing = {"<": value < thr, "<=": value <= thr,
                  ">": value > thr, ">=": value >= thr}[op]
        return firing, value, by_node

    def _mh_burn_rate(self, series: str, tags, slo_ms: float,
                      objective: float, window_s: float, now: float):
        """SLO burn rate over one window: the fraction of observations
        breaching the SLO, divided by the error budget (1 - objective).
        1.0 = burning exactly at budget; 10x = the window's traffic would
        exhaust a month's budget in ~3 days. None = no traffic (a silent
        service is not burning)."""
        _, by_node, extras = self._mh_window(
            series, tags=tags, window_s=window_s, agg="mean", now=now)
        total = extras.get("count") or 0.0
        if total <= 0:
            return None, by_node
        boundaries = extras.get("boundaries") or []
        buckets = extras.get("buckets") or []
        breaches = 0.0
        for i, c in enumerate(buckets):
            lower = boundaries[i - 1] if i > 0 else 0.0
            if i >= len(boundaries):
                lower = boundaries[-1] if boundaries else 0.0
            if lower >= slo_ms:
                breaches += c
        frac = breaches / total
        return frac / max(1e-9, 1.0 - objective), by_node

    async def handle_list_alerts(self, conn):
        """Current rule states (`state.summary()["alerts"]` data source).
        Rules never evaluated yet report state "ok" with no value."""
        from ray_tpu.runtime import alert_defs

        state = getattr(self, "_alert_state", None) or {}
        rules = []
        for rule in alert_defs.ALERT_RULES:
            st = state.get(rule["name"], {})
            rules.append({
                "name": rule["name"], "series": rule["series"],
                "kind": rule.get("kind", "threshold"),
                "severity": rule["severity"],
                "summary": rule.get("summary", ""),
                "state": st.get("state", "ok"),
                "since": st.get("since"), "value": st.get("value"),
                "threshold": rule.get("threshold"),
            })
        return {"rules": rules,
                "firing": sorted(getattr(self, "_alert_sigs", ()) or ())}

    # ---- pubsub ----------------------------------------------------------

    async def handle_subscribe(self, conn, channels: List[str]):
        for ch in channels:
            self._subscribers.setdefault(ch, set()).add(conn)
        return {"ok": True}

    async def handle_publish(self, conn, channel: str, message: Any):
        await self.publish(channel, message)
        return {"ok": True}

    async def publish(self, channel: str, message: Any):
        dead = []
        for conn in self._subscribers.get(channel, ()):  # long-poll-free push
            try:
                await conn.push("pubsub", {"channel": channel, "message": message})
            except Exception:
                dead.append(conn)
        for conn in dead:
            self._subscribers.get(channel, set()).discard(conn)

    # ---- job table --------------------------------------------------------

    async def handle_register_job(self, conn, metadata=None,
                                  owns_cluster: bool = False,
                                  token: Optional[str] = None):
        """`owns_cluster=True` marks this driver connection as the owner of
        an auto-started cluster: if the driver dies (connection drops
        without a graceful shutdown), the whole cluster is torn down —
        otherwise a SIGKILLed driver leaks GCS/raylet/worker processes
        forever (reference: ray.init()-owned clusters die with the driver).

        `token` makes registration idempotent under the client's
        auto_reconnect retry: a lost reply must not create a second job
        whose orphaned owner connection would later tear the cluster down
        under a live driver."""
        if token:
            for job in self._jobs.values():
                if job.get("token") == token:
                    conn.meta["job_id"] = job["job_id"]
                    if owns_cluster:
                        conn.meta["owns_cluster"] = True
                    job["alive"] = True
                    self._persist_job(job)
                    return {"job_id": job["job_id"]}
        self._job_counter += 1
        job_id = self._job_counter
        conn.meta["job_id"] = job_id
        if owns_cluster:
            conn.meta["owns_cluster"] = True
        self._jobs[job_id] = {"job_id": job_id, "start_time": time.time(),
                              "metadata": metadata or {}, "alive": True,
                              "token": token}
        self._persist_job(self._jobs[job_id])
        return {"job_id": job_id}

    def _persist_job(self, job: dict):
        import pickle

        try:
            self._store.put("jobs", str(job["job_id"]).encode(),
                            pickle.dumps(job))
        except Exception:
            logger.exception("job persist failed")

    async def handle_get_jobs(self, conn):
        return list(self._jobs.values())

    # ---- actor management (gcs_actor_manager.h:291 state machine) --------

    async def handle_create_actor(self, conn, spec: ActorSpec):
        if spec.name:
            key = (spec.namespace, spec.name)
            if key in self._named_actors:
                existing = self._actors[self._named_actors[key]]
                if existing.state != DEAD:
                    return {"ok": False, "error": f"actor name {spec.name!r} already taken"}
            self._named_actors[key] = spec.actor_id
        record = ActorRecord(spec)
        self._actors[spec.actor_id] = record
        self._actor_locks[spec.actor_id] = asyncio.Lock()
        self._persist_actor(record)
        try:
            await self._schedule_and_create(record)
        except Exception as e:
            record.state = DEAD
            record.death_reason = f"creation failed: {e!r}"
            self._persist_actor(record)
            return {"ok": False, "error": record.death_reason}
        return {"ok": True, "address": record.address, "actor_id": spec.actor_id}

    async def _schedule_and_create(self, record: ActorRecord):
        """GcsActorScheduler analog (gcs_actor_scheduler.h:111): lease a worker
        from a raylet, push the creation task to it, record the address."""
        spec = record.spec
        last_err = None
        import os as _os
        # Failed leases still need their req_ids canceled at the raylet (a
        # pending lease, or a grant that raced the timeout, must not leak
        # worker resources) — but a dead node's cancel must not stall the
        # scheduling loop, so cancels accumulate per node and fire batched
        # in the background at exit.
        pending_cancels: Dict[bytes, list] = {}

        def _flush_cancels():
            for nid, req_ids in pending_cancels.items():
                node_rec = self._nodes.get(nid)
                if node_rec is None or not node_rec.alive:
                    continue
                asyncio.ensure_future(
                    self._cancel_leases_at(node_rec, req_ids))

        try:
            for node in scheduling.rank_nodes_for_actor(self._nodes, spec,
                                                        self._pg_manager):
                req_id = _os.urandom(8)
                try:
                    lease = await node.client.call(
                        "lease_worker", resources=spec.resources,
                        for_actor=True,
                        placement_group_id=spec.placement_group_id,
                        bundle_index=spec.placement_group_bundle_index,
                        req_id=req_id, timeout=60)
                except Exception as e:
                    last_err = e
                    pending_cancels.setdefault(node.node_id, []).append(req_id)
                    continue
                if not lease.get("ok"):
                    last_err = RuntimeError(lease.get("error", "lease refused"))
                    continue
                worker_addr = tuple(lease["worker_address"])
                logger.debug("pushing create_actor %s to worker %s at %s",
                             spec.actor_id.hex()[:12],
                             lease["worker_id"].hex()[:12], worker_addr)
                worker_client = RpcClient(*worker_addr)
                try:
                    await worker_client.connect(timeout=15)
                    reply = await worker_client.call("create_actor", spec=spec,
                                                     timeout=300)
                    if not reply.get("ok"):
                        raise RuntimeError(
                            reply.get("error", "actor __init__ failed"))
                except Exception as e:
                    last_err = e
                    try:
                        await node.client.call(
                            "return_worker", lease_id=lease["lease_id"],
                            worker_dead=True)
                    except Exception:
                        pass
                    # __init__ raising is terminal, not a scheduling failure.
                    if isinstance(e, RuntimeError):
                        raise
                    continue
                finally:
                    await worker_client.close()
                record.state = ALIVE
                record.address = worker_addr
                record.node_id = node.node_id
                record.worker_id = lease["worker_id"]
                self._persist_actor(record)
                await self.publish("actor",
                                   {"event": "alive", "actor": record.view()})
                return
        finally:
            _flush_cancels()
        raise RuntimeError(f"no feasible node for actor {spec.class_name} "
                           f"(resources={spec.resources}): {last_err!r}")

    async def _cancel_leases_at(self, node: NodeRecord, req_ids: list):
        """Best-effort batched lease cancel at one raylet: a single
        cancel_lease_batch frame, per-id fallback against an old raylet; a
        node that died in the meantime is tolerated silently."""
        try:
            await node.client.call("cancel_lease_batch",
                                   req_ids=list(req_ids), timeout=10)
            return
        except Exception as e:
            from ray_tpu.runtime.rpc import ConnectionLost, RpcError
            if not (isinstance(e, RpcError)
                    and not isinstance(e, ConnectionLost)
                    and "no handler" in str(e)):
                return  # dead/unreachable node: nothing left to cancel
        results = await asyncio.gather(
            *(node.client.call("cancel_lease_request", req_id=rid, timeout=10)
              for rid in req_ids),
            return_exceptions=True)
        del results  # best-effort: failures mean the node is going away

    async def handle_get_actor(self, conn, actor_id: Optional[bytes] = None,
                               name: Optional[str] = None, namespace: str = "default"):
        if actor_id is None and name is not None:
            actor_id = self._named_actors.get((namespace, name))
        rec = self._actors.get(actor_id) if actor_id else None
        if rec is None:
            return {"found": False}
        return {"found": True, **rec.view()}

    async def handle_report_task_events(self, conn, events,
                                        wait_edges=None, reporter=None,
                                        node_id=None):
        """Batched task state transitions from workers/drivers
        (GcsTaskManager analog; task_event_buffer.h:224 export path) —
        legacy pickled envelope; new workers ship one typed
        TaskEventBatchMsg frame via report_task_events2 instead.

        `wait_edges` piggybacks the reporter's blocked-on edges on the
        same flush tick: None = no update, a list (possibly empty, to
        clear) replaces the reporter's previous edge set in the cluster
        wait-graph."""
        self._ingest_task_events(events, wait_edges, reporter, node_id, 0)
        return {"ok": True}

    async def handle_report_task_events2(self, conn, m: bytes):
        """Typed twin of handle_report_task_events: the whole flush tick
        arrives as one TaskEventBatchMsg frame (events + wait edges + the
        reporter's buffer-overflow drop count) instead of N dict-pickles."""
        from ray_tpu.runtime import wire

        msg = wire.TaskEventBatchMsg.decode(m)
        self._ingest_task_events(
            [e.to_event() for e in msg.events],
            msg.wait_edges if msg.has_wait_edges else None,
            msg.reporter or None, msg.node_id or None, msg.dropped)
        return {"ok": True}

    def _event_shards(self) -> list:
        """The task-event store, sharded by origin node: each shard is an
        independent bounded ring + latest-per-task index so ingest and
        index upkeep touch ONE shard — a 1k-node cluster's GCS tick stays
        O(shard), not O(cluster). Readers merge across shards."""
        shards = getattr(self, "_task_event_shards", None)
        if shards is None:
            from collections import deque

            from ray_tpu.config import cfg

            n = max(1, cfg().gcs_ring_shards)
            per = max(1, cfg().task_events_max // n)
            shards = self._task_event_shards = [
                {"ring": deque(maxlen=per), "latest": {}} for _ in range(n)]
            self._task_events_dropped_total = 0
        return shards

    def _shard_for(self, key) -> dict:
        shards = self._event_shards()
        if isinstance(key, str):
            key = key.encode()
        return shards[zlib.crc32(key or b"") % len(shards)]

    def _ingest_task_events(self, events, wait_edges, reporter, node_id,
                            dropped: int):
        if wait_edges is not None and reporter is not None:
            table = getattr(self, "_wait_edges", None)
            if table is None:
                table = self._wait_edges = {}
            if wait_edges:
                table[reporter] = {
                    "edges": list(wait_edges), "time": time.time(),
                    "node_id": (node_id.hex()
                                if isinstance(node_id, (bytes, bytearray))
                                else node_id)}
            else:
                table.pop(reporter, None)
        shard = self._shard_for(node_id or reporter or b"")
        if dropped:
            self._task_events_dropped_total = (
                getattr(self, "_task_events_dropped_total", 0) + dropped)
        ring, latest = shard["ring"], shard["latest"]
        for ev in events:
            ring.append(ev)
            cur = latest.get(ev["task_id"])
            if cur is None or ev["time"] >= cur["time"]:
                latest[ev["task_id"]] = ev
            # Bound the per-task index alongside its own ring only.
            if len(latest) > ring.maxlen:
                alive = {e["task_id"] for e in ring}
                stale = [k for k in latest if k not in alive]
                for k in stale:
                    del latest[k]
                shard["latest"] = latest

    async def handle_task_event_stats(self, conn):
        """Ingest-side health of the task-event plane: shard layout plus
        the cluster-wide count of events workers trimmed before flush
        (satellite of ray_tpu_task_events_dropped_total)."""
        shards = getattr(self, "_task_event_shards", None) or []
        return {
            "shards": len(shards),
            "events_stored": sum(len(s["ring"]) for s in shards),
            "tasks_indexed": sum(len(s["latest"]) for s in shards),
            "events_dropped_total":
                getattr(self, "_task_events_dropped_total", 0),
        }

    # ---- cluster wait-graph + stall/deadlock detector --------------------
    #
    # Workers piggyback blocked-on edges (task -> object -> owner task,
    # collective member -> group, channel reader -> channel) onto their
    # task-event flush; the GCS assembles them into one graph and a
    # periodic tick (a) finds actor-level cycles -> DEADLOCK_DETECTED and
    # (b) flags edges blocked past `stall_threshold_s` -> TASK_STALLED,
    # with collective edges grouped per group so the event names the
    # STRAGGLER ranks (members NOT blocked) rather than the whole gang —
    # the cross-link into the failure-domain plane.

    def _wait_edge_snapshot(self) -> list:
        """Live wait-graph edges, flattened with reporter attribution.
        Edges whose reporter stopped refreshing (crashed or unblocked
        worker) age out after `wait_edge_max_age_s`."""
        from ray_tpu.config import cfg

        table = getattr(self, "_wait_edges", None)
        if not table:
            return []
        now = time.time()
        max_age = cfg().wait_edge_max_age_s
        edges = []
        for reporter, rec in list(table.items()):
            if now - rec["time"] > max_age:
                table.pop(reporter, None)
                continue
            for e in rec["edges"]:
                e2 = dict(e)
                e2["reporter"] = reporter
                if rec.get("node_id") and "node_id" not in e2:
                    e2["node_id"] = rec["node_id"]
                edges.append(e2)
        return edges

    def _edge_node_slice(self, edge: dict):
        """(node hex, slice name) attribution for an edge's reporter."""
        node_hex = edge.get("node_id")
        if not node_hex:
            return None, None
        try:
            rec = self._nodes.get(bytes.fromhex(node_hex))
        except (ValueError, TypeError):
            rec = None
        return node_hex, (rec.labels.get("tpu-slice-name")
                          if rec else None)

    @staticmethod
    def _edge_stack(edge: dict) -> str:
        return "\n".join(edge.get("stack", ())[-2:])

    def _stall_detector_tick(self):
        from ray_tpu.config import cfg
        from ray_tpu.runtime import events as events_mod

        edges = self._wait_edge_snapshot()
        sigs = getattr(self, "_stall_sigs", None)
        if sigs is None:
            sigs = self._stall_sigs = set()
        active = set()
        counts = {"stalled_tasks": 0, "deadlocks": 0}
        now = time.time()
        threshold = cfg().stall_threshold_s

        # (a) Cycles: unit = actor when known, else the reporter process.
        graph: dict = {}
        cycle_edges: dict = {}
        for e in edges:
            if e.get("kind") != "object_get":
                continue
            src = e.get("waiter_actor") or e.get("reporter")
            dst = e.get("target_actor")
            if src and dst and src != dst:
                graph.setdefault(src, set()).add(dst)
                cycle_edges.setdefault((src, dst), e)
        deadlocks = _find_cycles(graph)
        self._active_deadlocks = deadlocks
        counts["deadlocks"] = len(deadlocks)
        for cyc in deadlocks:
            sig = ("deadlock", frozenset(cyc))
            active.add(sig)
            if sig in sigs:
                continue
            sigs.add(sig)
            hops, labels = [], {}
            for i, src in enumerate(cyc):
                dst = cyc[(i + 1) % len(cyc)]
                e = cycle_edges.get((src, dst), {})
                hops.append(
                    f"{src[:12]} waits on object {e.get('oid', '?')} "
                    f"({e.get('target_name', '?')}) held by {dst[:12]}")
                stack = self._edge_stack(e)
                if stack:
                    labels[f"stack_{src[:12]}"] = stack
            node_hex, slice_name = self._edge_node_slice(
                cycle_edges.get((cyc[0], cyc[1 % len(cyc)]), {}))
            labels["members"] = ",".join(c[:12] for c in cyc)
            self._record_event(events_mod.make_event(
                events_mod.DEADLOCK_DETECTED,
                f"wait-graph cycle across {len(cyc)} waiter(s): "
                + "; ".join(hops),
                severity=events_mod.ERROR, source="gcs",
                node_id=node_hex, slice_name=slice_name,
                actor_id=cyc[0], labels=labels))
            logger.error("deadlock detected: %s", "; ".join(hops))

        # (b) Long-stalled edges. Collective edges are grouped per group
        # so one event attributes the straggler ranks; everything else
        # stalls individually.
        coll: dict = {}
        for e in edges:
            if e.get("kind") == "collective_op":
                coll.setdefault(e.get("group"), []).append(e)
                continue
            age = now - e.get("since", now)
            if age < threshold:
                continue
            counts["stalled_tasks"] += 1
            sig = ("stall", e.get("reporter"), e.get("kind"),
                   e.get("oid") or e.get("channel"))
            active.add(sig)
            if sig in sigs:
                continue
            sigs.add(sig)
            node_hex, slice_name = self._edge_node_slice(e)
            who = (e.get("waiter_name") or e.get("waiter_task")
                   or e.get("reporter"))
            what = (f"object {e.get('oid')}" if e.get("oid")
                    else f"channel {e.get('channel')}")
            labels = {"kind": e.get("kind", ""), "reporter":
                      str(e.get("reporter", ""))}
            if e.get("oid"):
                labels["oid"] = e["oid"]
            if e.get("owner"):
                labels["owner"] = str(e["owner"])
            stack = self._edge_stack(e)
            if stack:
                labels["stack"] = stack
            self._record_event(events_mod.make_event(
                events_mod.TASK_STALLED,
                f"{who} blocked on {what} for {age:.0f}s "
                f"(threshold {threshold:g}s)",
                severity=events_mod.WARNING, source="gcs",
                node_id=node_hex, slice_name=slice_name,
                actor_id=e.get("waiter_actor"), labels=labels))
            logger.warning("stalled: %s blocked on %s for %.0fs",
                           who, what, age)
        for group, ges in coll.items():
            stalled = [e for e in ges
                       if now - e.get("since", now) >= threshold]
            if not stalled:
                continue
            counts["stalled_tasks"] += len(stalled)
            blocked_ranks = sorted({e.get("rank") for e in stalled
                                    if e.get("rank") is not None})
            world = next((e.get("world_size") for e in stalled
                          if e.get("world_size")), None)
            stragglers = (sorted(set(range(world)) - set(blocked_ranks))
                          if world else [])
            sig = ("stall_collective", group, tuple(blocked_ranks))
            active.add(sig)
            if sig in sigs:
                continue
            sigs.add(sig)
            age = max(now - e.get("since", now) for e in stalled)
            e0 = stalled[0]
            node_hex, slice_name = self._edge_node_slice(e0)
            msg = (f"collective group {group!r}: rank(s) "
                   f"{blocked_ranks} blocked in op "
                   f"#{e0.get('op_id', '?')} for {age:.0f}s")
            if stragglers:
                msg += (f"; straggler rank(s) {stragglers} have not "
                        f"entered the op")
            labels = {"group": str(group),
                      "blocked_ranks": ",".join(map(str, blocked_ranks)),
                      "straggler_ranks": ",".join(map(str, stragglers)),
                      "op_id": str(e0.get("op_id", ""))}
            stack = self._edge_stack(e0)
            if stack:
                labels["stack"] = stack
            self._record_event(events_mod.make_event(
                events_mod.TASK_STALLED, msg,
                severity=events_mod.WARNING, source="gcs",
                node_id=node_hex, slice_name=slice_name,
                labels=labels))
            logger.warning("%s", msg)
        # Retire resolved conditions so a recurrence re-alerts.
        sigs.intersection_update(active)
        self._stall_counts = counts

    async def handle_wait_graph(self, conn):
        """The assembled cluster wait-graph plus the detector's current
        verdict counts (`state.wait_graph()` / dashboard data source)."""
        return {
            "edges": self._wait_edge_snapshot(),
            "cycles": list(getattr(self, "_active_deadlocks", [])),
            **getattr(self, "_stall_counts",
                      {"stalled_tasks": 0, "deadlocks": 0}),
        }

    # ---- cluster event bus (runtime/events.py) ---------------------------

    def _record_event(self, ev: dict):
        """Append one typed cluster event to the bounded ring (see
        runtime/events.py for the record shape and the emitter list)."""
        from collections import deque

        from ray_tpu.config import cfg

        store = getattr(self, "_cluster_events", None)
        if store is None:
            store = self._cluster_events = deque(
                maxlen=cfg().cluster_events_max)
        store.append(ev)

    async def handle_report_events(self, conn, events):
        """Batched typed cluster events from any component (best-effort
        emitters: raylets, collective ranks, autoscaler, Train)."""
        for ev in events:
            if isinstance(ev, dict):
                self._record_event(dict(ev))
        return {"ok": True}

    async def handle_list_events(self, conn, event_type=None, severity=None,
                                 source=None, limit: int = 100):
        """Newest-first filtered view of the cluster event ring."""
        store = getattr(self, "_cluster_events", None) or ()
        out = []
        for ev in reversed(store):
            if event_type is not None and ev.get("type") != event_type:
                continue
            if severity is not None and ev.get("severity") != severity:
                continue
            if source is not None and ev.get("source") != source:
                continue
            out.append(ev)
            if len(out) >= limit:
                break
        return out

    async def handle_list_tasks(self, conn, state=None, name=None,
                                limit: int = 1000):
        shards = getattr(self, "_task_event_shards", None) or []
        out = []
        for ev in sorted((ev for s in shards for ev in s["latest"].values()),
                         key=lambda e: -e["time"]):
            if state is not None and ev["state"] != state:
                continue
            if name is not None and name not in ev["name"]:
                continue
            out.append(ev)
            if len(out) >= limit:
                break
        return out

    async def handle_get_task(self, conn, task_id_hex: str):
        """Per-task drill-through: the FULL transition history of one task
        (every recorded state event, oldest first), matched by hex id or
        unambiguous prefix — the dashboard task page's data source."""
        def _hex(tid):
            return tid.hex() if isinstance(tid, bytes) else str(tid)

        shards = getattr(self, "_task_event_shards", None) or []
        events = [ev for s in shards for ev in s["ring"]
                  if _hex(ev["task_id"]).startswith(task_id_hex)]
        ids = {_hex(ev["task_id"]) for ev in events}
        if len(ids) > 1:
            return {"error": f"ambiguous task id prefix {task_id_hex!r} "
                             f"({len(ids)} matches)"}
        return {"found": bool(events),
                "events": sorted(events, key=lambda e: e["time"])}

    async def handle_task_timeline(self, conn, limit: int = 2000):
        """Full state-transition log (not just latest-per-task): the
        dashboard timeline pairs RUNNING->FINISHED/FAILED per task into
        per-worker execution bars (GcsTaskManager export / `ray timeline`
        analog)."""
        shards = getattr(self, "_task_event_shards", None) or []
        events = sorted((ev for s in shards for ev in s["ring"]),
                        key=lambda e: e["time"])[-limit:]
        return events

    async def handle_list_actors(self, conn):
        return [r.view() for r in self._actors.values()]

    async def handle_kill_actor(self, conn, actor_id: bytes, no_restart=True):
        rec = self._actors.get(actor_id)
        if rec is None:
            return {"ok": False}
        if no_restart:
            rec.spec.max_restarts = 0
        node = self._nodes.get(rec.node_id) if rec.node_id else None
        if node is not None and node.alive and rec.worker_id is not None:
            try:
                await node.client.call("kill_worker", worker_id=rec.worker_id)
            except Exception:
                pass
        return {"ok": True}

    async def handle_report_worker_death(self, conn, node_id, worker_id, actor_id=None,
                                         reason="", pid=None):
        """Raylet tells us a worker process exited (node_manager death path).
        Republished on the 'worker_death' channel so object owners can prune
        dead borrowers (reference_count.h borrower-failure handling).

        When the raylet names the dead worker's os pid, the reporter's
        `metrics:<node>:<pid>` snapshot and its history rings are purged
        here — the per-worker flavor of the dead-node metrics purge (a pid
        that exited while its node stayed alive would otherwise count
        toward /metrics aggregation forever)."""
        if actor_id is not None:
            await self._handle_actor_failure(actor_id, reason or "worker died")
        if pid is not None:
            node_hex = (node_id.hex() if isinstance(node_id, bytes)
                        else str(node_id))
            key = f"metrics:{node_hex}:{pid}".encode()
            self._kv.pop(key, None)
            try:
                self._store.delete("kv", key)
            except Exception:
                pass
            self._mh_purge_reporter(f"{node_hex}:{pid}")
        await self.publish("worker_death", {
            "worker_id": worker_id.hex() if isinstance(worker_id, bytes)
            else worker_id, "reason": reason})
        return {"ok": True}

    async def _handle_actor_failure(self, actor_id: bytes, reason: str):
        rec = self._actors.get(actor_id)
        if rec is None or rec.state == DEAD:
            return
        lock = self._actor_locks.setdefault(actor_id, asyncio.Lock())
        async with lock:
            if rec.state == DEAD:
                return
            # Infinite-retry-on-preemption: a death caused by an ANNOUNCED
            # node retirement does not consume the restart budget (the
            # reference framework's drained-node semantics) — only actors
            # that are restartable at all (max_restarts > 0) qualify.
            from ray_tpu.core.exceptions import death_cause, CAUSE_PREEMPTION

            preempted = (death_cause(reason) == CAUSE_PREEMPTION
                         and rec.spec.max_restarts > 0)
            if preempted or rec.restarts_used < rec.spec.max_restarts:
                if not preempted:
                    rec.restarts_used += 1
                rec.state = RESTARTING
                rec.address = None
                await self.publish("actor", {"event": "restarting", "actor": rec.view()})
                try:
                    # Only an ANNOUNCED retirement has replacement capacity
                    # in flight worth waiting for; a plain crash keeps the
                    # old fail-fast semantics (an actor whose resource no
                    # longer exists anywhere must die, not stall).
                    if preempted:
                        await self._restart_with_capacity_wait(rec)
                    else:
                        await self._schedule_and_create(rec)
                except Exception as e:
                    rec.state = DEAD
                    rec.death_reason = f"restart failed: {e!r}"
                    self._persist_actor(rec)
                    await self.publish("actor", {"event": "dead", "actor": rec.view()})
            else:
                rec.state = DEAD
                rec.death_reason = reason
                self._persist_actor(rec)
                await self.publish("actor", {"event": "dead", "actor": rec.view()})

    async def _restart_with_capacity_wait(self, rec: "ActorRecord"):
        """Restart a PREEMPTED actor, waiting out a transient capacity gap.

        A restart triggered by an announced node retirement routinely
        RACES the capacity that replaces the node (the autoscaler
        launches at preemption notice time, but registration takes
        seconds) — failing the actor permanently on the first 'no
        feasible node' would make every graceful drain a coin flip.
        Only the feasibility error retries; anything else (e.g.
        __init__ raising) is terminal as before."""
        from ray_tpu.config import cfg

        deadline = time.monotonic() + cfg().actor_restart_capacity_wait_s
        while True:
            try:
                await self._schedule_and_create(rec)
                return
            except RuntimeError as e:
                if (not str(e).startswith("no feasible node")
                        or time.monotonic() >= deadline):
                    raise
                logger.info(
                    "actor %s restart waiting for capacity (%s)",
                    rec.spec.actor_id.hex()[:12], e)
                await asyncio.sleep(1.0)

    # ---- placement groups (delegated, see gcs/placement_groups.py) -------

    async def handle_create_placement_group(self, conn, **kw):
        return await self._pg_manager.create(**kw)

    async def handle_remove_placement_group(self, conn, **kw):
        return await self._pg_manager.remove(**kw)

    async def handle_get_placement_group(self, conn, **kw):
        return await self._pg_manager.get(**kw)

    async def handle_list_placement_groups(self, conn):
        return await self._pg_manager.list()

    # ---- shutdown ---------------------------------------------------------

    async def handle_shutdown_cluster(self, conn):
        self._spawn_bg(self._do_shutdown())
        return {"ok": True}

    async def _do_shutdown(self):
        logger.info("cluster shutdown: notifying %d nodes", len(self._nodes))
        await asyncio.sleep(0.05)  # let the reply flush
        for rec in self._nodes.values():
            if rec.alive and rec.client is not None:
                try:
                    await rec.client.call("shutdown_node", timeout=5)
                except Exception as e:
                    logger.warning("shutdown_node to %s failed: %r",
                                   rec.node_id.hex()[:12], e)
        logger.info("cluster shutdown: nodes notified; stopping GCS")
        self._shutdown.set()

    async def wait_for_shutdown(self):
        await self._shutdown.wait()
        if self._health_task:
            self._health_task.cancel()
        await self.server.close()
