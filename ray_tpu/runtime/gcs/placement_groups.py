"""Placement group manager: 2-phase bundle reservation across raylets.

Reference analog: src/ray/gcs/gcs_server/gcs_placement_group_manager.* and
gcs_placement_group_scheduler.h:453 (Prepare/Commit two-phase protocol),
strategies from src/ray/protobuf/common.proto:978-985 (PACK, SPREAD,
STRICT_PACK, STRICT_SPREAD).

TPU-native addition: STRICT_PACK placement prefers nodes advertising a whole
ICI slice (label "tpu-slice"), so a bundle-per-chip group lands on one
physically-connected slice (SURVEY §2 mapping note; see
runtime/resources.py for slice detection).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, List, Optional, Tuple

from ray_tpu.runtime import scheduling

logger = logging.getLogger(__name__)

PACK = "PACK"
SPREAD = "SPREAD"
STRICT_PACK = "STRICT_PACK"
STRICT_SPREAD = "STRICT_SPREAD"

PENDING = "PENDING"
CREATED = "CREATED"
REMOVED = "REMOVED"
RESCHEDULING = "RESCHEDULING"


class PlacementGroupRecord:
    def __init__(self, pg_id: bytes, bundles: List[Dict[str, float]], strategy: str,
                 name: str = ""):
        self.pg_id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        self.name = name
        self.state = PENDING
        # bundle index -> node_id
        self.locations: List[Optional[bytes]] = [None] * len(bundles)

    def view(self) -> dict:
        return {
            "placement_group_id": self.pg_id,
            "name": self.name,
            "strategy": self.strategy,
            "bundles": self.bundles,
            "state": self.state,
            "locations": list(self.locations),
        }


class PlacementGroupManager:
    def __init__(self, gcs):
        self.gcs = gcs
        self._groups: Dict[bytes, PlacementGroupRecord] = {}
        self._lock = asyncio.Lock()
        self._retry_task: Optional[asyncio.Task] = None

    def restore_record(self, d: dict):
        """Rebuild a record after a GCS restart (raylets still hold the
        committed bundles, so CREATED groups stay valid)."""
        rec = PlacementGroupRecord(d["pg_id"], d["bundles"], d["strategy"],
                                   d["name"])
        rec.state = d["state"]
        rec.locations = list(d["locations"])
        self._groups[d["pg_id"]] = rec

    # ---- queries ----------------------------------------------------------

    def bundle_location(self, pg_id: bytes, bundle_index: int) -> Optional[bytes]:
        rec = self._groups.get(pg_id)
        if rec is None or rec.state != CREATED:
            return None
        if bundle_index < 0:
            for loc in rec.locations:
                if loc is not None:
                    return loc
            return None
        return rec.locations[bundle_index]

    async def get(self, pg_id: bytes):
        rec = self._groups.get(pg_id)
        return {"found": rec is not None, **(rec.view() if rec else {})}

    async def list(self):
        return [r.view() for r in self._groups.values()]

    # ---- creation: plan, then 2PC prepare/commit --------------------------

    async def create(self, pg_id: bytes, bundles: List[Dict[str, float]],
                     strategy: str = PACK, name: str = ""):
        """Two infeasibility classes (reference: pending PGs queue in
        GcsPlacementGroupManager and retry as the cluster changes):

        * capacity-infeasible — no assignment exists even against TOTAL
          node resources (e.g. STRICT_PACK across fragmented slices): fail
          the create loudly, the group can never be satisfied as-is.
        * currently-infeasible — an assignment exists by capacity but not
          by current availability (resources still draining from a group
          torn down moments ago, workers mid-exit): the group stays
          PENDING and a retry loop re-places it as the resource view
          changes; pg.wait() observes CREATED when it lands. This is the
          elastic-restart path: shrink-after-failure re-requests its PG
          before the failed group's reservations finish releasing.
        """
        rec = PlacementGroupRecord(pg_id, bundles, strategy, name)
        self._groups[pg_id] = rec
        async with self._lock:
            # State transition under the SAME lock as placement: the retry
            # loop must never observe a successfully-placed record still
            # PENDING (it would place it a second time, leaking the first
            # set of bundle reservations).
            ok, err = await self._try_place(rec)
            if ok:
                rec.state = CREATED
        if not ok:
            if self._plan(rec, by_capacity=True) is None:
                self._groups.pop(pg_id, None)
                return {"ok": False, "error": err,
                        "placement_group_id": pg_id}
            # Persist the PENDING record: a GCS restart must restore it
            # (restore_record + kick) or pg.wait() would hang forever.
            self.gcs.persist_pg(rec)
            self._ensure_retry_loop()
            return {"ok": True, "placement_group_id": pg_id,
                    "state": PENDING}
        self.gcs.persist_pg(rec)
        await self.gcs.publish("placement_group", {"event": "created", "pg": rec.view()})
        return {"ok": True, "placement_group_id": pg_id}

    def _ensure_retry_loop(self):
        if self._retry_task is None or self._retry_task.done():
            self._retry_task = asyncio.ensure_future(self._retry_pending_loop())

    async def _retry_pending_loop(self):
        """Re-place PENDING groups until none remain. Cheap (a plan against
        the in-memory view) and self-terminating; woken again by create()/
        remove()/node events."""
        from ray_tpu.config import cfg

        interval = getattr(cfg(), "pg_retry_interval_s", 0.2)
        while True:
            await asyncio.sleep(interval)
            pending = [r for r in self._groups.values() if r.state == PENDING]
            if not pending:
                return
            for rec in pending:
                async with self._lock:
                    if rec.state != PENDING:
                        continue
                    ok, _err = await self._try_place(rec)
                    if ok:
                        rec.state = CREATED  # same-lock transition (above)
                if ok:
                    self.gcs.persist_pg(rec)
                    await self.gcs.publish(
                        "placement_group",
                        {"event": "created", "pg": rec.view()})

    def kick(self):
        """Resources may have freed (PG removed, node joined/recovered):
        wake the pending retry loop."""
        if any(r.state == PENDING for r in self._groups.values()):
            self._ensure_retry_loop()

    def _plan(self, rec: PlacementGroupRecord,
              by_capacity: bool = False) -> Optional[List[Tuple[int, bytes]]]:
        """Pick a node per bundle against a snapshot of available resources
        (or TOTAL resources with by_capacity=True — the can-this-ever-fit
        check). Returns [(bundle_index, node_id)] or None if infeasible.
        """
        # Draining nodes take no NEW bundles: a gang placed there would be
        # killed at the drain deadline moments later.
        nodes = [n for n in self.gcs._nodes.values()
                 if n.alive and not getattr(n, "draining", False)]
        snapshot = {n.node_id: dict(n.resources if by_capacity
                                    else n.available) for n in nodes}
        totals = {n.node_id: n.resources for n in nodes}
        labels = {n.node_id: n.labels for n in nodes}
        plan: List[Tuple[int, bytes]] = []

        def fits_on(nid, bundle):
            return scheduling.fits(snapshot[nid], bundle)

        idxs = list(range(len(rec.bundles)))
        if rec.strategy in (STRICT_PACK, PACK):
            # Try to land everything on one node. STRICT_PACK: prefer nodes
            # advertising an intact TPU slice (ICI-contiguous placement).
            candidates = sorted(
                snapshot.keys(),
                key=lambda nid: (0 if labels[nid].get("tpu-slice") else 1,
                                 scheduling.utilization_score(totals[nid], snapshot[nid], {})))
            for nid in candidates:
                snap = dict(snapshot[nid])
                ok = True
                for b in rec.bundles:
                    if scheduling.fits(snap, b):
                        scheduling.subtract(snap, b)
                    else:
                        ok = False
                        break
                if ok:
                    return [(i, nid) for i in idxs]
            if rec.strategy == STRICT_PACK:
                # Multi-host slice path: a bundle-per-host TPU group must
                # land on a CONTIGUOUS worker-id run of ONE slice — never
                # fragmented across slices (that would put DCN hops inside
                # the job's ICI mesh). See runtime/tpu_topology.py.
                if all(b.get("TPU", 0) > 0 for b in rec.bundles):
                    from ray_tpu.runtime import tpu_topology

                    node_views = [{"node_id": nid, "labels": labels[nid]}
                                  for nid in snapshot]
                    plan = tpu_topology.find_contiguous_hosts(
                        node_views, len(rec.bundles),
                        fits=lambda i, nid: scheduling.fits(
                            snapshot[nid], rec.bundles[i]))
                    if plan is not None:
                        return plan
                return None
            # PACK falls back to spreading while preferring fewer nodes.
        if rec.strategy == STRICT_SPREAD:
            used_nodes = set()
            for i in idxs:
                placed = False
                for nid in sorted(snapshot, key=lambda nid: scheduling.utilization_score(
                        totals[nid], snapshot[nid], rec.bundles[i])):
                    if nid in used_nodes or not fits_on(nid, rec.bundles[i]):
                        continue
                    scheduling.subtract(snapshot[nid], rec.bundles[i])
                    used_nodes.add(nid)
                    plan.append((i, nid))
                    placed = True
                    break
                if not placed:
                    return None
            return plan
        # PACK fallback / SPREAD: greedy per-bundle.
        prefer_few = rec.strategy == PACK
        for i in idxs:
            order = sorted(
                snapshot,
                key=lambda nid: scheduling.utilization_score(
                    totals[nid], snapshot[nid], rec.bundles[i]) * (-1 if prefer_few else 1))
            placed = False
            for nid in order:
                if fits_on(nid, rec.bundles[i]):
                    scheduling.subtract(snapshot[nid], rec.bundles[i])
                    plan.append((i, nid))
                    placed = True
                    break
            if not placed:
                return None
        return plan

    async def _try_place(self, rec: PlacementGroupRecord) -> Tuple[bool, str]:
        plan = self._plan(rec)
        if plan is None:
            return False, "infeasible: no node assignment satisfies the bundles"
        # Phase 1: prepare every bundle reservation.
        prepared: List[Tuple[int, bytes]] = []
        for i, nid in plan:
            node = self.gcs._nodes.get(nid)
            try:
                r = await node.client.call("prepare_bundle", pg_id=rec.pg_id,
                                           bundle_index=i, resources=rec.bundles[i],
                                           timeout=30)
            except Exception as e:
                r = {"ok": False, "error": repr(e)}
            if not r.get("ok"):
                for j, njd in prepared:
                    try:
                        await self.gcs._nodes[njd].client.call(
                            "cancel_bundle", pg_id=rec.pg_id, bundle_index=j, timeout=30)
                    except Exception:
                        pass
                return False, r.get("error", "prepare failed")
            prepared.append((i, nid))
        # Phase 2: commit.
        for i, nid in prepared:
            await self.gcs._nodes[nid].client.call(
                "commit_bundle", pg_id=rec.pg_id, bundle_index=i, timeout=30)
            rec.locations[i] = nid
        return True, ""

    async def remove(self, pg_id: bytes):
        rec = self._groups.get(pg_id)
        if rec is None:
            return {"ok": False}
        for i, nid in enumerate(rec.locations):
            if nid is None:
                continue
            node = self.gcs._nodes.get(nid)
            if node is not None and node.alive:
                try:
                    await node.client.call("return_bundle", pg_id=pg_id, bundle_index=i,
                                           timeout=30)
                except Exception:
                    pass
        rec.state = REMOVED
        self.gcs.persist_pg(rec)
        rec.locations = [None] * len(rec.bundles)
        await self.gcs.publish("placement_group", {"event": "removed", "pg": rec.view()})
        self.kick()  # freed bundles may unblock a pending group
        return {"ok": True}

    async def on_node_dead(self, node_id: bytes):
        """Reschedule groups that had bundles on a dead node."""
        for rec in self._groups.values():
            if rec.state == CREATED and node_id in rec.locations:
                rec.state = RESCHEDULING
                for i, nid in enumerate(rec.locations):
                    if nid is not None and nid != node_id:
                        node = self.gcs._nodes.get(nid)
                        if node is not None and node.alive:
                            try:
                                await node.client.call("return_bundle", pg_id=rec.pg_id,
                                                       bundle_index=i, timeout=30)
                            except Exception:
                                pass
                rec.locations = [None] * len(rec.bundles)
                async with self._lock:
                    ok, _ = await self._try_place(rec)
                    rec.state = CREATED if ok else PENDING
                if not ok:
                    self._ensure_retry_loop()
                self.gcs.persist_pg(rec)
                await self.gcs.publish("placement_group",
                                       {"event": "rescheduled" if ok else "pending",
                                        "pg": rec.view()})
