"""GCS server process entrypoint (gcs_server_main.cc analog)."""

import argparse
import asyncio
import logging
import os


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--ready-file", default=None)
    parser.add_argument("--storage", default=None,
                        help="sqlite file for durable GCS state (FT mode)")
    args = parser.parse_args()

    from ray_tpu.utils.debug import register_stack_dump_signal

    register_stack_dump_signal()
    logging.basicConfig(
        level=os.environ.get("RAY_TPU_LOG_LEVEL", "INFO"),
        format="[gcs %(asctime)s %(levelname)s %(name)s] %(message)s")

    from ray_tpu.runtime.gcs.server import GcsServer

    async def run():
        gcs = GcsServer(args.host, args.port, storage_path=args.storage)
        await gcs.start()
        if args.ready_file:
            tmp = args.ready_file + ".tmp"
            with open(tmp, "w") as f:
                f.write(f"{gcs.server.host}:{gcs.server.port}")
            os.replace(tmp, args.ready_file)
        await gcs.wait_for_shutdown()

    asyncio.run(run())


if __name__ == "__main__":
    main()
