"""TPU slice topology model: pod types, hosts, ICI contiguity.

Reference analog: the detection half exists in the reference
(python/ray/_private/accelerators/tpu.py:70-116 — pod-type metadata,
TPU_WORKER_ID, "TPU-{pod}-head" resources); the PLACEMENT half does not
(SURVEY §7 hard part 3: "no reference code exists — design from TPU pod
metadata"). Model:

  * A pod type "v5e-32" is a slice of 32 chips over 32/4 = 8 hosts.
  * Every host (node) of a multi-host slice advertises labels
    "tpu-slice-name" (shared), "tpu-worker-id" (its index), "tpu-pod-type".
  * ICI contiguity across hosts is modeled by worker-id adjacency: a
    contiguous run of worker ids is a connected sub-slice (exact for the
    v5e 2D torus's row-major host order along the ring dimension; the
    conservative approximation for 3D v4/v5p tori).

STRICT_PACK placement of a bundle-per-host group must land on a contiguous
run of hosts of ONE slice, or fail — fragmented placements (across slices,
or with holes) would put DCN hops inside what the job believes is ICI.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

# Chips per host by generation (all current TPU hosts expose 4 chips; v5e
# inference hosts can expose 8 — overridable via the pod-type table below).
CHIPS_PER_HOST: Dict[str, int] = {
    "v2": 4, "v3": 4, "v4": 4, "v5e": 4, "v5litepod": 4, "v5p": 4, "v6e": 4,
}

_POD_RE = re.compile(r"^(v\d+[a-z]*|v5litepod)-(\d+)$")


def parse_pod_type(pod_type: str) -> Optional[Tuple[str, int]]:
    """"v5e-32" -> ("v5e", 32); None if unparseable."""
    m = _POD_RE.match(pod_type.strip())
    if not m:
        return None
    return m.group(1), int(m.group(2))


def chips_per_host(pod_type: str) -> int:
    parsed = parse_pod_type(pod_type)
    if parsed is None:
        return 4
    gen, chips = parsed
    per = CHIPS_PER_HOST.get(gen, 4)
    return min(per, chips)


def hosts_in_slice(pod_type: str) -> int:
    parsed = parse_pod_type(pod_type)
    if parsed is None:
        return 1
    _, chips = parsed
    return max(1, chips // chips_per_host(pod_type))


def find_contiguous_hosts(
        nodes: List[dict], n_hosts: int,
        fits) -> Optional[List[Tuple[int, bytes]]]:
    """Choose n_hosts nodes forming a contiguous worker-id run inside ONE
    slice. `nodes`: [{"node_id", "labels", ...}]; `fits(bundle_index,
    node_id) -> bool` checks resources. Returns [(bundle_index, node_id)]
    with bundle i on run position i, or None.

    Prefers the smallest adequate slice (don't burn a v5e-256 on a
    4-host job) and the lowest-index run within it."""
    by_slice: Dict[str, List[Tuple[int, dict]]] = {}
    for n in nodes:
        name = n["labels"].get("tpu-slice-name")
        if not name:
            continue
        try:
            wid = int(n["labels"].get("tpu-worker-id", "0"))
        except ValueError:
            continue
        by_slice.setdefault(name, []).append((wid, n))
    for name, hosts in sorted(by_slice.items(), key=lambda kv: len(kv[1])):
        if len(hosts) < n_hosts:
            continue
        hosts.sort(key=lambda t: t[0])
        wids = [w for w, _ in hosts]
        # Scan every contiguous worker-id window of length n_hosts.
        for start in range(len(hosts) - n_hosts + 1):
            window = hosts[start:start + n_hosts]
            if window[-1][0] - window[0][0] != n_hosts - 1:
                continue  # hole in the run (busy/dead host): not contiguous
            if all(fits(i, window[i][1]["node_id"])
                   for i in range(n_hosts)):
                return [(i, window[i][1]["node_id"]) for i in range(n_hosts)]
    return None


def slice_labels(slice_name: str, pod_type: str, worker_id: int) -> Dict[str, str]:
    """Labels one host of a (possibly multi-host) slice advertises."""
    return {
        "tpu-pod-type": pod_type,
        "tpu-slice-name": slice_name,
        "tpu-worker-id": str(worker_id),
        "tpu-slice": f"{pod_type}-{slice_name}-{worker_id}",  # legacy key
    }
