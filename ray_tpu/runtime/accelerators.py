"""Accelerator manager registry.

Reference analog: python/ray/_private/accelerators/ — an ABC
(accelerator.py) with one manager per vendor (tpu.py:70, nvidia_gpu.py, ...)
resolving detection, visibility-env isolation, and per-node labels. This
build is TPU-first: the TPU manager wraps runtime/resources.py; the GPU
manager detects NVIDIA devices so mixed clusters schedule a "GPU" resource
(compute on GPUs is out of scope — jax here targets TPU/CPU); new vendors
register a subclass.
"""

from __future__ import annotations

import glob
import os
from typing import Dict, List, Optional, Tuple, Type


class AcceleratorManager:
    """One per accelerator family (accelerator.py ABC analog)."""

    # The resource name this manager contributes, e.g. "TPU".
    resource_name: str = ""

    @staticmethod
    def detect_count() -> int:
        """Number of local devices (0 = family absent on this node)."""
        raise NotImplementedError

    @staticmethod
    def node_labels() -> Dict[str, str]:
        """Scheduler-visible labels (topology, slice ids, ...)."""
        return {}

    @staticmethod
    def visibility_env(device_ids: Tuple[int, ...]) -> Dict[str, str]:
        """Env vars isolating a worker to `device_ids`."""
        return {}


class TPUAcceleratorManager(AcceleratorManager):
    resource_name = "TPU"

    @staticmethod
    def detect_count() -> int:
        from ray_tpu.runtime import resources

        return resources.detect_tpu_chips()

    @staticmethod
    def node_labels() -> Dict[str, str]:
        from ray_tpu.runtime import resources

        return resources.tpu_slice_labels()

    @staticmethod
    def visibility_env(device_ids: Tuple[int, ...]) -> Dict[str, str]:
        from ray_tpu.runtime import resources

        return resources.visible_chip_env(device_ids)


class NvidiaGPUAcceleratorManager(AcceleratorManager):
    """Detection + isolation only (nvidia_gpu.py analog): lets mixed
    clusters schedule a "GPU" resource; the compute path stays jax."""

    resource_name = "GPU"

    @staticmethod
    def detect_count() -> int:
        fake = os.environ.get("RAY_TPU_FAKE_GPUS")
        if fake:
            return int(fake)
        visible = os.environ.get("CUDA_VISIBLE_DEVICES")
        if visible is not None:
            return len([d for d in visible.split(",") if d.strip() != ""])
        return len(glob.glob("/dev/nvidia[0-9]*"))

    @staticmethod
    def visibility_env(device_ids: Tuple[int, ...]) -> Dict[str, str]:
        return {"CUDA_VISIBLE_DEVICES": ",".join(map(str, device_ids))}


_MANAGERS: List[Type[AcceleratorManager]] = [
    TPUAcceleratorManager,
    NvidiaGPUAcceleratorManager,
]


def register(manager: Type[AcceleratorManager]) -> None:
    _MANAGERS.append(manager)


def all_managers() -> List[Type[AcceleratorManager]]:
    return list(_MANAGERS)


def get_manager(resource_name: str) -> Optional[Type[AcceleratorManager]]:
    for m in _MANAGERS:
        if m.resource_name == resource_name:
            return m
    return None


def detect_accelerators() -> Dict[str, float]:
    """Every present accelerator family's {resource_name: count}."""
    out: Dict[str, float] = {}
    for m in _MANAGERS:
        n = m.detect_count()
        if n > 0:
            out[m.resource_name] = float(n)
    return out


def accelerator_labels() -> Dict[str, str]:
    labels: Dict[str, str] = {}
    for m in _MANAGERS:
        if m.detect_count() > 0:
            labels.update(m.node_labels())
    return labels
