from ray_tpu.workflow.api import (  # noqa: F401
    cancel,
    get_metadata,
    get_output,
    list_all,
    resume,
    run,
    run_async,
)
from ray_tpu.workflow.events import (  # noqa: F401
    EventListener,
    HTTPEventProvider,
    HTTPListener,
    TimerListener,
    wait_for_event,
)
