"""Durable workflows: run task DAGs with per-step checkpointing and resume.

Reference analog: python/ray/workflow/ (workflow_executor.py,
workflow_state_from_dag.py, storage layer). A workflow is an ordinary
ray_tpu.dag graph of FunctionNode steps; each step's result is persisted to
workflow storage as it completes, so a crashed or cancelled run resumes from
the last finished step instead of recomputing the prefix.

Storage layout (filesystem, one dir per workflow):
    <storage>/<workflow_id>/meta.json           status + DAG topology digest
    <storage>/<workflow_id>/steps/<step_key>    pickled result per step
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import time
from typing import Any, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.dag.node import (
    DAGNode,
    FunctionNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
)

logger = logging.getLogger(__name__)

_DEFAULT_STORAGE = os.environ.get(
    "RAY_TPU_WORKFLOW_STORAGE", os.path.expanduser("~/.ray_tpu/workflows"))


def _storage(storage: Optional[str]) -> str:
    path = storage or _DEFAULT_STORAGE
    os.makedirs(path, exist_ok=True)
    return path


class _Store:
    def __init__(self, root: str, workflow_id: str):
        self.dir = os.path.join(root, workflow_id)
        self.steps_dir = os.path.join(self.dir, "steps")
        os.makedirs(self.steps_dir, exist_ok=True)

    # -- meta --------------------------------------------------------------
    def read_meta(self) -> Optional[dict]:
        try:
            with open(os.path.join(self.dir, "meta.json")) as f:
                return json.load(f)
        except FileNotFoundError:
            return None

    def write_meta(self, meta: dict):
        tmp = os.path.join(self.dir, f"meta.json.{os.getpid()}.tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, os.path.join(self.dir, "meta.json"))

    def set_status(self, status: str, **extra):
        meta = self.read_meta() or {}
        meta.update(status=status, updated_at=time.time(), **extra)
        self.write_meta(meta)

    # -- step results ------------------------------------------------------
    def step_path(self, key: str) -> str:
        return os.path.join(self.steps_dir, key)

    def has_step(self, key: str) -> bool:
        return os.path.exists(self.step_path(key))

    def save_step(self, key: str, value: Any):
        tmp = self.step_path(key) + f".{os.getpid()}.tmp"
        with open(tmp, "wb") as f:
            cloudpickle.dump(value, f)
        os.replace(tmp, self.step_path(key))

    def load_step(self, key: str) -> Any:
        with open(self.step_path(key), "rb") as f:
            return cloudpickle.load(f)


def _step_key(node: DAGNode, index: int) -> str:
    """Stable per-step key: topo index + function name (topology-addressed,
    like the reference's workflow_state step ids)."""
    from ray_tpu.workflow.events import EventNode

    name = "output"
    if isinstance(node, FunctionNode):
        name = getattr(node.remote_fn, "__name__", "step")
    elif isinstance(node, EventNode):
        name = f"event_{node.listener_cls.__name__}"
    return f"{index:04d}_{name}"


def _dag_digest(nodes: List[DAGNode]) -> str:
    parts = []
    for i, n in enumerate(nodes):
        parts.append(f"{i}:{type(n).__name__}:{_step_key(n, i)}")
    return hashlib.sha1("|".join(parts).encode()).hexdigest()


class _Execution:
    def __init__(self, dag: DAGNode, store: _Store, args, kwargs):
        self.dag = dag
        self.store = store
        self.args = args
        self.kwargs = kwargs

    def run(self) -> Any:
        from ray_tpu.workflow.events import EventNode

        nodes = self.dag.topo_sort()
        cache: Dict[int, Any] = {}
        for i, node in enumerate(nodes):
            key = _step_key(node, i)
            if isinstance(node, EventNode):
                # External-event step: checkpointed like any step, so a
                # resume replays the stored event instead of re-polling;
                # the listener ack runs only AFTER the durable write
                # (commit-then-confirm, reference http_event_provider.py).
                if self.store.has_step(key):
                    event = self.store.load_step(key)
                    # Re-ack on restore: the previous run may have died
                    # between the durable write and the ack, leaving the
                    # provider holding the sender's POST. poll is skipped
                    # (exactly-once), the confirm is at-least-once.
                    try:
                        replay = node.listener_cls()
                        replay.wait_args = node.listener_args
                        replay.wait_kwargs = node.listener_kwargs
                        replay.event_checkpointed(event)
                    except Exception:
                        logger.exception(
                            "workflow: event %s re-ack failed", key)
                    cache[node.node_id] = event
                    logger.info("workflow: event %s restored from storage",
                                key)
                    continue
                listener = node.listener_cls()
                listener.wait_args = node.listener_args
                listener.wait_kwargs = node.listener_kwargs
                event = listener.poll_for_event(*node.listener_args,
                                                **node.listener_kwargs)
                self.store.save_step(key, event)
                listener.event_checkpointed(event)
                cache[node.node_id] = event
            elif isinstance(node, FunctionNode):
                if self.store.has_step(key):
                    cache[node.node_id] = self.store.load_step(key)
                    logger.info("workflow: step %s restored from storage", key)
                    continue
                resolved_args = [self._resolve(a, cache) for a in node.args]
                resolved_kwargs = {k: self._resolve(v, cache)
                                   for k, v in node.kwargs.items()}
                ref = node.remote_fn.remote(*resolved_args, **resolved_kwargs)
                value = ray_tpu.get(ref)
                self.store.save_step(key, value)
                cache[node.node_id] = value
            elif isinstance(node, InputAttributeNode):
                k = node.key
                cache[node.node_id] = (self.kwargs[k] if isinstance(k, str)
                                       else self.args[k])
            elif isinstance(node, InputNode):
                cache[node.node_id] = (self.args[0] if len(self.args) == 1
                                       and not self.kwargs
                                       else (self.args, self.kwargs))
            elif isinstance(node, MultiOutputNode):
                cache[node.node_id] = [self._resolve(o, cache)
                                       for o in node.outputs]
        return cache[nodes[-1].node_id]

    def _resolve(self, x, cache):
        if isinstance(x, DAGNode):
            return cache[x.node_id]
        if isinstance(x, (list, tuple)):
            return type(x)(self._resolve(v, cache) for v in x)
        if isinstance(x, dict):
            return {k: self._resolve(v, cache) for k, v in x.items()}
        return x


def _canonical(x) -> str:
    """Process-stable repr for input fingerprinting: cloudpickle bytes and
    set/dict iteration order vary across interpreters (PYTHONHASHSEED), so a
    raw pickle digest would spuriously reject legitimate resumes."""
    if isinstance(x, dict):
        items = sorted(((_canonical(k), _canonical(v)) for k, v in x.items()))
        return "{" + ",".join(f"{k}:{v}" for k, v in items) + "}"
    if isinstance(x, (set, frozenset)):
        return "{" + ",".join(sorted(_canonical(v) for v in x)) + "}"
    if isinstance(x, (list, tuple)):
        return "[" + ",".join(_canonical(v) for v in x) + "]"
    if callable(x):
        return f"fn:{getattr(x, '__module__', '')}.{getattr(x, '__qualname__', repr(x))}"
    if isinstance(x, (str, bytes, int, float, bool, type(None))):
        return repr(x)
    try:
        import numpy as np

        if isinstance(x, np.ndarray):
            return f"nd:{x.shape}:{x.dtype}:{hashlib.sha1(np.ascontiguousarray(x).tobytes()).hexdigest()}"
    except Exception:
        pass
    r = repr(x)
    if " at 0x" in r:  # default object repr embeds the address: not stable
        raise ValueError(f"cannot fingerprint {type(x).__name__}")
    return r


def _args_digest(args, kwargs) -> Optional[str]:
    try:
        return hashlib.sha1(_canonical(
            (tuple(args), dict(kwargs or {}))).encode()).hexdigest()
    except Exception:
        return None  # un-fingerprintable args: skip the guard


def run(dag: DAGNode, *, workflow_id: Optional[str] = None,
        storage: Optional[str] = None, args: tuple = (),
        kwargs: Optional[dict] = None) -> Any:
    """Execute a task DAG durably; returns the final output."""
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    workflow_id = workflow_id or f"workflow-{int(time.time())}-{os.getpid()}"
    root = _storage(storage)
    store = _Store(root, workflow_id)
    nodes = dag.topo_sort()
    meta = store.read_meta()
    digest = _dag_digest(nodes)
    args_digest = _args_digest(args, kwargs)
    if meta and meta.get("digest") not in (None, digest):
        raise ValueError(
            f"workflow {workflow_id} already exists with a different DAG")
    if (meta and args_digest is not None
            and meta.get("args_digest") not in (None, args_digest)):
        raise ValueError(
            f"workflow {workflow_id} already exists with different inputs; "
            f"resuming it would return results computed from the old args. "
            f"Use a new workflow_id (or workflow.resume() to continue the "
            f"original inputs).")
    store.write_meta({"workflow_id": workflow_id, "digest": digest,
                      "args_digest": args_digest,
                      "status": "RUNNING", "created_at": time.time(),
                      "updated_at": time.time()})
    try:
        result = _Execution(dag, store, args, kwargs or {}).run()
    except KeyboardInterrupt:
        store.set_status("CANCELED")
        raise
    except Exception as e:
        store.set_status("FAILED", error=repr(e))
        raise
    store.save_step("__output__", result)
    store.set_status("SUCCESSFUL")
    return result


def run_async(dag: DAGNode, **kw):
    """Run a workflow in a detached driver thread; returns the workflow_id."""
    import threading

    workflow_id = kw.setdefault(
        "workflow_id", f"workflow-{int(time.time())}-{os.getpid()}")
    t = threading.Thread(target=lambda: _swallow(run, dag, **kw), daemon=True,
                         name=f"workflow-{workflow_id}")
    t.start()
    return workflow_id


def _swallow(fn, *a, **kw):
    try:
        fn(*a, **kw)
    except Exception:
        logger.exception("async workflow failed")


def resume(workflow_id: str, dag: DAGNode, *, storage: Optional[str] = None,
           args: tuple = (), kwargs: Optional[dict] = None) -> Any:
    """Resume a failed/cancelled workflow: completed steps are restored from
    storage, the rest re-execute. The caller re-supplies the DAG (code is not
    persisted — same contract as re-registering workflow defs on recovery)."""
    root = _storage(storage)
    store = _Store(root, workflow_id)
    meta = store.read_meta()
    if meta is None:
        raise ValueError(f"no workflow {workflow_id!r} in {root}")
    if meta.get("status") == "SUCCESSFUL" and store.has_step("__output__"):
        return store.load_step("__output__")
    return run(dag, workflow_id=workflow_id, storage=storage,
               args=args, kwargs=kwargs)


def get_output(workflow_id: str, *, storage: Optional[str] = None) -> Any:
    store = _Store(_storage(storage), workflow_id)
    if not store.has_step("__output__"):
        raise ValueError(f"workflow {workflow_id} has no output "
                         f"(status={get_metadata(workflow_id, storage=storage).get('status')})")
    return store.load_step("__output__")


def get_metadata(workflow_id: str, *, storage: Optional[str] = None) -> dict:
    meta = _Store(_storage(storage), workflow_id).read_meta()
    if meta is None:
        raise ValueError(f"no workflow {workflow_id!r}")
    return meta


def list_all(*, storage: Optional[str] = None) -> List[dict]:
    root = _storage(storage)
    out = []
    for wid in sorted(os.listdir(root)):
        meta = _Store(root, wid).read_meta()
        if meta:
            out.append(meta)
    return out


def cancel(workflow_id: str, *, storage: Optional[str] = None):
    _Store(_storage(storage), workflow_id).set_status("CANCELED")
