"""Workflow event system: durable external-event steps.

Reference analog: python/ray/workflow/event_listener.py (EventListener,
TimerListener) and python/ray/workflow/http_event_provider.py
(HTTPEventProvider named actor + HTTPListener). Redesigned for this
engine: an event is just a workflow STEP whose value comes from the
outside world — the engine checkpoints the received event through the
same per-step storage as any other step (exactly-once: a resumed workflow
replays the checkpointed event instead of re-polling), then acks the
listener (`event_checkpointed`) so the provider can confirm delivery.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Optional, Tuple

from ray_tpu.dag.node import DAGNode


class EventListener:
    """Subclass and pass to wait_for_event. poll_for_event blocks until the
    event arrives and returns its payload; event_checkpointed runs AFTER
    the engine has durably stored the event (commit ack).

    The engine sets `wait_args`/`wait_kwargs` (the wait_for_event
    arguments) on every instance before calling either method — on resume,
    event_checkpointed may run on a FRESH instance whose poll was skipped
    (the event replays from storage), so ack logic must key off wait_args,
    not poll-time state."""

    wait_args: Tuple = ()
    wait_kwargs: dict = {}

    def poll_for_event(self, *args, **kwargs) -> Any:
        raise NotImplementedError

    def event_checkpointed(self, event: Any) -> None:
        pass


class TimerListener(EventListener):
    """Fires at an absolute unix timestamp (reference: TimerListener)."""

    def poll_for_event(self, timestamp: float) -> float:
        time.sleep(max(0.0, timestamp - time.time()))
        return timestamp


class EventNode(DAGNode):
    """A DAG node whose value is an external event. Executed by the
    workflow engine in-driver: listeners keep local state and the ack must
    happen after the engine's checkpoint write."""

    def __init__(self, listener_cls, args: Tuple, kwargs: dict):
        super().__init__((), {})
        self.listener_cls = listener_cls
        self.listener_args = tuple(args)
        self.listener_kwargs = dict(kwargs or {})

    def _eval(self, cache, args, kwargs):  # uncompiled dag.execute() path
        listener = self.listener_cls()
        listener.wait_args = self.listener_args
        listener.wait_kwargs = self.listener_kwargs
        event = listener.poll_for_event(*self.listener_args,
                                        **self.listener_kwargs)
        listener.event_checkpointed(event)
        return event


def wait_for_event(listener_cls, *args, **kwargs) -> EventNode:
    """DAG node that waits for an external event (reference:
    workflow.wait_for_event). The event payload becomes the node's value;
    downstream steps consume it like any task result."""
    if not (isinstance(listener_cls, type)
            and issubclass(listener_cls, EventListener)):
        raise TypeError("wait_for_event needs an EventListener subclass")

    def has_node(x):
        if isinstance(x, DAGNode):
            return True
        if isinstance(x, (list, tuple)):
            return any(has_node(v) for v in x)
        if isinstance(x, dict):
            return any(has_node(v) for v in x.values())
        return False

    if has_node(args) or has_node(kwargs):
        # The engine passes listener args verbatim (an event step has no
        # upstream deps); a DAG node here would reach poll_for_event raw.
        raise TypeError(
            "wait_for_event arguments must be plain values, not DAG nodes")
    return EventNode(listener_cls, args, kwargs)


# --------------------------------------------------------- HTTP provider

class HTTPEventProvider:
    """A small HTTP endpoint external systems POST events to
    (reference: http_event_provider.py's named-actor aiohttp server;
    ours is a threaded stdlib server — no event-loop coupling).

        provider = HTTPEventProvider()          # .address -> (host, port)
        POST http://host:port/event/send_event/<workflow_id>
             {"event_key": k, "event_payload": p}    -> 200 after delivery

    The POST response is held until the workflow checkpoints the event
    (event_checkpointed ack) or times out — at-least-once from the
    sender's view, exactly-once in the workflow via step storage."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 ack_timeout_s: float = 60.0):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self._events = {}       # (workflow_id, event_key) -> payload
        self._acked = set()
        self._cv = threading.Condition()
        ack_timeout = ack_timeout_s
        provider = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                parts = self.path.strip("/").split("/")
                # event/send_event/<workflow_id>
                if len(parts) != 3 or parts[:2] != ["event", "send_event"]:
                    self.send_response(404)
                    self.end_headers()
                    return
                workflow_id = parts[2]
                n = int(self.headers.get("Content-Length", 0))
                try:
                    req = json.loads(self.rfile.read(n))
                    key = req["event_key"]
                    payload = req["event_payload"]
                except Exception:
                    self.send_response(400)
                    self.end_headers()
                    return
                with provider._cv:
                    provider._events[(workflow_id, key)] = payload
                    provider._cv.notify_all()
                    ok = provider._cv.wait_for(
                        lambda: (workflow_id, key) in provider._acked,
                        timeout=ack_timeout)
                body = json.dumps(
                    {"status": "delivered" if ok else "timeout"}).encode()
                self.send_response(200 if ok else 500)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._srv = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True, name="workflow-events-http")
        self._thread.start()

    @property
    def address(self) -> Tuple[str, int]:
        return self._srv.server_address[:2]

    # Listener-facing API ---------------------------------------------------
    def get_event(self, workflow_id: str, event_key: str,
                  timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while (workflow_id, event_key) not in self._events:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        f"no event {event_key!r} for {workflow_id!r}")
                self._cv.wait(timeout=remaining)
            return self._events[(workflow_id, event_key)]

    def report_checkpointed(self, workflow_id: str, event_key: str) -> None:
        with self._cv:
            self._acked.add((workflow_id, event_key))
            self._cv.notify_all()

    def shutdown(self):
        self._srv.shutdown()
        self._thread.join(timeout=5)


class HTTPListener(EventListener):
    """Listens for events delivered to an HTTPEventProvider in this
    process (reference: HTTPListener polling the named provider actor)."""

    provider: Optional[HTTPEventProvider] = None  # set by tests/apps

    def poll_for_event(self, workflow_id: str, event_key: str,
                       timeout: Optional[float] = None) -> Any:
        if self.provider is None:
            raise RuntimeError("HTTPListener.provider is not set")
        return self.provider.get_event(workflow_id, event_key, timeout)

    def event_checkpointed(self, event: Any) -> None:
        # Keyed off wait_args (not poll state): on resume this runs on a
        # fresh instance to re-confirm a held/re-sent POST.
        if self.provider is not None and len(self.wait_args) >= 2:
            self.provider.report_checkpointed(*self.wait_args[:2])
