"""LLM engine: continuous batching over the paged-KV model runner.

Reference analog: the vLLM engine the reference wraps (SURVEY §3.5 hot loop:
"engine continuous-batching step loop (vLLM-internal in reference; Pallas
paged-attention engine in the TPU build)"). Components:

  * BlockManager — host-side page allocator for the KV pool (free list,
    per-sequence block tables, OOM preemption by recompute).
  * Scheduler — admission: waiting requests join the running batch when KV
    pages are available; prefill happens on admission, decode runs batched
    every step.
  * LLMEngine — add_request / step / generate; step() = (maybe prefills) +
    one batched decode + sampling + finish detection.
"""

from __future__ import annotations

import dataclasses
import uuid
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from ray_tpu.llm.sampling import SamplingParams, sample


@dataclasses.dataclass
class RequestOutput:
    request_id: str
    prompt_token_ids: List[int]
    output_token_ids: List[int]
    finished: bool
    finish_reason: Optional[str] = None
    text: Optional[str] = None


class _Request:
    def __init__(self, request_id: str, prompt: List[int],
                 params: SamplingParams):
        self.id = request_id
        self.prompt = list(prompt)
        self.params = params
        self.output: List[int] = []
        self.blocks: List[int] = []
        self.finished_reason: Optional[str] = None

    @property
    def num_tokens(self) -> int:
        return len(self.prompt) + len(self.output)


class BlockManager:
    def __init__(self, num_blocks: int, block_size: int):
        self.block_size = block_size
        self.free: deque = deque(range(num_blocks))

    def blocks_needed(self, num_tokens: int) -> int:
        return (num_tokens + self.block_size - 1) // self.block_size

    def can_allocate(self, num_tokens: int) -> bool:
        return len(self.free) >= self.blocks_needed(num_tokens)

    def allocate(self, req: _Request, num_tokens: int) -> bool:
        need = self.blocks_needed(num_tokens) - len(req.blocks)
        if need > len(self.free):
            return False
        for _ in range(max(0, need)):
            req.blocks.append(self.free.popleft())
        return True

    def release(self, req: _Request):
        self.free.extend(req.blocks)
        req.blocks = []


class LLMEngine:
    def __init__(self, model_runner, *, max_batch_size: int = 8,
                 max_blocks_per_seq: Optional[int] = None,
                 tokenizer=None):
        self.runner = model_runner
        self.block_size = model_runner.block_size
        self.block_manager = BlockManager(model_runner.num_blocks,
                                          model_runner.block_size)
        self.max_batch = max_batch_size
        self.max_blocks_per_seq = max_blocks_per_seq or (
            model_runner.config.max_seq // model_runner.block_size)
        self.tokenizer = tokenizer
        self.waiting: deque = deque()
        self.running: List[_Request] = []
        self.finished_outputs: List[RequestOutput] = []

    # ---- API -------------------------------------------------------------

    def add_request(self, prompt_token_ids: Sequence[int],
                    params: Optional[SamplingParams] = None,
                    request_id: Optional[str] = None) -> str:
        rid = request_id or uuid.uuid4().hex[:12]
        self.waiting.append(_Request(rid, list(prompt_token_ids),
                                     params or SamplingParams()))
        return rid

    def has_unfinished(self) -> bool:
        return bool(self.waiting or self.running)

    def step(self) -> List[RequestOutput]:
        """One engine iteration: admit+prefill, batched decode, sample."""
        self._admit()
        outputs: List[RequestOutput] = []
        if self.finished_outputs:
            # Requests that finished during admission (stop token / length on
            # the very first sampled token).
            outputs.extend(self.finished_outputs)
            self.finished_outputs.clear()
        if not self.running:
            return outputs
        logits = self._decode_batch()
        finished: List[_Request] = []
        for i, req in enumerate(self.running):
            token = sample(logits[i], req.params,
                           np.asarray(req.prompt + req.output))
            req.output.append(int(token))
            if self._is_finished(req):
                finished.append(req)
                outputs.append(RequestOutput(
                    req.id, req.prompt, req.output, True, req.finished_reason,
                    self._detok(req.output)))
        for req in finished:
            self.running.remove(req)
            self.block_manager.release(req)
        return outputs

    def generate(self, prompts: List[Sequence[int]],
                 params: Optional[SamplingParams] = None,
                 ) -> List[RequestOutput]:
        ids = [self.add_request(p, params) for p in prompts]
        collected: Dict[str, RequestOutput] = {}
        while self.has_unfinished():
            for out in self.step():
                collected[out.request_id] = out
        return [collected[i] for i in ids]

    # ---- internals -------------------------------------------------------

    def _admit(self):
        """Move waiting requests into the running batch while KV pages and
        batch slots allow; prefill each admitted prompt."""
        import jax.numpy as jnp

        while self.waiting and len(self.running) < self.max_batch:
            req = self.waiting[0]
            # Reserve room for the prompt plus at least one generated token.
            if not self.block_manager.can_allocate(req.num_tokens + 1):
                break
            self.waiting.popleft()
            assert self.block_manager.allocate(req, req.num_tokens + 1)
            table = self._block_table(req)
            logits = self.runner.prefill(
                jnp.asarray([req.prompt], dtype=jnp.int32), table)
            token = sample(np.asarray(logits[0]), req.params,
                           np.asarray(req.prompt))
            req.output.append(int(token))
            if self._is_finished(req):
                self.block_manager.release(req)
                self.finished_outputs.append(RequestOutput(
                    req.id, req.prompt, req.output, True, req.finished_reason,
                    self._detok(req.output)))
            else:
                self.running.append(req)

    def _decode_batch(self) -> np.ndarray:
        import jax.numpy as jnp

        # Ensure every request has a page for its next token.
        for req in self.running:
            if not self.block_manager.allocate(req, req.num_tokens + 1):
                # Preempt the newest request (recompute later) to free pages.
                victim = self.running[-1]
                self.block_manager.release(victim)
                victim.output = []
                self.running.remove(victim)
                self.waiting.appendleft(victim)
                if req is victim:
                    continue
                assert self.block_manager.allocate(req, req.num_tokens + 1)
        b = len(self.running)
        tokens = jnp.asarray([r.output[-1] for r in self.running], dtype=jnp.int32)
        positions = jnp.asarray([r.num_tokens - 1 for r in self.running],
                                dtype=jnp.int32)
        seq_lens = jnp.asarray([r.num_tokens for r in self.running],
                               dtype=jnp.int32)
        tables = jnp.concatenate([self._block_table(r)[None] for r in self.running])
        logits = self.runner.decode(tokens, tables, positions, seq_lens)
        return np.asarray(logits)

    def _block_table(self, req: _Request):
        import jax.numpy as jnp

        table = np.zeros(self.max_blocks_per_seq, dtype=np.int32)
        table[:len(req.blocks)] = req.blocks
        return jnp.asarray(table)

    def _is_finished(self, req: _Request) -> bool:
        p = req.params
        if p.stop_token_ids and req.output[-1] in p.stop_token_ids:
            req.finished_reason = "stop"
            return True
        if len(req.output) >= p.max_tokens:
            req.finished_reason = "length"
            return True
        if req.num_tokens >= self.runner.config.max_seq:
            req.finished_reason = "length"
            return True
        return False

    def _detok(self, token_ids: List[int]) -> Optional[str]:
        if self.tokenizer is None:
            return None
        try:
            return self.tokenizer.decode(token_ids)
        except Exception:
            return None
