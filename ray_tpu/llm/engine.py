"""LLM engine: continuous batching over the paged-KV model runner.

Reference analog: the vLLM engine the reference wraps (SURVEY §3.5 hot loop:
"engine continuous-batching step loop (vLLM-internal in reference; Pallas
paged-attention engine in the TPU build)"). Components:

  * BlockManager — host-side page allocator for the KV pool (free list,
    per-sequence block tables, OOM preemption by recompute).
  * LLMEngine — add_request / step / generate / stream. step() runs chunked
    prefill for admitted sequences (batched, bucketed) and one batched
    decode, and emits a RequestOutput PER SAMPLED TOKEN, so callers can
    stream tokens before requests finish (the ReportGeneratorItemReturns
    path vLLM uses, core_worker.proto:462, maps to our streaming actors).

Scheduling: admission reserves pages for the whole prompt + 1 token, so
prefill never stalls mid-prompt; decode preemption (pages exhausted) evicts
the newest sequence and re-admits it later by recomputing prompt+generated
tokens (already-emitted tokens are preserved — vLLM's recompute preemption).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import time
import uuid
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ray_tpu.llm.sampling import SamplingParams, sample

# Per-process key for the prefix-cache digest chain: unpredictable to
# clients, so cache addresses can't be forged across tenants.
_PREFIX_CACHE_SALT = os.urandom(16)


def prefix_digest_chain(prompt: Sequence[int], block_size: int, *,
                        salt: Optional[bytes] = None,
                        seed: bytes = b"") -> List[bytes]:
    """Keyed rolling digest per FULL block of `prompt` (position-and-content
    chain, so identical blocks at different depths never collide).

    blake2b keyed with a random salt, NOT builtin hash(): hash(int)==int is
    attacker-predictable, letting a multi-tenant client construct a block
    whose chain value collides with another user's cached block — silent
    cross-request KV reuse (the vLLM prefix-cache collision vulnerability).

    `salt` defaults to the per-process engine salt (BlockManager's cache
    addresses); the serving router (llm/router.py) passes its OWN salt and
    keeps a router-local chain->replica map — per-process salts mean replica
    digests are deliberately NOT comparable across processes. `seed` mixes
    extra context into the chain root (the engine seeds with the LoRA slot;
    the router with the adapter name)."""
    out: List[bytes] = []
    h = b"prefix-chain"
    bs = block_size
    key = _PREFIX_CACHE_SALT if salt is None else salt
    n_blocks = len(prompt) // bs
    if n_blocks == 0:
        return out
    # One vectorized tobytes per block (fixed-width little-endian i64),
    # not per-token int.to_bytes: this runs at every admission on the
    # prefill scheduling path (and per routed request in the router).
    flat = np.asarray(prompt[:n_blocks * bs], dtype="<i8")
    for i in range(n_blocks):
        m = hashlib.blake2b(key=key, digest_size=16)
        m.update(h)
        m.update(seed)
        m.update(flat[i * bs:(i + 1) * bs].tobytes())
        h = m.digest()
        out.append(h)
    return out


@dataclasses.dataclass
class RequestOutput:
    request_id: str
    prompt_token_ids: List[int]
    output_token_ids: List[int]
    finished: bool
    finish_reason: Optional[str] = None
    text: Optional[str] = None
    new_token_ids: List[int] = dataclasses.field(default_factory=list)


class _Request:
    def __init__(self, request_id: str, prompt: List[int],
                 params: SamplingParams, lora_slot: int = 0):
        self.id = request_id
        self.prompt = list(prompt)
        self.params = params
        self.lora_slot = lora_slot    # 0 = base model (llm/lora.py)
        self.output: List[int] = []
        self.blocks: List[int] = []
        self.prefilled = 0          # context tokens already run through
        self.dispatched = 0         # device-sampled tokens not yet fetched
        import zlib

        self.seed_val = (params.seed if params.seed is not None
                         else zlib.crc32(request_id.encode()) & 0x7FFFFFFF)
        self.finished_reason: Optional[str] = None
        self.lora_pinned = lora_slot != 0   # released once on finish
        self.prefix_hashes: Optional[List[bytes]] = None  # lazy, per prompt
        self.registered_blocks = 0  # prompt blocks made cache-addressable
        # Lifecycle timestamps (wall clock, so they compare across replicas)
        # for the TTFT/ITL decomposition. The dict travels INSIDE the
        # export_request/export_session state, so queue/prefill time spent
        # on a prefill replica stays attributed after a disagg handoff or a
        # live migration; handoff_s/pause_s accumulate the off-engine gaps.
        self.timing: Dict[str, Optional[float]] = {
            "t_submit": time.time(), "t_admit": None,
            "t_first_token": None, "t_last_token": None,
            "handoff_s": 0.0, "pause_s": 0.0}
        self.adopted = False   # arrived via KV handoff (prefill elsewhere)

    @property
    def num_tokens(self) -> int:
        return len(self.prompt) + len(self.output)

    @property
    def context(self) -> List[int]:
        """Tokens whose KV must exist before decode continues (prompt plus
        anything generated before a preemption)."""
        return self.prompt + self.output


class BlockManager:
    """Paged-KV allocator with automatic prefix caching.

    vLLM analog (reference: vllm's automatic prefix caching, placed by
    ray.llm at deployments/llm/vllm/): every FULL prompt block registers
    under a keyed rolling digest h_i = blake2b(h_{i-1}, block_tokens);
    a new request reuses the longest cached chain (refcounted, copy-free —
    cached blocks are immutable full blocks, and writes only ever target a
    sequence's own fresh tail blocks), skipping that prefix's prefill
    compute entirely. Freed cached blocks park in an LRU reuse pool and
    are recycled only under allocation pressure, so a hot system prompt
    stays resident."""

    def __init__(self, num_blocks: int, block_size: int,
                 enable_prefix_caching: bool = True):
        from collections import OrderedDict

        self.block_size = block_size
        self.free: deque = deque(range(num_blocks))
        self.caching = enable_prefix_caching
        self.refcount: Dict[int, int] = {}       # live blocks
        self.cached: Dict[bytes, int] = {}       # digest -> block_id
        self.block_hash: Dict[int, bytes] = {}   # block_id -> digest
        self.reusable: "OrderedDict[int, None]" = OrderedDict()  # LRU
        self.prefix_hits = 0
        self.prefix_tokens_saved = 0
        # digest -> (lora_slot, lora_name, root-anchored token prefix
        # through that block): what the host/cluster prefix tiers
        # (llm/prefix_store.py) need to re-address and token-verify a block
        # after it leaves this device pool. The adapter NAME is resolved at
        # registration time — while the owning request still pins its slot
        # — because slot numbers are recycled across adapter loads and a
        # spill-time resolution could attribute old KV to a new adapter.
        self.digest_meta: Dict[bytes, Tuple[int, Optional[str],
                                            Tuple[int, ...]]] = {}
        # Hooks installed by LLMEngine.attach_prefix_store: spill_fn is
        # called with (block_id, digest) just before a parked cached block
        # is recycled — the last moment its pages are intact; lora_name_fn
        # maps a pinned slot to its adapter name ("" = base model).
        self.spill_fn = None
        self.lora_name_fn = None

    def _slot_name(self, lora_slot: int) -> Optional[str]:
        if self.lora_name_fn is not None:
            return self.lora_name_fn(lora_slot)
        return "" if lora_slot == 0 else None

    def blocks_needed(self, num_tokens: int) -> int:
        return (num_tokens + self.block_size - 1) // self.block_size

    def _available(self) -> int:
        return len(self.free) + len(self.reusable)

    def can_allocate(self, num_tokens: int) -> bool:
        return self._available() >= self.blocks_needed(num_tokens)

    def _take_free_block(self) -> int:
        if self.free:
            return self.free.popleft()
        # Evict the least-recently-used parked cached block — spilling it
        # to the host prefix tier first (best-effort) while its pages are
        # still unwritten.
        bid, _ = self.reusable.popitem(last=False)
        h = self.block_hash.pop(bid)
        if self.spill_fn is not None:
            self.spill_fn(bid, h)
        self.cached.pop(h, None)
        self.digest_meta.pop(h, None)
        return bid

    def allocate(self, req: _Request, num_tokens: int) -> bool:
        need = self.blocks_needed(num_tokens) - len(req.blocks)
        if need > self._available():
            return False
        for _ in range(max(0, need)):
            bid = self._take_free_block()
            self.refcount[bid] = self.refcount.get(bid, 0) + 1
            req.blocks.append(bid)
        return True

    def release(self, req: _Request):
        self.release_blocks(req.blocks)
        req.blocks = []

    def release_blocks(self, blocks: List[int]):
        """THE release path for detached block lists too (deferred release,
        error recovery): anything pushing block ids straight onto .free
        would bypass refcounts and corrupt/leak shared cached blocks."""
        for bid in blocks:
            n = self.refcount.get(bid, 1) - 1
            if n > 0:
                self.refcount[bid] = n
                continue
            self.refcount.pop(bid, None)
            if bid in self.block_hash:
                # Still addressable by content: park for reuse.
                self.reusable[bid] = None
                self.reusable.move_to_end(bid)
            else:
                self.free.append(bid)

    # ---- prefix caching --------------------------------------------------
    def prefix_hashes(self, prompt: Sequence[int],
                      lora_slot: int = 0) -> List[bytes]:
        """Digest chain for this manager's cache addresses (module-level
        prefix_digest_chain under the per-process salt). The chain is seeded
        with the LoRA slot: adapters change wk/wv (llm/lora.py TARGETS), so
        KV content differs per adapter and cross-adapter sharing would be
        silently wrong."""
        slot = int(lora_slot).to_bytes(8, "little", signed=True)
        return prefix_digest_chain(prompt, self.block_size, seed=slot)

    def match_prefix(self, req: _Request, hashes: List[bytes]) -> int:
        """Attach the longest cached chain to req; returns tokens skipped.
        The prompt's final token is ALWAYS recomputed (its logits seed the
        first sampled token), capping reuse at (len(prompt)-1)//bs blocks."""
        if not self.caching:
            return 0
        limit = min(len(hashes), (len(req.prompt) - 1) // self.block_size)
        skipped = 0
        for i in range(limit):
            bid = self.cached.get(hashes[i])
            if bid is None:
                break
            if self.refcount.get(bid, 0) == 0:
                self.reusable.pop(bid, None)
            self.refcount[bid] = self.refcount.get(bid, 0) + 1
            req.blocks.append(bid)
            skipped += self.block_size
        if skipped:
            self.prefix_hits += 1
            self.prefix_tokens_saved += skipped
        return skipped

    def register_block(self, req: _Request, index: int, h: bytes):
        """A full prompt block finished prefilling: make it addressable.
        First writer wins; a duplicate stays private to its sequence."""
        if not self.caching:
            return
        bid = req.blocks[index]
        if bid in self.block_hash or h in self.cached:
            return
        self.cached[h] = bid
        self.block_hash[bid] = h
        self.digest_meta[h] = (
            req.lora_slot, self._slot_name(req.lora_slot),
            tuple(req.prompt[:(index + 1) * self.block_size]))

    def register_adopted_block(self, bid: int, h: bytes, lora_slot: int,
                               tokens: Sequence[int]) -> bool:
        """Make a block adopted from the prefix store addressable under
        digest `h` (the adopter already holds a refcount on `bid`). First
        writer wins, like register_block."""
        if not self.caching or h in self.cached or bid in self.block_hash:
            return False
        self.cached[h] = bid
        self.block_hash[bid] = h
        self.digest_meta[h] = (int(lora_slot), self._slot_name(lora_slot),
                               tuple(tokens))
        return True

    def invalidate_prefix_cache(self) -> int:
        """Drop EVERY cached prefix mapping: cached KV was computed under
        the previous weights, so after a weight hot-swap a prefix hit would
        silently decode against stale activations. Parked reusable blocks
        return to the free pool outright; blocks still referenced by live
        sequences merely lose content-addressability (their normal release
        now routes to `free` since their hash entry is gone). Returns the
        number of cache entries dropped."""
        n = len(self.cached)
        self.cached.clear()
        self.block_hash.clear()
        self.digest_meta.clear()
        while self.reusable:
            bid, _ = self.reusable.popitem(last=False)
            self.free.append(bid)
        return n

    # ---- disaggregated handoff (llm/disagg.py) ---------------------------

    def adopt_blocks(self, n: int) -> Optional[List[int]]:
        """Allocate `n` fresh private pages for KV adopted from another
        replica (prefill->decode handoff). Refcounted like any allocation so
        the normal release path applies; returns None when the pool cannot
        fit them (the caller rejects the handoff, nothing partial sticks)."""
        if self._available() < n:
            return None
        out: List[int] = []
        for _ in range(n):
            bid = self._take_free_block()
            self.refcount[bid] = self.refcount.get(bid, 0) + 1
            out.append(bid)
        return out


class LLMEngine:
    def __init__(self, model_runner, *, max_batch_size: int = 8,
                 max_blocks_per_seq: Optional[int] = None,
                 tokenizer=None, prefill_chunk: Optional[int] = None,
                 pipeline_depth: Optional[int] = None,
                 enable_prefix_caching: bool = True,
                 speculative_ngram: int = 0,
                 decode_multi_step: int = 1,
                 prefill_only: bool = False,
                 unified_ticks: bool = True,
                 token_budget: Optional[int] = None):
        self.runner = model_runner
        self.block_size = model_runner.block_size
        self.block_manager = BlockManager(
            model_runner.num_blocks, model_runner.block_size,
            enable_prefix_caching=enable_prefix_caching)
        self.max_batch = max_batch_size
        self.max_blocks_per_seq = max_blocks_per_seq or min(
            model_runner.max_blocks_per_seq,
            model_runner.config.max_seq // model_runner.block_size)
        # Hard length cap: a sequence may never outgrow its block-table row.
        self._cap_tokens = min(model_runner.config.max_seq,
                               self.max_blocks_per_seq * self.block_size)
        self.tokenizer = tokenizer
        self.prefill_chunk = prefill_chunk or getattr(
            model_runner, "chunk_size", 128)
        self.waiting: deque = deque()
        self.prefilling: List[_Request] = []
        self.running: List[_Request] = []
        self._rejected: List[RequestOutput] = []
        # Async decode pipeline: up to pipeline_depth steps stay in flight,
        # each chaining its token input from the previous step ON DEVICE;
        # device->host copies start at dispatch (copy_to_host_async) and are
        # consumed pipeline_depth ticks later, so the transfer round-trip —
        # dominant on remote-attached accelerators — amortizes across depth
        # steps instead of gating every tick (vLLM's async output
        # processing, deepened).
        from ray_tpu.config import cfg

        self.pipeline_depth = max(1, pipeline_depth
                                  if pipeline_depth is not None
                                  else cfg().llm_pipeline_depth)
        self._flights: deque = deque()
        # (req, detached_blocks): pages an in-flight step may still write.
        # Detached from req.blocks so a re-admitted (preempted) request's
        # fresh allocation is never confused with the stale pages.
        self._pending_release: List[tuple] = []
        # n-gram (prompt-lookup) speculative decoding: propose up to K
        # tokens per step from the sequence's own history, verify in one
        # multi-position step. 0 = off; engages only for all-greedy
        # batches (exact acceptance needs argmax determinism).
        self.spec_ngram = int(speculative_ngram)
        self.spec_tokens_accepted = 0
        self.spec_tokens_proposed = 0
        # Multi-step decode: one dispatch scans k tokens on device (the
        # vLLM multi-step-scheduling analog, done as a lax.scan). The big
        # lever when per-execute dispatch latency (remote TPU relays)
        # rivals per-token compute. A batch uses k = decode_multi_step
        # only when EVERY member has k tokens of page/length headroom —
        # otherwise it falls back to the single-step program (both are
        # precompiled; no mid-stream compiles either way).
        self.multi_step = max(1, int(decode_multi_step))
        # Disaggregated prefill tier (llm/disagg.py): a prefill-only engine
        # never runs a decode tick — sequences that finish prefill (first
        # token sampled) park in `running` until export_request hands them
        # to a decode replica.
        self.prefill_only = bool(prefill_only)
        # Bumped by update_weights (RLHF weight sync); rollout experiences
        # record the version they were sampled under.
        self.weights_version = 0
        # Prefill tokens actually run through the model (cache hits and
        # adopted KV excluded): the "zero re-prefill" proof for session
        # migration — an adopted sequence never adds to this.
        self.prefill_tokens_computed = 0
        # Tiered prefix store (llm/prefix_store.py), attached by the
        # serving layer via attach_prefix_store. Host tier catches device
        # evictions; cluster store makes spilled prefixes adoptable fleet
        # wide. Both optional — a bare engine behaves exactly as before.
        self.host_prefix_tier = None
        self.cluster_store = None
        self.host_prefix_hits = 0
        self.host_prefix_tokens_saved = 0
        self.cluster_prefix_hits = 0
        self.cluster_prefix_tokens_saved = 0
        # Unified ragged ticks: ONE mixed kernel launch per iteration —
        # decode rows (1 token), spec-verify rows (k+1 tokens), and prefill
        # chunk slices share a token-major batch bucketed on TOTAL token
        # count, so a long prompt's chunk no longer stalls every running
        # decode behind a separate rectangular launch. Engages when
        # decode_multi_step == 1 (the on-device k-token scan is its own
        # optimized program) and the engine decodes (prefill-only tiers
        # keep the split path for the disagg handoff discipline).
        self.unified_ticks = bool(unified_ticks)
        self._spec_width = 1 + self.spec_ngram
        # Token budget per unified tick: decode/verify rows are admitted
        # first, the remainder fills from the prefill backlog. Must cover
        # every running row's verify width, and stays a multiple of 8 (the
        # ragged kernel's q_block — token buckets inherit it).
        budget = (int(token_budget) if token_budget else
                  self.prefill_chunk + self.max_batch * self._spec_width)
        budget = max(budget, self.max_batch * self._spec_width, 8)
        self.token_budget = -(-budget // 8) * 8
        self._warm_mixed: set = set()   # token buckets already precompiled
        # Tick flight recorder: bounded ring of per-tick records (batch
        # composition, token budget used, T-bucket, recompile flag, tokens
        # emitted per request) so a slow token is attributable to a CAUSE —
        # budget exhaustion behind a long prefill, a silent recompile, a
        # migration pause — not just visible as a gap. Dict-append per tick,
        # no device sync: cheap enough to stay always-on.
        self.flight_records: deque = deque(
            maxlen=int(os.environ.get("RAY_TPU_LLM_FLIGHT_RECORDS", "256")))
        self._tick_note: Dict = {}

    # ---- API -------------------------------------------------------------

    def add_request(self, prompt_token_ids: Sequence[int],
                    params: Optional[SamplingParams] = None,
                    request_id: Optional[str] = None,
                    lora_name: Optional[str] = None) -> str:
        rid = request_id or uuid.uuid4().hex[:12]
        slot = 0
        if lora_name:
            if self.runner.lora is None:
                raise ValueError(
                    "engine has no LoRA manager; lora_name unsupported")
            slot = self.runner.lora.slot_of(lora_name)  # KeyError if absent
            # Pin until the request finishes: LRU eviction must not hand
            # this slot to another adapter mid-generation.
            self.runner.lora.pin(slot)
        self.waiting.append(_Request(rid, list(prompt_token_ids),
                                     params or SamplingParams(), slot))
        return rid

    def _unpin_lora(self, req: "_Request"):
        if req.lora_pinned:
            req.lora_pinned = False
            self.runner.lora.unpin(req.lora_slot)

    def _lora_idx(self, batch, S) -> Optional[np.ndarray]:
        if self.runner.lora is None:
            return None
        idx = np.zeros(S, dtype=np.int32)
        for i, req in enumerate(batch):
            idx[i] = req.lora_slot
        return idx

    def has_unfinished(self) -> bool:
        return bool(self.waiting or self.prefilling or self.running
                    or self._flights)

    def step(self) -> List[RequestOutput]:
        """One engine iteration: admit, chunked prefill, batched decode.
        Emits a RequestOutput for every request that gained tokens (decode
        emissions trail one tick behind dispatch — async pipeline)."""
        self._admit()
        outputs: List[RequestOutput] = []
        if self._rejected:
            outputs.extend(self._rejected)
            self._rejected.clear()
        t0 = time.time()
        self._tick_note = {}
        if self._use_unified():
            outputs.extend(self._mixed_tick())
        else:
            if self.prefilling:
                outputs.extend(self._prefill_step())
            if not self.prefill_only and (self.running or self._flights):
                outputs.extend(self._decode_tick())
        note = self._tick_note
        if note:
            note["t"] = t0
            note["dur_ms"] = round((time.time() - t0) * 1e3, 3)
            note["waiting"] = len(self.waiting)
            # Per-request token positions emitted this tick: rid -> absolute
            # output position after the tick (gap attribution joins a slow
            # token's position to the tick that produced it).
            note["emitted"] = {o.request_id: len(o.output_token_ids)
                               for o in outputs if o.new_token_ids}
            self.flight_records.append(note)
        return outputs

    def _note(self, **fields):
        """Merge one phase's facts into the current tick record (the split
        path may run prefill AND decode inside one step)."""
        n = self._tick_note
        if "kind" in n and "kind" in fields:
            fields["kind"] = f"{n['kind']}+{fields['kind']}"
        n.update(fields)

    def _use_unified(self) -> bool:
        """Route this iteration through the unified mixed launch. Falls back
        to the split phases when a feature needs them: the multi-step
        on-device scan, prefill-only (disagg) engines, requests needing
        host logits (repetition penalty), or async flights still draining
        from a pre-unified tick."""
        if not (self.unified_ticks and self.multi_step == 1
                and not self.prefill_only):
            return False
        if self._flights:
            return False
        if not (self.prefilling or self.running):
            return False
        return not self._needs_logits(list(self.prefilling) + self.running)

    def generate(self, prompts: List[Sequence[int]],
                 params: Optional[SamplingParams] = None,
                 ) -> List[RequestOutput]:
        ids = [self.add_request(p, params) for p in prompts]
        done: Dict[str, RequestOutput] = {}
        while self.has_unfinished():
            for out in self.step():
                if out.finished:
                    done[out.request_id] = out
        return [done[i] for i in ids]

    def stream(self, prompt_token_ids: Sequence[int],
               params: Optional[SamplingParams] = None):
        """Single-request token stream: yields token ids as they are
        sampled; the engine may be concurrently serving other requests only
        if the caller drives step() elsewhere — this helper drives it."""
        rid = self.add_request(prompt_token_ids, params)
        while True:
            for out in self.step():
                if out.request_id != rid:
                    continue
                for t in out.new_token_ids:
                    yield t
                if out.finished:
                    return
            if not self.has_unfinished():
                return

    def abort_request(self, request_id: str) -> bool:
        """Drop a request wherever it lives and free its pages — the serving
        layer calls this when the client disappears (stream consumer gone,
        wait timeout) so an abandoned request stops burning decode compute
        and KV pages on a dead stream. Pages an in-flight device step may
        still write into are release-deferred until those flights drain
        (the same discipline as preemption). Returns False when the id is
        unknown (already finished/aborted)."""
        for i, req in enumerate(self.waiting):
            if req.id == request_id:
                del self.waiting[i]
                req.finished_reason = "abort"
                self._unpin_lora(req)
                self._defer_release(req)
                return True
        for queue_ in (self.prefilling, self.running):
            for req in queue_:
                if req.id == request_id:
                    queue_.remove(req)
                    req.finished_reason = "abort"
                    self._unpin_lora(req)
                    self._defer_release(req)
                    return True
        return False

    def update_weights(self, params, *, version: Optional[int] = None,
                       force: bool = False) -> Dict:
        """Hot-swap the model weights in place (RLHF weight sync).

        Validates the incoming pytree against the loaded model FIRST —
        structure, per-leaf shape, per-leaf dtype — and raises a typed
        `WeightSyncError` on any mismatch, so a malformed sync payload
        surfaces here instead of as a shape error deep inside the next
        prefill. On success the params are re-placed through the runner's
        normal placement path (sharded over the mesh when one exists) and
        the ENTIRE prefix cache is invalidated: cached KV was computed
        under the old weights and a post-swap prefix hit would be silently
        wrong. The jitted step programs close over nothing — params are an
        argument — so an identical-shaped swap triggers no recompiles.

        Refuses (WeightSyncError) while requests are in flight unless
        `force=True`: an in-flight sequence would mix logits from two
        policies mid-generation. Drain or abort first (the RLHF trainer
        syncs between rollout rounds, when the engine is idle).
        """
        import jax

        from ray_tpu.core.exceptions import WeightSyncError

        if self.has_unfinished() and not force:
            raise WeightSyncError(
                "engine has unfinished requests; drain rollouts before "
                "swapping weights (or pass force=True)")
        old_paths, old_def = jax.tree_util.tree_flatten_with_path(
            self.runner.params)
        try:
            new_leaves, new_def = jax.tree.flatten(params)
        except Exception as exc:
            raise WeightSyncError(f"weight payload is not a pytree: {exc}")
        if new_def != old_def:
            raise WeightSyncError(
                f"pytree structure mismatch: engine has {old_def}, "
                f"payload has {new_def}")
        for (path, old_leaf), new_leaf in zip(old_paths, new_leaves):
            name = jax.tree_util.keystr(path)
            old_shape = tuple(old_leaf.shape)
            new_shape = tuple(np.shape(new_leaf))
            if old_shape != new_shape:
                raise WeightSyncError(
                    f"shape mismatch at {name}: engine {old_shape}, "
                    f"payload {new_shape}")
            old_dt = np.dtype(old_leaf.dtype)
            new_dt = np.dtype(getattr(new_leaf, "dtype", type(new_leaf)))
            if old_dt != new_dt:
                raise WeightSyncError(
                    f"dtype mismatch at {name}: engine {old_dt}, "
                    f"payload {new_dt}")
        self.runner.params = self.runner._place_params(params)
        invalidated = self.block_manager.invalidate_prefix_cache()
        self.weights_version = (version if version is not None
                                else self.weights_version + 1)
        # Spilled KV is as stale as cached KV after a hot-swap: drop the
        # host tier outright and GC cluster entries below the new version
        # (adoption also gates on exact version match, so a racing peer's
        # lookup can never resurrect pre-swap pages either way).
        if self.host_prefix_tier is not None:
            invalidated += self.host_prefix_tier.clear()
        if self.cluster_store is not None:
            self.cluster_store.purge(
                below_weights_version=self.weights_version)
        return {"version": self.weights_version,
                "invalidated_prefix_entries": invalidated}

    def stats(self) -> Dict:
        """Scheduler/cache load signal for the serving router: queue depths,
        KV pool occupancy, prefix-cache effectiveness, and the queued
        prefill backlog the SLO admission estimator divides by prefill
        throughput. Cheap (no device sync) — safe to poll per request."""
        bm = self.block_manager
        backlog = sum(len(r.context) - r.prefilled for r in self.prefilling)
        backlog += sum(len(r.context) for r in self.waiting)
        out = {
            "waiting": len(self.waiting),
            "prefilling": len(self.prefilling),
            "running": len(self.running),
            "inflight_steps": len(self._flights),
            "free_kv_blocks": bm._available(),
            "total_kv_blocks": self.runner.num_blocks,
            "block_size": self.block_size,
            "prefix_hits": bm.prefix_hits,
            "prefix_tokens_saved": bm.prefix_tokens_saved,
            "prefill_tokens_computed": self.prefill_tokens_computed,
            "queued_prefill_tokens": backlog,
            "weights_version": self.weights_version,
            # Speculation effectiveness (accepted/proposed is the win
            # ratio) + the runner's compile count: steady-state growth of
            # step_compiles flags a silent hot-loop recompile.
            "spec_tokens_proposed": self.spec_tokens_proposed,
            "spec_tokens_accepted": self.spec_tokens_accepted,
            "step_compiles": getattr(self.runner, "step_compiles", 0),
            "unified_ticks": self.unified_ticks,
            "token_budget": self.token_budget,
            "tick_records": len(self.flight_records),
        }
        if self.host_prefix_tier is not None:
            t = self.host_prefix_tier.stats()
            out.update({
                "host_prefix_entries": t["entries"],
                "host_prefix_bytes": t["bytes"],
                "host_prefix_spills": t["spills"],
                "host_prefix_demotions": t["demotions"],
                "host_prefix_hits": self.host_prefix_hits,
                "host_prefix_tokens_saved": self.host_prefix_tokens_saved,
            })
        if self.cluster_store is not None:
            c = self.cluster_store.stats()
            out.update({
                "cluster_prefix_published": c["published"],
                "cluster_prefix_adopted_blocks": c["adopted_blocks"],
                "cluster_prefix_stale_rejected": c["stale_rejected"],
                "cluster_prefix_hits": self.cluster_prefix_hits,
                "cluster_prefix_tokens_saved":
                    self.cluster_prefix_tokens_saved,
            })
        lm = self.runner.lora
        if lm is not None:
            out.update({
                "lora_slots": lm.n_slots - 1,
                "lora_loaded": len(getattr(lm, "_slots", {})),
                "lora_pinned": len(getattr(lm, "_pins", {})),
                "lora_loads": getattr(lm, "loads", 0),
                "lora_evictions": getattr(lm, "evictions", 0),
            })
        return out

    def tick_records(self, limit: Optional[int] = None,
                     request_id: Optional[str] = None) -> List[Dict]:
        """Flight-recorder snapshot, newest last. `request_id` filters to
        ticks that emitted tokens for that request (gap attribution for one
        stream); `limit` keeps the newest N after filtering."""
        records = list(self.flight_records)
        if request_id is not None:
            records = [r for r in records
                       if request_id in (r.get("emitted") or {})]
        if limit is not None:
            records = records[-int(limit):]
        return records

    # ---- disaggregated prefill/decode handoff (llm/disagg.py) ------------

    def drain_flights(self) -> List[RequestOutput]:
        """Synchronously harvest every in-flight decode step and release
        deferred pages. After this, no device step can still write into any
        sequence's pages and every request's `dispatched` is 0 — the
        precondition for exporting decode state (session migration). Tokens
        the drained steps sampled commit normally (some requests may finish
        here); the caller fans the returned outputs to its streams."""
        outputs: List[RequestOutput] = []
        while self._flights:
            outputs.extend(self._process_inflight(self._flights.popleft()))
        self._drain_release()
        return outputs

    def export_session(self, request_id: str):
        """Detach a live request wherever it lives for replica->replica
        migration (llm/disagg.py migrate_session). Returns (state, mode):

          * ("kv" mode) the request was decoding — state carries its block
            ids under "blocks" exactly like export_request; the caller
            gathers + streams the pages and the adopter resumes decode with
            zero re-prefill.
          * ("replay" mode) the request had not finished prefill — its
            partial KV is discarded whole (never exported torn) and state
            carries prompt/output/seed only; the importer re-runs from the
            prompt, and seeded sampling makes the retry token-identical.

        (None, None) when the id is unknown (already finished). Call
        drain_flights() first: decode export requires dispatched == 0."""
        for req in self.running:
            if req.id == request_id:
                return self.export_request(request_id), "kv"
        for queue_ in (self.waiting, self.prefilling):
            for req in list(queue_):
                if req.id == request_id:
                    queue_.remove(req)
                    self._unpin_lora(req)
                    self._defer_release(req)
                    return {
                        "id": req.id,
                        "prompt": list(req.prompt),
                        "output": list(req.output),
                        "seed": req.seed_val,
                        "lora_slot": req.lora_slot,
                        "params": dataclasses.asdict(req.params),
                    }, "replay"
        return None, None

    def export_request(self, request_id: str) -> Optional[dict]:
        """Detach a just-prefilled request for handoff to a decode replica.
        Returns the portable request state with its (detached) block ids
        under "blocks"; the caller gathers those pages off the device
        (ModelRunner.gather_pages), streams them, and THEN releases the
        blocks via block_manager.release_blocks — shared cached prefix
        blocks stay addressable for the next prompt sharing them."""
        for req in self.running:
            if req.id == request_id:
                break
        else:
            return None
        if req.dispatched:
            raise RuntimeError(
                f"request {request_id} has in-flight decode steps; call "
                "drain_flights() first (its pages may still be written)")
        self.running.remove(req)
        self._unpin_lora(req)
        blocks, req.blocks = req.blocks, []
        return {
            "id": req.id,
            "prompt": list(req.prompt),
            "output": list(req.output),
            "seed": req.seed_val,
            "lora_slot": req.lora_slot,
            "params": dataclasses.asdict(req.params),
            "blocks": blocks,
            # t_handoff marks when the request left this engine; the adopter
            # books (adopt time - t_handoff) as handoff_s (or pause_s for a
            # migration), so the off-engine gap stays attributed.
            "timing": dict(req.timing, t_handoff=time.time()),
        }

    def adopt_request(self, state: dict, k_pages, v_pages) -> bool:
        """Adopt a prefilled request streamed from another replica: fresh
        private pages, KV scattered in, the sequence enters decode directly.
        Decode is bit-identical to a colocated run because the device
        sampler keys on (seed, absolute position counter) — both carried in
        `state`. Returns False (nothing allocated) when the pool can't fit
        the pages; the sender keeps ownership and the router retries."""
        from ray_tpu.llm.sampling import SamplingParams

        params = SamplingParams(**state["params"])
        req = _Request(state["id"], list(state["prompt"]), params,
                       int(state.get("lora_slot", 0)))
        req.output = [int(t) for t in state["output"]]
        req.seed_val = int(state["seed"])
        req.adopted = True
        timing = state.get("timing")
        if timing:
            for key in ("t_submit", "t_admit", "t_first_token",
                        "t_last_token", "handoff_s", "pause_s"):
                if timing.get(key) is not None:
                    req.timing[key] = timing[key]
            t_handoff = timing.get("t_handoff")
            if t_handoff is not None:
                gap = max(0.0, time.time() - float(t_handoff))
                key = "pause_s" if state.get("migrated") else "handoff_s"
                req.timing[key] = float(req.timing.get(key) or 0.0) + gap
        n_pages = int(np.shape(k_pages)[2])
        if self.block_manager.blocks_needed(len(req.context)) > n_pages:
            # The stream must cover every context token's KV; anything less
            # is a protocol error (torn export), not pressure.
            raise ValueError(
                f"handoff for {req.id} carries {n_pages} pages; "
                f"{self.block_manager.blocks_needed(len(req.context))} "
                "needed")
        if req.lora_slot and self.runner.lora is None:
            raise ValueError(
                "handoff carries a LoRA slot but this replica has no LoRA "
                "manager (disaggregated tiers must preload identical "
                "adapters)")
        # Allocate headroom for the next token too when the stream covered
        # the context exactly (a migrated sequence whose context fills its
        # last block): decode resumes without an immediate allocation.
        total = max(n_pages,
                    self.block_manager.blocks_needed(len(req.context) + 1))
        ids = self.block_manager.adopt_blocks(total)
        if ids is None:
            return False
        if req.lora_pinned:
            self.runner.lora.pin(req.lora_slot)
        req.blocks = ids
        req.prefilled = len(req.context)
        self.runner.scatter_pages(ids[:n_pages], k_pages, v_pages)
        if self.block_manager.caching:
            # Re-register full prompt blocks under THIS replica's digest
            # chain so disaggregation composes with prefix caching: the next
            # prompt sharing the system prefix hits locally.
            req.prefix_hashes = self.block_manager.prefix_hashes(
                req.prompt, req.lora_slot)
            full = min(len(req.prompt) // self.block_size, len(ids))
            while req.registered_blocks < full:
                j = req.registered_blocks
                self.block_manager.register_block(
                    req, j, req.prefix_hashes[j])
                req.registered_blocks += 1
        self.running.append(req)
        return True

    # ---- tiered prefix store (llm/prefix_store.py) -------------------------

    def attach_prefix_store(self, host_tier=None, cluster_store=None):
        """Wire the tiered prefix store in: BlockManager evictions spill
        through `host_tier`, host-tier watermark victims demote into
        `cluster_store`, and _admit promotes from both. Either tier may be
        None (host-only works standalone; cluster-only skips host RAM)."""
        self.host_prefix_tier = host_tier
        self.cluster_store = cluster_store
        self.block_manager.lora_name_fn = self._lora_name
        if host_tier is not None:
            self.block_manager.spill_fn = self._spill_block
            if cluster_store is not None and host_tier.on_demote is None:
                host_tier.on_demote = self._demote_entry

    def _lora_name(self, lora_slot: int) -> Optional[str]:
        """Adapter name for a pinned slot: "" = base model, None = cannot
        resolve (no manager / unknown slot — such KV is unaddressable)."""
        if lora_slot == 0:
            return ""
        lm = self.runner.lora
        name_of = getattr(lm, "name_of", None) if lm is not None else None
        return name_of(lora_slot) if name_of is not None else None

    def _spill_block(self, bid: int, h: bytes) -> None:
        """BlockManager eviction hook: copy the victim block's pages to the
        host tier before the device page is recycled. Best-effort — a
        failed spill is a future cache miss, never an engine error."""
        tier = self.host_prefix_tier
        if tier is None:
            return
        meta = self.block_manager.digest_meta.get(h)
        if meta is None:
            return
        slot, lora_name, tokens = meta
        if lora_name is None:
            return
        try:
            k, v = self.runner.gather_pages([bid])
            k = np.asarray(k)
            v = np.asarray(v)
        except Exception:
            return
        tier.put(h, {"tokens": tokens, "k": k, "v": v, "lora_slot": slot,
                     "lora_name": lora_name,
                     "weights_version": self.weights_version,
                     "nbytes": int(k.nbytes + v.nbytes)})

    def _demote_entry(self, entry: dict) -> None:
        """Host-tier watermark victim -> cluster store (tier 2)."""
        if self.cluster_store is None:
            return
        self.cluster_store.publish(entry)

    def _promote_prefix(self, req: _Request) -> int:
        """Extend req's cached-chain attachment past the device tier: host
        RAM block by block, then ONE cluster-table fetch for the rest of
        the chain. Promoted blocks are scattered into fresh device pages
        and re-registered under the local digest chain, so the next prompt
        sharing them hits the device tier directly. Returns tokens saved."""
        from ray_tpu.util import tracing

        bm = self.block_manager
        bs = self.block_size
        limit = min(len(req.prefix_hashes), (len(req.prompt) - 1) // bs)
        promoted = 0
        t_adopt0 = time.time()
        tier = self.host_prefix_tier
        while tier is not None and len(req.blocks) < limit:
            j = len(req.blocks)
            e = tier.get(req.prefix_hashes[j])
            if (e is None
                    or e.get("weights_version") != self.weights_version
                    or e.get("lora_name") != self._lora_name(req.lora_slot)
                    or tuple(e["tokens"])
                    != tuple(req.prompt[:(j + 1) * bs])):
                break
            ids = bm.adopt_blocks(1)
            if ids is None:
                break
            self.runner.scatter_pages(ids, e["k"], e["v"])
            req.blocks.extend(ids)
            bm.register_adopted_block(ids[0], req.prefix_hashes[j],
                                      req.lora_slot, e["tokens"])
            promoted += bs
            self.host_prefix_hits += 1
            self.host_prefix_tokens_saved += bs
        if self.cluster_store is not None and len(req.blocks) < limit:
            lora_name = self._lora_name(req.lora_slot)
            if lora_name is not None:
                from ray_tpu.llm.prefix_store import cluster_chain

                j0 = len(req.blocks)
                chain = cluster_chain(req.prompt[:limit * bs], bs, lora_name)
                verified = []
                for e in self.cluster_store.lookup_pages(
                        chain[j0:limit], lora_id=lora_name,
                        weights_version=self.weights_version):
                    j = j0 + len(verified)
                    want = [int(t) for t in req.prompt[:(j + 1) * bs]]
                    if [int(t) for t in e["tokens"]] != want:
                        break  # token verification IS the forgery guard
                    verified.append((e, want))
                while verified:  # pool pressure: adopt a shorter prefix
                    ids = bm.adopt_blocks(len(verified))
                    if ids is not None:
                        break
                    verified.pop()
                if verified:
                    # One batched scatter: a per-block device write costs
                    # ~1-2 ms of dispatch each, which is most of the
                    # adopt-vs-reprefill budget for long contexts.
                    self.runner.scatter_pages(
                        ids,
                        np.concatenate([e["k"] for e, _ in verified],
                                       axis=2),
                        np.concatenate([e["v"] for e, _ in verified],
                                       axis=2))
                    for bid, (e, want) in zip(ids, verified):
                        bm.register_adopted_block(
                            bid, req.prefix_hashes[len(req.blocks)],
                            req.lora_slot, want)
                        req.blocks.append(bid)
                        promoted += bs
                        self.cluster_prefix_hits += 1
                        self.cluster_prefix_tokens_saved += bs
        if promoted and tracing.enabled():
            # Stitch adoption into the request's trace: tokens the prefill
            # did NOT have to recompute show up as a named span instead of
            # unexplained TTFT variance.
            with tracing.trace_context(tracing.request_trace_id(req.id),
                                       None):
                tracing.record_span(
                    "llm:prefix_adopt", "llm", t_adopt0, time.time(),
                    request_id=req.id, tokens_saved=promoted)
        return promoted

    def adopt_prefix(self, state: dict, k_pages, v_pages) -> int:
        """Adopt prefix blocks pushed by a draining peer (llm/disagg.py
        wire, meta["prefix"]=True): scatter each block into a fresh page,
        register it under THIS engine's digest chain, and park it in the
        reusable pool — exactly as if a local request had prefilled and
        released it. Skips (never errors on) blocks it cannot place:
        stale weights, unknown adapters, token/shape mismatches, or pool
        pressure. Returns blocks adopted."""
        if int(state.get("weights_version", 0)) != self.weights_version:
            return 0
        entries = state.get("entries") or []
        k_pages = np.asarray(k_pages)
        v_pages = np.asarray(v_pages)
        if k_pages.ndim != 5 or int(k_pages.shape[2]) != len(entries):
            return 0
        bm = self.block_manager
        bs = self.block_size
        adopted = 0
        for i, ent in enumerate(entries):
            tokens = [int(t) for t in (ent.get("tokens") or [])]
            if not tokens or len(tokens) % bs:
                continue
            lora = ent.get("lora") or ""
            slot = 0
            if lora:
                lm = self.runner.lora
                try:
                    slot = lm.slot_of(lora) if lm is not None else None
                except KeyError:
                    slot = None
                if slot is None or self._lora_name(slot) != lora:
                    continue  # adapter not resident here: unaddressable
            seed = int(slot).to_bytes(8, "little", signed=True)
            h = prefix_digest_chain(tokens, bs, seed=seed)[-1]
            if h in bm.cached:
                continue
            ids = bm.adopt_blocks(1)
            if ids is None:
                break
            self.runner.scatter_pages(ids, k_pages[:, :, i:i + 1],
                                      v_pages[:, :, i:i + 1])
            if bm.register_adopted_block(ids[0], h, slot, tokens):
                adopted += 1
            # Parks in `reusable` (hashed, refcount hits 0) — or returns
            # straight to `free` if registration lost the race.
            bm.release_blocks(ids)
        return adopted

    def export_prefixes(self, limit: int = 16):
        """Snapshot the hottest idle prefix blocks for a drain-time push
        (serving.LLMServer.push_prefixes): parked device blocks first
        (hottest), then host-tier entries. Returns (state, k, v) shaped
        for llm/disagg.py send_handoff, or None when there is nothing
        worth pushing."""
        bm = self.block_manager
        picked = []
        for bid in reversed(bm.reusable):
            h = bm.block_hash.get(bid)
            meta = bm.digest_meta.get(h) if h is not None else None
            if meta is None:
                continue
            slot, lora_name, tokens = meta
            if lora_name is None:
                continue
            picked.append((bid, lora_name, tokens))
            if len(picked) >= limit:
                break
        entries, ks, vs = [], [], []
        if picked:
            k, v = self.runner.gather_pages([b for b, _, _ in picked])
            ks.append(np.asarray(k))
            vs.append(np.asarray(v))
            entries.extend({"tokens": list(t), "lora": name}
                           for _, name, t in picked)
        if self.host_prefix_tier is not None and len(entries) < limit:
            for e in self.host_prefix_tier.hottest(limit - len(entries)):
                entries.append({"tokens": list(e["tokens"]),
                                "lora": e["lora_name"]})
                ks.append(np.asarray(e["k"]))
                vs.append(np.asarray(e["v"]))
        if not entries:
            return None
        k = np.concatenate(ks, axis=2) if len(ks) > 1 else ks[0]
        v = np.concatenate(vs, axis=2) if len(vs) > 1 else vs[0]
        state = {"prefix": True, "entries": entries,
                 "weights_version": self.weights_version}
        return state, k, v

    # ---- internals -------------------------------------------------------

    def _admit(self):
        """waiting -> prefilling while pages for (context + 1 token) and
        batch slots are available."""
        while (self.waiting
               and len(self.prefilling) + len(self.running) < self.max_batch):
            req = self.waiting[0]
            if req.dispatched:
                # Preempted with steps still in flight: quarantine until the
                # stale flights drain (their tokens reference KV in pages
                # already detached for release — mixing them with a fresh
                # prefill would corrupt the recomputed sequence).
                break
            if len(req.context) + 1 > self._cap_tokens:
                self.waiting.popleft()
                req.finished_reason = "length"
                self._unpin_lora(req)
                self._rejected.append(RequestOutput(
                    req.id, req.prompt, list(req.output), True, "length",
                    self._detok(req.output)))
                continue
            if not self.block_manager.can_allocate(len(req.context) + 1):
                break
            self.waiting.popleft()
            # Prefix cache: attach the longest cached chain of full prompt
            # blocks and skip their prefill compute entirely (recompute
            # admits after preemption re-match too — their KV may still be
            # resident).
            cached_tokens = 0
            if self.block_manager.caching:
                if req.prefix_hashes is None:
                    req.prefix_hashes = self.block_manager.prefix_hashes(
                        req.prompt, req.lora_slot)
                cached_tokens = self.block_manager.match_prefix(
                    req, req.prefix_hashes)
                # Device tier exhausted: promote from host RAM, then the
                # cluster store (llm/prefix_store.py) — spilled blocks
                # re-enter fresh device pages instead of re-prefilling.
                if (self.host_prefix_tier is not None
                        or self.cluster_store is not None):
                    cached_tokens += self._promote_prefix(req)
                req.registered_blocks = len(req.blocks)
            assert self.block_manager.allocate(req, len(req.context) + 1)
            req.prefilled = cached_tokens
            if req.timing["t_admit"] is None:
                req.timing["t_admit"] = time.time()
            self.prefilling.append(req)

    def warmup(self, *, full: bool = False) -> int:
        """Precompile the bucketed step grid so no user request ever pays an
        XLA compile mid-stream (vLLM's TPU backend precompiles the same way
        at startup). Without this, the first request hitting a new
        (batch, chunk) bucket — e.g. the short suffix after a prefix-cache
        hit — stalls for a full compile (observed 13 s on a ~2B model vs a
        105 ms steady-state TTFT).

        Dummy rows carry q_lens=0, so every KV write lands in the scatter
        drop zone: the KV pool, block tables, and scheduler state are
        untouched. The default (light) set warms the device-sampling step
        for sequential traffic: every prefill chunk bucket at batch 1,
        every decode batch bucket at Bq=1, and — with speculation on — the
        verify step at every reachable proposal-width bucket per batch
        bucket. full=True warms the whole batch x chunk grid AND the
        host-logits step (repetition-penalty requests); only then does the
        no-compile guarantee cover every request shape. Returns the number
        of shapes compiled."""
        r = self.runner
        batch_buckets = sorted({r.batch_bucket(n)
                                for n in range(1, self.max_batch + 1)})
        # The runner owns the bucket ladder (one source of truth); warm only
        # the buckets this engine's prefill_chunk can reach.
        cap = r.chunk_bucket(self.prefill_chunk)
        chunk_buckets = [cb for cb in r.chunk_buckets() if cb <= cap]
        # Spec proposals vary per tick from width 1 up to spec_ngram+1, so
        # EVERY chunk bucket up to the max proposal's bucket can carry a
        # verify step.
        spec_cap = (r.chunk_bucket(self.spec_ngram + 1)
                    if self.spec_ngram else 0)
        # Light set: single-sequence prefill chunks + per-batch decode (the
        # sequential-traffic pattern). Full grid: every batch bucket at every
        # chunk bucket — required for "no request ever compiles" once
        # prefills batch, so servers default to it.
        combos = {(batch_buckets[0], cb) for cb in chunk_buckets}
        combos |= {(sb, 1) for sb in batch_buckets}
        verify_widths = ({cb for cb in r.chunk_buckets() if cb <= spec_cap}
                         if spec_cap else set())
        if spec_cap:
            combos |= {(sb, cb) for sb in batch_buckets
                       for cb in verify_widths}
        if full:
            combos |= {(sb, cb) for sb in batch_buckets
                       for cb in chunk_buckets}
        for S, Bq in sorted(combos):
            tokens = np.zeros((S, Bq), dtype=np.int32)
            zeros = np.zeros(S, dtype=np.int32)
            tables = np.zeros((S, self.max_blocks_per_seq), dtype=np.int32)
            args = (tokens, zeros, zeros, zeros, tables)
            samp = (np.zeros(S, np.float32), np.zeros(S, np.int32),
                    np.ones(S, np.float32), np.zeros(S, np.int32), zeros)
            r.step_sample(*args, *samp)
            if Bq == 1 and self.multi_step > 1:
                # The k-token scan is a distinct program per batch bucket:
                # warm it or the first multi-step dispatch compiles
                # mid-stream (exactly the cliff warmup exists to prevent).
                r.step_sample_multi(self.multi_step, *args, *samp)
            if Bq in verify_widths:
                # Membership in the runner's own ladder (not a hardcoded
                # lower bound): a chunk_size < 8 config has ladder
                # [chunk_size], and its verify bucket must warm too.
                r.step_verify(*args)
            if full:
                # Host-logits path (runner.step): taken whenever a request
                # uses repetition_penalty — warm it too so the "no compile
                # mid-stream" guarantee covers every sampling feature.
                r.step(*args)
        compiled = len(combos)
        if self.unified_ticks and self.multi_step == 1 \
                and not self.prefill_only:
            # The unified tick's whole bucket grid is the TOKEN ladder at
            # one pinned batch bucket — precompile it so the serving hot
            # loop runs steady-state with zero compiles.
            from ray_tpu.llm.model_runner import token_buckets

            S = r.batch_bucket(self.max_batch)
            for Tb in token_buckets(self.token_budget):
                if Tb in self._warm_mixed:
                    continue
                r.warm_mixed(Tb, S, self._spec_width)
                self._warm_mixed.add(Tb)
                compiled += 1
        return compiled

    def _needs_logits(self, reqs) -> bool:
        """Host sampling (full logits fetch) is only needed for features the
        device sampler lacks (repetition penalty)."""
        return any(r.params.repetition_penalty != 1.0 for r in reqs)

    def _sampling_arrays(self, batch, S, counters):
        temps = np.zeros(S, dtype=np.float32)
        top_ks = np.zeros(S, dtype=np.int32)
        top_ps = np.ones(S, dtype=np.float32)
        seeds = np.zeros(S, dtype=np.int32)
        for i, req in enumerate(batch):
            temps[i] = req.params.temperature
            top_ks[i] = req.params.top_k
            top_ps[i] = req.params.top_p
            seeds[i] = req.seed_val
        return temps, top_ks, top_ps, seeds, np.asarray(counters, np.int32)

    def _prefill_step(self) -> List[RequestOutput]:
        """One chunk for every prefilling sequence, batched and bucketed.
        Chunk dispatches are async; only the final token fetch syncs."""
        batch = self.prefilling[:self.max_batch]
        chunks = [min(len(r.context) - r.prefilled, self.prefill_chunk)
                  for r in batch]
        Bq = self.runner.chunk_bucket(max(chunks))
        chunks = [min(c, Bq) for c in chunks]
        self.prefill_tokens_computed += sum(chunks)
        self._note(kind="prefill", prefill_rows=len(batch),
                   chunk_bucket=Bq, prefill_tokens=sum(chunks))
        S = self.runner.batch_bucket(len(batch))
        tokens = np.zeros((S, Bq), dtype=np.int32)
        q_positions = np.zeros(S, dtype=np.int32)
        kv_lens = np.zeros(S, dtype=np.int32)
        q_lens = np.zeros(S, dtype=np.int32)
        tables = np.zeros((S, self.max_blocks_per_seq), dtype=np.int32)
        counters = np.zeros(S, dtype=np.int32)
        for i, (req, c) in enumerate(zip(batch, chunks)):
            ctx = req.context
            tokens[i, :c] = ctx[req.prefilled:req.prefilled + c]
            q_positions[i] = req.prefilled
            kv_lens[i] = req.prefilled + c
            q_lens[i] = c
            tables[i, :len(req.blocks)] = req.blocks
            counters[i] = req.prefilled + c
        outputs: List[RequestOutput] = []
        lora_idx = self._lora_idx(batch, S)
        if self._needs_logits(batch):
            logits = np.asarray(self.runner.step(
                tokens, q_positions, kv_lens, q_lens, tables,
                lora_idx=lora_idx))
            sampled = None
        else:
            temps, top_ks, top_ps, seeds, counters = self._sampling_arrays(
                batch, S, counters)
            sampled = np.asarray(self.runner.step_sample(
                tokens, q_positions, kv_lens, q_lens, tables,
                temps, top_ks, top_ps, seeds, counters, lora_idx=lora_idx))
            logits = None
        for i, (req, c) in enumerate(zip(batch, chunks)):
            req.prefilled += c
            # Newly completed FULL prompt blocks become cache-addressable
            # (their KV is now written and immutable).
            if self.block_manager.caching:
                full = min(req.prefilled, len(req.prompt)) // self.block_size
                while req.registered_blocks < full:
                    j = req.registered_blocks
                    self.block_manager.register_block(
                        req, j, req.prefix_hashes[j])
                    req.registered_blocks += 1
            if req.prefilled < len(req.context):
                continue  # mid-prompt: this chunk's sample is unused
            self.prefilling.remove(req)
            if req.output:
                # Recomputed after preemption: context already includes
                # generated tokens; resume decoding without re-sampling.
                self.running.append(req)
                continue
            if sampled is not None:
                token = int(sampled[i])
            else:
                token = int(sample(logits[i], req.params,
                                   np.asarray(req.context)))
            req.output.append(token)
            outputs.append(self._emit(req, [token]))
            if req.finished_reason:
                self.block_manager.release(req)
            else:
                self.running.append(req)
        return outputs

    # ---- async decode pipeline ------------------------------------------

    def _decode_tick(self) -> List[RequestOutput]:
        """Dispatch one speculative decode step chained off the newest
        in-flight step, then (only once the pipeline is full, or when
        nothing could be dispatched) process the OLDEST step's tokens —
        whose device->host copy has been in flight for pipeline_depth
        ticks."""
        if self._needs_logits(self.running):
            return self._decode_sync()
        if (self.spec_ngram > 0
                and all(r.params.temperature <= 0.0 for r in self.running)):
            if self._flights:
                # Drain the async pipeline one step per tick (a sampled
                # request may have primed it); spec engages once empty.
                outputs = self._process_inflight(self._flights.popleft())
                self._drain_release()
                return outputs
            return self._decode_spec()
        prev = self._flights[-1] if self._flights else None
        flight = self._dispatch_decode(prev) if self.running else None
        if flight is not None:
            self._flights.append(flight)
        outputs: List[RequestOutput] = []
        if self._flights and (len(self._flights) > self.pipeline_depth
                              or flight is None):
            outputs = self._process_inflight(self._flights.popleft())
        self._drain_release()
        return outputs

    def _ensure_pages(self) -> None:
        """Every running seq needs pages for committed + dispatched + the
        next dispatch's tokens (multi_step when active); preempt the
        newest otherwise. Preempted/finished pages that an in-flight step
        may still write are released only once drained."""
        for req in list(self.running):
            if req not in self.running:
                continue
            while not self.block_manager.allocate(
                    req, min(req.num_tokens + req.dispatched
                             + self.multi_step, self._cap_tokens)):
                victim = self.running[-1]
                self.running.remove(victim)
                victim.prefilled = 0
                self.waiting.appendleft(victim)
                self._defer_release(victim)
                if req is victim:
                    break

    def _dispatch_decode(self, prev: Optional[dict]) -> Optional[dict]:
        import jax.numpy as jnp

        self._ensure_pages()
        prev_reqs = set(prev["batch"]) if prev else set()

        def eligible(r):
            if self.block_manager.blocks_needed(
                    r.num_tokens + r.dispatched + 1) > len(r.blocks):
                return False
            # Don't speculate past max_tokens / the length cap (bounded
            # overshoot; also keeps block tables within their static width).
            if (len(r.output) + r.dispatched >= r.params.max_tokens
                    or r.num_tokens + r.dispatched >= self._cap_tokens):
                return False
            # A req with device-resident tokens must chain from the newest
            # flight; if it is not there (just recomputed/odd scheduling),
            # wait until its flights are processed.
            if r.dispatched and r not in prev_reqs:
                return False
            return True

        batch = [r for r in self.running if eligible(r)]
        if not batch:
            return None

        def kv_headroom(r):
            # Room for KV writes only: pages and the static table width are
            # hard bounds (an in-flight step writes k entries regardless of
            # what the harvest keeps). max_tokens is deliberately NOT here —
            # a nearly-finished member overshoots within its pages and
            # _process_inflight discards tokens past the end, instead of
            # dropping the whole batch to single-step for its remaining
            # lifetime.
            return min(
                self._cap_tokens - r.num_tokens - r.dispatched,
                len(r.blocks) * self.block_size - r.num_tokens
                - r.dispatched)

        # All-or-nothing k: the scan's block tables and step count are
        # static, so every member needs full KV headroom or the batch takes
        # the (equally precompiled) single-step program.
        k = self.multi_step if (self.multi_step > 1 and
                                all(kv_headroom(r) >= self.multi_step
                                    for r in batch)) else 1
        S = self.runner.batch_bucket(len(batch))
        host_tokens = np.zeros(S, dtype=np.int32)
        gather_idx = np.zeros(S, dtype=np.int32)
        from_prev = np.zeros(S, dtype=bool)
        q_positions = np.zeros(S, dtype=np.int32)
        kv_lens = np.zeros(S, dtype=np.int32)
        q_lens = np.zeros(S, dtype=np.int32)
        tables = np.zeros((S, self.max_blocks_per_seq), dtype=np.int32)
        counters = np.zeros(S, dtype=np.int32)
        prev_rows = ({req: i for i, req in enumerate(prev["batch"])}
                     if prev else {})
        for i, req in enumerate(batch):
            pos = req.num_tokens + req.dispatched - 1  # last token's position
            if req.dispatched and req in prev_rows:
                from_prev[i] = True
                gather_idx[i] = prev_rows[req]
            else:
                host_tokens[i] = req.output[-1] if req.output else req.prompt[-1]
            q_positions[i] = pos
            kv_lens[i] = pos + 1
            q_lens[i] = 1
            tables[i, :len(req.blocks)] = req.blocks
            counters[i] = pos + 1
        if prev is not None and from_prev.any():
            toks = jnp.where(jnp.asarray(from_prev),
                             prev["last"][jnp.asarray(gather_idx)],
                             jnp.asarray(host_tokens))
        else:
            toks = jnp.asarray(host_tokens)
        temps, top_ks, top_ps, seeds, counters = self._sampling_arrays(
            batch, S, counters)
        if k > 1:
            dev_tokens = self.runner.step_sample_multi(
                k, toks[:, None], q_positions, kv_lens, q_lens, tables,
                temps, top_ks, top_ps, seeds, counters,
                lora_idx=self._lora_idx(batch, S))  # (S, k)
            last = dev_tokens[:, -1]
        else:
            dev_tokens = self.runner.step_sample(
                toks[:, None], q_positions, kv_lens, q_lens, tables,
                temps, top_ks, top_ps, seeds, counters,
                lora_idx=self._lora_idx(batch, S))  # (S,)
            last = dev_tokens
        try:
            dev_tokens.copy_to_host_async()
        except AttributeError:
            pass
        for req in batch:
            req.dispatched += k
        self._note(kind="decode", decode_rows=len(batch), multi_step=k,
                   inflight=len(self._flights) + 1)
        return {"batch": batch, "tokens": dev_tokens, "last": last, "k": k}

    def _process_inflight(self, flight: Optional[dict]) -> List[RequestOutput]:
        if flight is None:
            return []
        fetched = np.asarray(flight["tokens"])  # sync point (overlapped)
        k = flight.get("k", 1)
        if fetched.ndim == 1:
            fetched = fetched[:, None]
        outputs: List[RequestOutput] = []
        for i, req in enumerate(flight["batch"]):
            req.dispatched -= k
            if req not in self.running:
                continue  # preempted: will recompute from context
            for j in range(k):
                if req.finished_reason is not None:
                    break  # tokens sampled past the end: discard
                token = int(fetched[i, j])
                req.output.append(token)
                outputs.append(self._emit(req, [token]))
                if req.finished_reason:
                    self.running.remove(req)
                    self._defer_release(req)
        return outputs

    def _defer_release(self, req: _Request):
        """Release a seq's pages now, or after in-flight writes drain."""
        if req.dispatched:
            blocks, req.blocks = req.blocks, []
            self._pending_release.append((req, blocks))
        else:
            self.block_manager.release(req)

    def _drain_release(self):
        """Free pages of finished/preempted seqs once no in-flight step can
        still write into them."""
        keep = []
        for req, blocks in self._pending_release:
            if req.dispatched == 0:
                self.block_manager.release_blocks(blocks)
            else:
                keep.append((req, blocks))
        self._pending_release = keep

    # ---- n-gram speculative decode --------------------------------------

    @staticmethod
    def _ngram_propose(context: List[int], k: int, n: int = 3) -> List[int]:
        """Prompt-lookup proposal (vLLM's ngram speculative method): find
        the most recent earlier occurrence of the trailing (n-1)-gram and
        propose the k tokens that followed it. Falls back to shorter grams
        (down to matching just the last token) when the longer key has no
        earlier occurrence — the lookup-max/min ladder; a weak proposal
        costs only a wasted verify row, never a wrong token."""
        for nn in range(min(n, len(context)), 1, -1):
            key = tuple(context[-(nn - 1):])
            for i in range(len(context) - nn, -1, -1):
                if tuple(context[i:i + nn - 1]) == key:
                    prop = list(context[i + nn - 1:i + nn - 1 + k])
                    if prop:
                        return prop
        return []

    def _decode_spec(self) -> List[RequestOutput]:
        """Greedy speculative decode via prompt lookup: each sequence's
        step carries [last_token, proposal...]; the verify head returns the
        model's greedy token at every position, and the longest agreeing
        prefix (plus the model's own next token) is accepted. Repetitive
        outputs advance several tokens per step; a miss costs nothing
        beyond the (tiny) multi-position vocab matmul. KV written for
        rejected positions is overwritten by the next step's scatter (the
        kv_len accounting only ever covers accepted tokens).

        Determinism note: acceptance compares the verify head's argmax
        against the plain head's; exact in fp32, while bf16 argmax TIES
        may resolve differently across the two matmul shapes (same caveat
        as any speculative scheme under finite precision)."""
        outputs: List[RequestOutput] = []
        self._drain_release()
        batch = self.running[:self.max_batch]
        if not batch:
            return outputs
        k = self.spec_ngram
        # Proposals FIRST: pages are reserved for what will actually be
        # written (num_tokens + len(prop) + 1), not the worst-case k — a
        # missed proposal must not cause allocation pressure/preemption a
        # plain decode wouldn't.
        proposals = []
        for r in batch:
            room = self._cap_tokens - (r.num_tokens + 1)
            budget = min(k, max(0, room),
                         r.params.max_tokens - len(r.output) - 1)
            proposals.append(
                self._ngram_propose(r.context, budget) if budget > 0 else [])
        for req, prop in zip(list(batch), list(proposals)):
            if not self.block_manager.allocate(
                    req, min(req.num_tokens + len(prop) + 1,
                             self._cap_tokens)):
                # Page pressure: plain 1-token verify this tick.
                proposals = [[] for _ in batch]
                self._ensure_pages()  # may preempt; re-filter the batch
                keep = [(r, p) for r, p in zip(batch, proposals)
                        if r in self.running]
                if not keep:
                    return outputs
                batch = [r for r, _ in keep]
                proposals = [p for _, p in keep]
                break
        width = 1 + max((len(p) for p in proposals), default=1)
        Bq = self.runner.chunk_bucket(width)
        S = self.runner.batch_bucket(len(batch))
        tokens = np.zeros((S, Bq), dtype=np.int32)
        q_positions = np.zeros(S, dtype=np.int32)
        kv_lens = np.zeros(S, dtype=np.int32)
        q_lens = np.zeros(S, dtype=np.int32)
        tables = np.zeros((S, self.max_blocks_per_seq), dtype=np.int32)
        for i, (req, prop) in enumerate(zip(batch, proposals)):
            row = [req.output[-1] if req.output else req.prompt[-1]] + prop
            tokens[i, :len(row)] = row
            q_positions[i] = req.num_tokens - 1
            kv_lens[i] = req.num_tokens + len(prop)
            q_lens[i] = len(row)
            tables[i, :len(req.blocks)] = req.blocks
        self._note(kind="spec_verify", decode_rows=len(batch),
                   spec_tokens=sum(len(p) for p in proposals),
                   chunk_bucket=Bq)
        got = np.asarray(self.runner.step_verify(
            tokens, q_positions, kv_lens, q_lens, tables,
            lora_idx=self._lora_idx(batch, S)))
        finished: List[_Request] = []
        for i, (req, prop) in enumerate(zip(batch, proposals)):
            accepted: List[int] = []
            for j, proposed_tok in enumerate(prop):
                if int(got[i, j]) != proposed_tok:
                    break
                accepted.append(proposed_tok)
            # The model's own next token after the agreed prefix.
            accepted.append(int(got[i, len(accepted)]))
            # Never exceed max_tokens mid-bonus.
            room = req.params.max_tokens - len(req.output)
            accepted = accepted[:max(1, room)]
            # Honor stop tokens inside the accepted run.
            stops = req.params.stop_token_ids or ()
            for j, t in enumerate(accepted):
                if t in stops:
                    accepted = accepted[:j + 1]
                    break
            req.output.extend(accepted)
            self.spec_tokens_accepted += len(accepted) - 1
            if prop:
                from ray_tpu.runtime import metric_defs

                self.spec_tokens_proposed += len(prop)
                metric_defs.LLM_SPEC_PROPOSED.inc(len(prop))
                if len(accepted) > 1:
                    metric_defs.LLM_SPEC_ACCEPTED.inc(len(accepted) - 1)
            outputs.append(self._emit(req, accepted))
            if req.finished_reason:
                finished.append(req)
        for req in finished:
            self.running.remove(req)
            self.block_manager.release(req)
        return outputs

    # ---- unified ragged tick --------------------------------------------

    def _mixed_tick(self) -> List[RequestOutput]:
        """ONE mixed kernel launch per engine iteration (ISSUE 17 tentpole,
        the Ragged Paged Attention layout): a token-budget batch composer
        admits decode and spec-verify rows FIRST — running sequences never
        stall behind a long prompt — then fills the remaining budget from
        the prefill backlog, and dispatches the whole composition through
        ModelRunner.step_mixed, bucketed on total token count.

        Speculation runs at ANY temperature here: greedy rows accept by
        argmax agreement (exactly the split _decode_spec rule) and
        temperature>0 rows by seeded acceptance (rejection) sampling —
        keys derive from crc32(request_id) and the token's absolute index,
        so a failover replay or migrated session re-derives the identical
        accept/reject trajectory. The tick is synchronous (dispatched
        stays 0 for every request), which keeps the PR 12 export/migration
        preconditions trivially true mid-stream."""
        from ray_tpu.llm.model_runner import _bucket, token_buckets
        from ray_tpu.runtime import metric_defs

        outputs: List[RequestOutput] = []
        self._drain_release()
        W = self._spec_width
        budget = self.token_budget
        # The batch dimension is pinned to one bucket (compiles scale with
        # the token ladder alone) — the composer must respect it as a ROW
        # cap too, or a backlog of near-finished prefills (many requests,
        # tiny remaining chunks) overflows cu/out_rows.
        S = self.runner.batch_bucket(self.max_batch)
        # -- decode / spec-verify rows first --------------------------------
        batch = self.running[:self.max_batch]
        proposals: List[List[int]] = []
        if batch:
            spec_left = budget - len(batch)   # 1 token/row is reserved
            k = self.spec_ngram
            for r in batch:
                room = self._cap_tokens - (r.num_tokens + 1)
                pb = min(k, max(0, room),
                         r.params.max_tokens - len(r.output) - 1, spec_left)
                prop = (self._ngram_propose(r.context, pb) if pb > 0 else [])
                spec_left -= len(prop)
                proposals.append(prop)
            for req, prop in zip(list(batch), list(proposals)):
                if not self.block_manager.allocate(
                        req, min(req.num_tokens + len(prop) + 1,
                                 self._cap_tokens)):
                    # Page pressure: degrade to plain 1-token rows, then
                    # preempt-newest until the plain tick fits (the same
                    # fallback ladder as _decode_spec).
                    self._ensure_pages()
                    batch = [r for r in batch if r in self.running]
                    proposals = [[] for _ in batch]
                    break
        entries: List[dict] = []
        used = 0
        for req, prop in zip(batch, proposals):
            row = [req.output[-1] if req.output else req.prompt[-1]] + prop
            entries.append({"req": req, "tokens": row, "prop": prop,
                            "kind": "decode",
                            "q_pos": req.num_tokens - 1,
                            "kv_len": req.num_tokens + len(prop),
                            "counter": req.num_tokens})
            used += len(row)
        # -- remaining budget fills from the prefill backlog ----------------
        for req in list(self.prefilling):
            if len(entries) >= S:
                break
            c = min(len(req.context) - req.prefilled, self.prefill_chunk,
                    budget - used)
            if c <= 0:
                break
            entries.append({"req": req,
                            "tokens": req.context[req.prefilled:
                                                  req.prefilled + c],
                            "prop": [], "kind": "prefill", "chunk": c,
                            "q_pos": req.prefilled,
                            "kv_len": req.prefilled + c,
                            "counter": req.prefilled + c})
            used += c
            self.prefill_tokens_computed += c
        if not entries:
            return outputs
        # -- assemble the token-major batch ---------------------------------
        Tb = _bucket(used, token_buckets(budget))
        recompile = Tb not in self._warm_mixed
        self._note(
            kind="mixed", budget=budget, used=used, bucket=Tb,
            recompile=recompile,
            decode_rows=sum(1 for e in entries if e["kind"] == "decode"),
            prefill_rows=sum(1 for e in entries if e["kind"] == "prefill"),
            spec_tokens=sum(len(e["prop"]) for e in entries),
            budget_exhausted=used >= budget)
        if recompile:
            # A bucket outside the warmed ladder (or a pre-warmup call):
            # compile it on a dummy BEFORE the real tokens ride it, so the
            # steady-state loop never absorbs the stall unannounced.
            self.runner.warm_mixed(Tb, S, W)
            self._warm_mixed.add(Tb)
        flat = np.zeros(Tb, dtype=np.int32)
        cu = np.zeros(S + 1, dtype=np.int32)
        q_positions = np.zeros(S, dtype=np.int32)
        kv_lens = np.zeros(S, dtype=np.int32)
        tables = np.zeros((S, self.max_blocks_per_seq), dtype=np.int32)
        out_rows = np.zeros((S, W), dtype=np.int32)
        props = np.zeros((S, W), dtype=np.int32)
        prop_lens = np.zeros(S, dtype=np.int32)
        counters = np.zeros(S, dtype=np.int32)
        pos = 0
        for i, e in enumerate(entries):
            n = len(e["tokens"])
            flat[pos:pos + n] = e["tokens"]
            cu[i] = pos
            cu[i + 1] = pos + n
            q_positions[i] = e["q_pos"]
            kv_lens[i] = e["kv_len"]
            req = e["req"]
            tables[i, :len(req.blocks)] = req.blocks
            if e["kind"] == "prefill":
                # The chunk's LAST row carries the next-token logits.
                out_rows[i] = pos + n - 1
            else:
                # Row j of a decode/verify span: logits after consuming
                # proposal tokens 0..j-1 (clamped for the padding columns).
                out_rows[i] = [pos + min(j, n - 1) for j in range(W)]
            pl = len(e["prop"])
            if pl:
                props[i, :pl] = e["prop"]
            prop_lens[i] = pl
            counters[i] = e["counter"]
            pos += n
        cu[len(entries) + 1:] = pos
        reqs = [e["req"] for e in entries]
        temps, top_ks, top_ps, seeds, counters = self._sampling_arrays(
            reqs, S, counters)
        accept, samples = self.runner.step_mixed(
            flat, q_positions, kv_lens, cu, tables, out_rows, props,
            prop_lens, temps, top_ks, top_ps, seeds, counters,
            lora_idx=self._lora_idx(reqs, S))
        acc = np.asarray(accept)
        smp = np.asarray(samples)
        # -- commit ---------------------------------------------------------
        for i, e in enumerate(entries):
            req = e["req"]
            if e["kind"] == "prefill":
                req.prefilled += e["chunk"]
                if self.block_manager.caching:
                    full = (min(req.prefilled, len(req.prompt))
                            // self.block_size)
                    while req.registered_blocks < full:
                        j = req.registered_blocks
                        self.block_manager.register_block(
                            req, j, req.prefix_hashes[j])
                        req.registered_blocks += 1
                if req.prefilled < len(req.context):
                    continue   # mid-prompt: this chunk's sample is unused
                self.prefilling.remove(req)
                if req.output:
                    # Recomputed after preemption: resume decoding without
                    # re-sampling already-emitted tokens.
                    self.running.append(req)
                    continue
                token = int(smp[i, 0])
                req.output.append(token)
                outputs.append(self._emit(req, [token]))
                if req.finished_reason:
                    self.block_manager.release(req)
                else:
                    self.running.append(req)
                continue
            if req not in self.running:
                continue   # preempted inside this tick: recompute path
            prop = e["prop"]
            accepted: List[int] = []
            for j, t in enumerate(prop):
                if not bool(acc[i, j]):
                    break
                accepted.append(int(t))
            # The model's own token after the agreed prefix (greedy rows)
            # or the residual/bonus sample (temperature rows).
            accepted.append(int(smp[i, len(accepted)]))
            room = req.params.max_tokens - len(req.output)
            accepted = accepted[:max(1, room)]
            stops = req.params.stop_token_ids or ()
            for j, t in enumerate(accepted):
                if t in stops:
                    accepted = accepted[:j + 1]
                    break
            req.output.extend(accepted)
            if prop:
                self.spec_tokens_proposed += len(prop)
                self.spec_tokens_accepted += len(accepted) - 1
                metric_defs.LLM_SPEC_PROPOSED.inc(len(prop))
                if len(accepted) > 1:
                    metric_defs.LLM_SPEC_ACCEPTED.inc(len(accepted) - 1)
            outputs.append(self._emit(req, accepted))
            if req.finished_reason:
                self.running.remove(req)
                self.block_manager.release(req)
        return outputs

    def _decode_sync(self) -> List[RequestOutput]:
        """Legacy synchronous decode (host sampling with full logits) —
        used when a request needs repetition penalty."""
        outputs: List[RequestOutput] = []
        while self._flights:
            outputs.extend(self._process_inflight(self._flights.popleft()))
        self._drain_release()
        self._ensure_pages()
        batch = self.running
        if not batch:
            return outputs
        S = self.runner.batch_bucket(len(batch))
        tokens = np.zeros((S, 1), dtype=np.int32)
        q_positions = np.zeros(S, dtype=np.int32)
        kv_lens = np.zeros(S, dtype=np.int32)
        q_lens = np.zeros(S, dtype=np.int32)
        tables = np.zeros((S, self.max_blocks_per_seq), dtype=np.int32)
        for i, req in enumerate(batch):
            tokens[i, 0] = req.output[-1] if req.output else req.prompt[-1]
            q_positions[i] = req.num_tokens - 1
            kv_lens[i] = req.num_tokens
            q_lens[i] = 1
            tables[i, :len(req.blocks)] = req.blocks
        self._note(kind="decode_host", decode_rows=len(batch))
        logits = np.asarray(self.runner.step(
            tokens, q_positions, kv_lens, q_lens, tables,
            lora_idx=self._lora_idx(batch, S)))
        finished: List[_Request] = []
        for i, req in enumerate(batch):
            token = sample(logits[i], req.params, np.asarray(req.context))
            req.output.append(int(token))
            outputs.append(self._emit(req, [int(token)]))
            if req.finished_reason:
                finished.append(req)
        for req in finished:
            self.running.remove(req)
            self.block_manager.release(req)
        return outputs

    def _emit(self, req: _Request, new_tokens: List[int]) -> RequestOutput:
        from ray_tpu.runtime import metric_defs

        metric_defs.LLM_TOKENS_GENERATED.inc(len(new_tokens))
        now = time.time()
        if req.timing["t_first_token"] is None:
            req.timing["t_first_token"] = now
        req.timing["t_last_token"] = now
        self._check_finished(req)
        done = req.finished_reason is not None
        if done:
            self._unpin_lora(req)
            self._finish_trace(req)
        return RequestOutput(
            req.id, req.prompt, list(req.output), done, req.finished_reason,
            self._detok(req.output) if done else None, new_tokens)

    def request_breakdown(self, req: _Request) -> Optional[Dict[str, float]]:
        """TTFT/ITL decomposition for one request from its lifecycle
        timestamps: queue_s (submit->admit), prefill_s (admit->first token,
        minus handoff time), handoff_s (disagg KV streams), decode_s
        (first->last token, minus stalls), stall_s (migration pauses)."""
        t = req.timing
        if t["t_first_token"] is None:
            return None
        t_submit = t["t_submit"]
        t_admit = t["t_admit"] if t["t_admit"] is not None else t_submit
        t_first = t["t_first_token"]
        t_last = (t["t_last_token"] if t["t_last_token"] is not None
                  else t_first)
        handoff_s = float(t.get("handoff_s") or 0.0)
        stall_s = float(t.get("pause_s") or 0.0)
        return {
            "queue_s": max(0.0, t_admit - t_submit),
            "prefill_s": max(0.0, t_first - t_admit),
            "handoff_s": handoff_s,
            "decode_s": max(0.0, t_last - t_first - handoff_s - stall_s),
            "stall_s": stall_s,
        }

    def _finish_trace(self, req: _Request):
        """Close out a finished request's latency attribution: observe the
        ray_tpu_llm_{ttft,itl}_breakdown_ms histograms and record the
        queue/prefill/decode lifecycle spans under the request's trace (the
        trace id derives from the rid, so these stitch with the router's
        root span and the disagg handoff spans without any context having
        crossed a process boundary)."""
        from ray_tpu.runtime import metric_defs
        from ray_tpu.util import tracing

        bd = self.request_breakdown(req)
        if bd is None:
            return
        metric_defs.LLM_TTFT_BREAKDOWN_MS.observe(
            bd["queue_s"] * 1e3, tags={"phase": "queue"})
        metric_defs.LLM_TTFT_BREAKDOWN_MS.observe(
            bd["prefill_s"] * 1e3, tags={"phase": "prefill"})
        if bd["handoff_s"]:
            metric_defs.LLM_TTFT_BREAKDOWN_MS.observe(
                bd["handoff_s"] * 1e3, tags={"phase": "handoff"})
        # ITL phases are per inter-token gap: the mean decode gap, and the
        # stall share (migration pauses) amortized over the same gaps.
        gaps = max(1, len(req.output) - 1)
        metric_defs.LLM_ITL_BREAKDOWN_MS.observe(
            bd["decode_s"] * 1e3 / gaps, tags={"phase": "decode"})
        if bd["stall_s"]:
            metric_defs.LLM_ITL_BREAKDOWN_MS.observe(
                bd["stall_s"] * 1e3 / gaps, tags={"phase": "stall"})
        if not tracing.enabled():
            return
        t = req.timing
        t_admit = t["t_admit"] if t["t_admit"] is not None else t["t_submit"]
        with tracing.trace_context(tracing.request_trace_id(req.id), None):
            if t_admit > t["t_submit"]:
                tracing.record_span("llm:queue", "llm", t["t_submit"],
                                    t_admit, request_id=req.id)
            if not req.adopted:
                # Adopted requests prefilled elsewhere — that replica
                # already recorded the llm:prefill span.
                tracing.record_span(
                    "llm:prefill", "llm", t_admit, t["t_first_token"],
                    request_id=req.id, tokens=len(req.prompt))
            tracing.record_span(
                "llm:decode", "llm", t["t_first_token"], t["t_last_token"],
                request_id=req.id, tokens=len(req.output),
                finish_reason=req.finished_reason or "",
                **{k: round(v, 6) for k, v in bd.items()})

    def _check_finished(self, req: _Request):
        p = req.params
        if p.stop_token_ids and req.output and req.output[-1] in p.stop_token_ids:
            req.finished_reason = "stop"
        elif len(req.output) >= p.max_tokens:
            req.finished_reason = "length"
        elif req.num_tokens >= self._cap_tokens:
            req.finished_reason = "length"

    def _detok(self, token_ids: List[int]) -> Optional[str]:
        if self.tokenizer is None:
            return None
        try:
            return self.tokenizer.decode(token_ids)
        except Exception:
            return None
