"""Telemetry-driven replica-count policy for the LLM serving fleet.

The generic deployment autoscaler (serve/controller.py) scales on queue
length at the replica actors — the right signal for stateless RPC apps,
and the wrong one for LLM serving, where the binding resources are KV
cache pages and prefill compute: a fleet can show short actor queues
while every engine is one admission away from evicting reusable prefixes,
or deep prefill backlogs that the actor queue never sees (requests sit
INSIDE the engine's waiting queue, not in the mailbox).

This policy consumes what the router already collects — the per-replica
engine_stats() payloads — and turns two signals into a desired count:

  * **Queue delay**: total queued prefill tokens across the fleet divided
    by aggregate measured prefill throughput = seconds of prefill work a
    new request waits behind. Over `queue_delay_high_s` -> add a replica
    (before SLO admission starts shedding); prefill throughput unknown ->
    fall back on mean engine queue depth vs `queue_depth_high`.
  * **KV pressure**: mean fraction of KV pages in use. Over
    `kv_pressure_high` -> add a replica (an engine past ~85% occupancy
    is cannibalizing its own prefix cache to admit).

Scale-down is deliberately sticky: BOTH signals must sit below their low
watermarks continuously for `scale_down_quiet_s` (any busy sample resets
the clock), and then the fleet shrinks by ONE replica. The asymmetry is
intentional — upscale errors cost money for minutes, downscale errors
cost live sessions a migration each — and the router retires the victim
through the drain plane (drain -> migrate sessions -> remap affinity ->
kill), never by killing a loaded replica.

Pure and cluster-free (desired(stats, current, now) -> int) so unit tests
drive it with synthetic stats and explicit clocks; LLMRouter's control
loop owns the real feed.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence


@dataclasses.dataclass
class ReplicaPolicyConfig:
    """Watermarks for the LLM replica policy (see module docstring)."""

    min_replicas: int = 1
    max_replicas: int = 8
    # Seconds of queued prefill work behind which a new request waits.
    queue_delay_high_s: float = 2.0
    queue_delay_low_s: float = 0.25
    # Fallback when no prefill-throughput signal exists yet: mean engine
    # queue depth (waiting + prefilling) per replica.
    queue_depth_high: float = 4.0
    queue_depth_low: float = 0.5
    # Mean fraction of KV pages in use across the fleet.
    kv_pressure_high: float = 0.85
    kv_pressure_low: float = 0.50
    # Both signals must stay below the low watermarks this long before a
    # scale-down fires (busy samples reset the clock).
    scale_down_quiet_s: float = 30.0
    # At most one step per direction per this interval (lets a freshly
    # added replica absorb load before the policy reads the fleet again).
    cooldown_s: float = 10.0
    # Windowed-input mode: > 0 means watermark tests run against the mean
    # of the signals over this many trailing seconds instead of the
    # instantaneous tick, so a single-tick spike (one burst of queued
    # prefill tokens, one transient KV high-water) cannot trigger an
    # upscale by itself. 0 keeps the original instantaneous behaviour.
    signal_window_s: float = 0.0

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if self.signal_window_s < 0:
            raise ValueError("signal_window_s must be >= 0")


class ReplicaPolicy:
    """Stateful wrapper: config + the quiet/cooldown clocks."""

    def __init__(self, config: Optional[ReplicaPolicyConfig] = None):
        self.config = config or ReplicaPolicyConfig()
        self._quiet_since: Optional[float] = None
        self._last_action_t: Optional[float] = None
        # (t, signals) samples for windowed-input mode; trimmed each tick.
        self._samples: List = []

    # ---- signal extraction ----------------------------------------------

    @staticmethod
    def signals(stats: Sequence[Optional[Dict]]) -> Dict[str, float]:
        """Fleet-level (queue_delay_s, queue_depth, kv_pressure) from the
        per-replica engine_stats payloads; replicas with no fresh stats
        (probe failed this tick) contribute nothing."""
        live = [s for s in stats if s]
        if not live:
            return {"queue_delay_s": 0.0, "queue_depth": 0.0,
                    "kv_pressure": 0.0, "live": 0}
        queued_tokens = sum(s.get("queued_prefill_tokens", 0) for s in live)
        # tokens_per_s is the decode EWMA; prefill throughput rides under
        # its own key when a replica measured one. Either way, treat the
        # aggregate as the fleet's drain rate; zero means "unknown".
        tps = sum(s.get("prefill_tokens_per_s") or s.get("tokens_per_s") or 0
                  for s in live)
        depth = sum(s.get("waiting", 0) + s.get("prefilling", 0)
                    for s in live) / len(live)
        utils = []
        for s in live:
            total = s.get("total_kv_blocks", 0)
            if total:
                utils.append(1.0 - s.get("free_kv_blocks", 0) / total)
        return {
            "queue_delay_s": (queued_tokens / tps) if tps > 0 else -1.0,
            "queue_depth": depth,
            "kv_pressure": sum(utils) / len(utils) if utils else 0.0,
            "live": len(live),
        }

    def _windowed(self, sig: Dict[str, float], now: float) -> Dict[str, float]:
        """Fold this tick's signals into the sample window and return the
        window means. Unknown queue delays (-1) are excluded from the delay
        mean; the result is -1 only when NO sample in the window knew it."""
        w = self.config.signal_window_s
        self._samples.append((now, sig))
        self._samples = [(t, s) for t, s in self._samples if now - t <= w]
        samples = [s for _, s in self._samples]
        delays = [s["queue_delay_s"] for s in samples
                  if s["queue_delay_s"] >= 0]
        return {
            "queue_delay_s": (sum(delays) / len(delays)) if delays else -1.0,
            "queue_depth": sum(s["queue_depth"] for s in samples)
            / len(samples),
            "kv_pressure": sum(s["kv_pressure"] for s in samples)
            / len(samples),
            "live": sig["live"],
        }

    # ---- the decision ----------------------------------------------------

    def desired(self, stats: Sequence[Optional[Dict]], current: int,
                now: float) -> int:
        """Desired replica count given this tick's fleet stats. Returns
        `current` (no-op) outside the cooldown window or when neither
        watermark trips."""
        cfg = self.config
        if current < cfg.min_replicas:
            return cfg.min_replicas
        sig = self.signals(stats)
        if sig["live"] == 0:
            return current  # blind tick: never act on no data
        if cfg.signal_window_s > 0:
            sig = self._windowed(sig, now)
        delay = sig["queue_delay_s"]
        hot = (sig["kv_pressure"] > cfg.kv_pressure_high
               or (delay >= 0 and delay > cfg.queue_delay_high_s)
               or (delay < 0 and sig["queue_depth"] > cfg.queue_depth_high))
        quiet = (sig["kv_pressure"] < cfg.kv_pressure_low
                 and ((delay >= 0 and delay < cfg.queue_delay_low_s)
                      or (delay < 0
                          and sig["queue_depth"] < cfg.queue_depth_low)))
        if not quiet:
            self._quiet_since = None
        elif self._quiet_since is None:
            self._quiet_since = now
        in_cooldown = (self._last_action_t is not None
                       and now - self._last_action_t < cfg.cooldown_s)
        if hot and current < cfg.max_replicas and not in_cooldown:
            self._quiet_since = None
            self._last_action_t = now
            return current + 1
        if (quiet and current > cfg.min_replicas and not in_cooldown
                and self._quiet_since is not None
                and now - self._quiet_since >= cfg.scale_down_quiet_s):
            self._last_action_t = now
            self._quiet_since = now  # the next step needs its own quiet run
            return current - 1
        return current
