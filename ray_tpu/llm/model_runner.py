"""Model execution for serving: one bucketed step for prefill + decode.

Reference analog: the vLLM engine internals the reference only *places*
(vllm_engine.py:222, vllm_models.py:117-168). TPU-native design:

  * The KV cache is a paged pool `(layers, kv_heads, num_blocks, block_size,
    head_dim)`; block tables map each sequence's logical positions onto pool
    pages.
  * ONE jitted step function serves both chunked prefill (Bq = chunk tokens
    per sequence) and decode (Bq = 1): new-token KV is scattered into the
    pool, then ragged paged attention (ops/paged_attention.py — Pallas on
    TPU, O(actual context)) attends over each sequence's pages.
  * Shapes are bucketed on (batch, Bq): the engine runs a small fixed set of
    compiled programs — no recompiles in the hot loop (the round-1 runner
    recompiled per prompt length and per batch size).
  * Tensor parallelism: pass a mesh — params/cache shard per SERVE_RULES
    (heads/kv_heads/mlp/vocab over tp), attention runs under shard_map with
    per-shard heads.
"""

from __future__ import annotations

import logging
import math
from functools import partial
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

logger = logging.getLogger(__name__)

from ray_tpu.models import llama as llama_mod
from ray_tpu.ops import paged_attention as pa
from ray_tpu.ops.layers import apply_rope, rms_norm, rope_frequencies, swiglu


def init_kv_cache(config: llama_mod.LlamaConfig, num_blocks: int,
                  block_size: int) -> Dict[str, jax.Array]:
    shape = (config.n_layers, config.n_kv_heads, num_blocks, block_size,
             config.head_dim)
    return {"k": jnp.zeros(shape, dtype=config.dtype),
            "v": jnp.zeros(shape, dtype=config.dtype)}


def _bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    # Beyond the precomputed set: next power of two (a new compile, never a
    # silent cap — capping would overflow the engine's padded arrays).
    # ModelRunner._note_shapes makes that compile visible (metric + log)
    # instead of a silent multi-second hot-loop stall.
    return 1 << (n - 1).bit_length()


def token_buckets(budget: int) -> list:
    """Static token-budget ladder for the unified mixed step: powers of two
    from 8 up to (and always including) `budget`. Single source of truth for
    runtime bucketing AND warmup precompilation, mirroring chunk_buckets().
    Every bucket is a multiple of 8 — the Pallas unified kernel's q_block."""
    buckets, b = [], 8
    while b < budget:
        buckets.append(b)
        b *= 2
    buckets.append(budget)
    return buckets


class ModelRunner:
    """Bucketed, jit-compiled unified step over a paged cache."""

    BATCH_BUCKETS = (1, 2, 4, 8, 16, 32)

    def __init__(self, config: llama_mod.LlamaConfig, params,
                 num_blocks: int, block_size: int = 16,
                 mesh=None, attention_impl: str = "auto",
                 chunk_size: int = 128,
                 max_blocks_per_seq: Optional[int] = None,
                 lora_manager=None):
        self.config = config
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.chunk_size = chunk_size
        self.max_blocks_per_seq = max_blocks_per_seq or (
            (config.max_seq + block_size - 1) // block_size)
        self.mesh = mesh
        self.tp = int(mesh.shape.get("tp", 1)) if mesh is not None else 1
        if attention_impl == "auto":
            from ray_tpu.ops import is_tpu_backend

            # The Pallas kernel's page DMA needs a 128-aligned trailing dim.
            attention_impl = ("pallas" if is_tpu_backend()
                              and config.head_dim % 128 == 0 else "reference")
        self.attention_impl = attention_impl
        # Multi-LoRA (llm/lora.py): when a manager is attached, the step
        # takes the slot stacks + a per-sequence slot index and adds batched
        # low-rank deltas; without one the step compiles with no LoRA code.
        self.lora = lora_manager
        self.params = self._place_params(params)
        self.cache = self._place_cache(
            init_kv_cache(config, num_blocks, block_size))
        self.cos, self.sin = rope_frequencies(
            config.head_dim, config.max_seq, config.rope_theta)
        self._step_jit = jax.jit(self._step, donate_argnums=(1,))
        self._step_sample_jit = jax.jit(self._step_sample, donate_argnums=(1,))
        self._step_verify_jit = jax.jit(self._step_verify, donate_argnums=(1,))
        self._step_mixed_jit = jax.jit(self._step_mixed, donate_argnums=(1,))
        self._multi_jits: Dict[int, object] = {}  # n_steps -> jitted scan
        # Shape signatures already dispatched: a new one means XLA compiles
        # a fresh program on this call (satellite of ISSUE 17 — silent
        # hot-loop recompiles become a counted, logged event).
        self._seen_shapes: set = set()
        self.step_compiles = 0

    def _note_shapes(self, kind: str, *arrs) -> bool:
        """Record the padded shape signature entering a jitted entry point.
        Returns True (bumping ray_tpu_llm_step_compiles_total and logging
        once) when the signature is new — i.e. this dispatch pays a compile."""
        key = (kind,) + tuple(tuple(getattr(a, "shape", ())) for a in arrs)
        if key in self._seen_shapes:
            return False
        self._seen_shapes.add(key)
        self.step_compiles += 1
        from ray_tpu.runtime import metric_defs
        from ray_tpu.util import tracing

        metric_defs.LLM_STEP_COMPILES.inc()
        logger.info("llm step compile #%d: %s", self.step_compiles, key)
        # Instant span: the compile itself happens inside the dispatch that
        # follows, but a marker in the request timeline is what attributes
        # the one slow inter-token gap to XLA rather than to scheduling.
        import time as time_mod
        t = time_mod.time()
        tracing.record_span("llm:step_compile", "llm", t, t,
                            entry_point=kind,
                            compile_index=self.step_compiles)
        return True

    # ---- placement (TP over the mesh, SERVE_RULES) -----------------------

    def _place_params(self, params):
        if self.mesh is None:
            return params
        from ray_tpu.parallel.sharding import SERVE_RULES, shard_tree

        return shard_tree(params, llama_mod.param_logical_axes(self.config),
                          SERVE_RULES, self.mesh)

    def _place_cache(self, cache):
        if self.mesh is None:
            return cache
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = NamedSharding(self.mesh, P(None, "tp", None, None, None))
        return jax.tree.map(lambda x: jax.device_put(x, spec), cache)

    # ---- attention dispatch ---------------------------------------------

    def _attend(self, q, k_pages, v_pages, block_tables, kv_lens, q_positions,
                scale):
        impl = (pa.ragged_paged_attention if self.attention_impl == "pallas"
                else pa.ragged_paged_attention_reference)
        fn = partial(impl, scale=scale)
        if self.tp > 1:
            from jax import shard_map
            from jax.sharding import PartitionSpec as P

            fn = shard_map(
                fn, mesh=self.mesh,
                in_specs=(P(None, None, "tp", None), P("tp"), P("tp"),
                          P(), P(), P()),
                out_specs=P(None, None, "tp", None))
        return fn(q, k_pages, v_pages, block_tables, kv_lens, q_positions)

    def _attend_mixed(self, q, k_pages, v_pages, block_tables, kv_lens,
                      q_positions, cu_q_lens, scale):
        impl = (pa.ragged_paged_attention_unified
                if self.attention_impl == "pallas"
                else pa.ragged_paged_attention_unified_reference)
        fn = partial(impl, scale=scale)
        if self.tp > 1:
            from jax import shard_map
            from jax.sharding import PartitionSpec as P

            fn = shard_map(
                fn, mesh=self.mesh,
                in_specs=(P(None, "tp", None), P("tp"), P("tp"),
                          P(), P(), P(), P()),
                out_specs=P(None, "tp", None))
        return fn(q, k_pages, v_pages, block_tables, kv_lens, q_positions,
                  cu_q_lens)

    # ---- the unified step ------------------------------------------------

    def _backbone(self, params, cache, tokens, q_positions, kv_lens, q_lens,
                  block_tables, lora=None, lora_idx=None):
        """tokens: (S, Bq) new tokens (padded); q_positions: (S,) absolute
        position of tokens[s, 0]; kv_lens: (S,) context length AFTER this
        step's tokens; q_lens: (S,) real token count per row (0 for padding
        sequences); lora/lora_idx: slot stacks + per-sequence adapter slot
        (llm/lora.py) when multi-LoRA is active. Returns (final hidden
        states (S, Bq, d), cache); the heads below pay the vocab matmul
        only where they need it."""
        config = self.config
        S, Bq = tokens.shape
        H, K, hd = config.n_heads, config.n_kv_heads, config.head_dim
        scale = 1.0 / math.sqrt(hd)
        x = params["embed"][tokens].astype(config.dtype)        # (S, Bq, d)
        positions = q_positions[:, None] + jnp.arange(Bq)[None, :]
        valid = jnp.arange(Bq)[None, :] < q_lens[:, None]
        logical_block = positions // self.block_size
        block_ids = jnp.take_along_axis(
            block_tables, jnp.clip(logical_block, 0,
                                   block_tables.shape[1] - 1), axis=1)
        # Padding rows get id == num_blocks: out of bounds HIGH, which
        # mode="drop" discards. (-1 would NOT be dropped — JAX wraps
        # negative indices before the bounds check, so padded rows would
        # silently corrupt the pool's last page.)
        block_ids = jnp.where(valid, block_ids, self.num_blocks)
        offsets = positions % self.block_size
        rope_pos = jnp.clip(positions, 0, config.max_seq - 1)
        use_lora = bool(lora)   # static: {}/None compiles the base program

        def proj(h, lp, ll, name):
            out = h @ lp[name]
            if use_lora and name in ll:
                from ray_tpu.llm.lora import apply_lora

                out = out + apply_lora(h, ll[name]["a"], ll[name]["b"],
                                       lora_idx).astype(out.dtype)
            return out

        def layer_step(carry, scanned):
            x, ck, cv = carry
            lp, li, ll = scanned
            h = rms_norm(x, lp["attn_norm"], config.norm_eps)
            q = proj(h, lp, ll, "wq").reshape(S, Bq, H, hd)
            k = proj(h, lp, ll, "wk").reshape(S, Bq, K, hd)
            v = proj(h, lp, ll, "wv").reshape(S, Bq, K, hd)
            q = apply_rope(q, self.cos, self.sin, rope_pos)
            k = apply_rope(k, self.cos, self.sin, rope_pos)
            # Scatter this step's kv into the pool: layer li, every kv head,
            # page block_ids[s,b], slot offsets[s,b]. Mixed advanced
            # indexing puts the (S, Bq) index dims first, so the value is
            # (S, Bq, K, hd) — k/v as computed.
            ck = ck.at[li, :, block_ids, offsets].set(k, mode="drop")
            cv = cv.at[li, :, block_ids, offsets].set(v, mode="drop")
            attn = self._attend(q, ck[li], cv[li], block_tables, kv_lens,
                                q_positions, scale)
            x = x + proj(attn.reshape(S, Bq, H * hd), lp, ll, "wo")
            h = rms_norm(x, lp["mlp_norm"], config.norm_eps)
            x = x + proj(swiglu(proj(h, lp, ll, "w_gate"),
                                proj(h, lp, ll, "w_up")), lp, ll, "w_down")
            return (x, ck, cv), None

        layer_indices = jnp.arange(config.n_layers)
        (x, ck, cv), _ = jax.lax.scan(
            layer_step, (x, cache["k"], cache["v"]),
            (params["layers"], layer_indices, lora if use_lora else {}))
        x = rms_norm(x, params["final_norm"], config.norm_eps)
        return x, {"k": ck, "v": cv}

    def _step(self, params, cache, tokens, q_positions, kv_lens, q_lens,
              block_tables, lora=None, lora_idx=None):
        """Standard head: only the last REAL position per sequence pays the
        vocab matmul. Returns (logits (S, vocab), cache)."""
        x, cache = self._backbone(params, cache, tokens, q_positions,
                                  kv_lens, q_lens, block_tables, lora,
                                  lora_idx)
        last = jnp.take_along_axis(
            x, jnp.maximum(q_lens - 1, 0)[:, None, None], axis=1)[:, 0]
        # fp32 accumulation out of the matmul (not a post-hoc cast, which
        # would keep bf16 rounding): logits feed sampling/argmax decisions.
        logits = jnp.matmul(last, params["lm_head"].astype(self.config.dtype),
                            preferred_element_type=jnp.float32)
        return logits, cache

    def _step_verify(self, params, cache, tokens, q_positions, kv_lens,
                     q_lens, block_tables, lora=None, lora_idx=None):
        """Speculative-verify head: greedy argmax at EVERY position of the
        chunk (the (S*Bq, vocab) matmul is tiny at verify widths; logits
        never leave the device). Returns (token ids (S, Bq) int32, cache)."""
        x, cache = self._backbone(params, cache, tokens, q_positions,
                                  kv_lens, q_lens, block_tables, lora,
                                  lora_idx)
        # Same matmul expression as _step's head — fp32 accumulation via
        # preferred_element_type, NOT a post-hoc cast (a monotone bf16->f32
        # cast can't change argmax). Identical rounding on both heads keeps
        # the "spec-decode exactly matches non-speculative greedy"
        # acceptance property under bf16 production configs.
        logits = jnp.matmul(x, params["lm_head"].astype(self.config.dtype),
                            preferred_element_type=jnp.float32)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

    # ---- the unified RAGGED step (one launch per engine tick) ------------

    def _backbone_mixed(self, params, cache, tokens, q_positions, kv_lens,
                        cu_q_lens, block_tables, lora=None, lora_idx=None):
        """Token-major unified backbone: `tokens` is flat (T,) — sequence s
        owns rows [cu_q_lens[s], cu_q_lens[s+1]) and rows past cu_q_lens[S]
        are padding. q_positions[s] is the absolute position of s's FIRST
        query token; kv_lens[s] the context length AFTER this step's
        tokens. Embed / RoPE / KV-scatter run per token on (T, ...) shapes;
        attention is the ragged unified kernel — decode rows, spec-verify
        rows, and prefill chunk slices share ONE launch instead of one
        rectangular (S, Bq) launch per phase. Returns (hidden (T, d),
        cache)."""
        config = self.config
        T = tokens.shape[0]
        S = kv_lens.shape[0]
        H, K, hd = config.n_heads, config.n_kv_heads, config.head_dim
        scale = 1.0 / math.sqrt(hd)
        seq = pa.token_seq_ids(cu_q_lens, T, S)              # (T,)
        local = jnp.arange(T) - cu_q_lens[seq]
        valid = jnp.arange(T) < cu_q_lens[S]
        positions = q_positions[seq] + local                 # (T,)
        x = params["embed"][tokens].astype(config.dtype)     # (T, d)
        logical_block = positions // self.block_size
        block_ids = block_tables[seq, jnp.clip(
            logical_block, 0, block_tables.shape[1] - 1)]
        # Padding rows get id == num_blocks (out of bounds HIGH, dropped);
        # -1 would wrap to the pool's last page and corrupt it.
        block_ids = jnp.where(valid, block_ids, self.num_blocks)
        offsets = positions % self.block_size
        rope_pos = jnp.clip(positions, 0, config.max_seq - 1)
        use_lora = bool(lora)
        tok_lora = (lora_idx[seq] if use_lora and lora_idx is not None
                    else None)

        def proj(h, lp, ll, name):
            out = h @ lp[name]
            if use_lora and name in ll:
                from ray_tpu.llm.lora import apply_lora

                # apply_lora is (S, Bq, d)-shaped; flat rows ride as Bq=1
                # with a per-TOKEN slot index (sequences may differ).
                out = out + apply_lora(
                    h[:, None], ll[name]["a"], ll[name]["b"],
                    tok_lora)[:, 0].astype(out.dtype)
            return out

        def layer_step(carry, scanned):
            x, ck, cv = carry
            lp, li, ll = scanned
            h = rms_norm(x, lp["attn_norm"], config.norm_eps)
            q = proj(h, lp, ll, "wq").reshape(T, H, hd)
            k = proj(h, lp, ll, "wk").reshape(T, K, hd)
            v = proj(h, lp, ll, "wv").reshape(T, K, hd)
            q = apply_rope(q, self.cos, self.sin, rope_pos)
            k = apply_rope(k, self.cos, self.sin, rope_pos)
            ck = ck.at[li, :, block_ids, offsets].set(k, mode="drop")
            cv = cv.at[li, :, block_ids, offsets].set(v, mode="drop")
            attn = self._attend_mixed(q, ck[li], cv[li], block_tables,
                                      kv_lens, q_positions, cu_q_lens,
                                      scale)
            x = x + proj(attn.reshape(T, H * hd), lp, ll, "wo")
            h = rms_norm(x, lp["mlp_norm"], config.norm_eps)
            x = x + proj(swiglu(proj(h, lp, ll, "w_gate"),
                                proj(h, lp, ll, "w_up")), lp, ll, "w_down")
            return (x, ck, cv), None

        layer_indices = jnp.arange(config.n_layers)
        (x, ck, cv), _ = jax.lax.scan(
            layer_step, (x, cache["k"], cache["v"]),
            (params["layers"], layer_indices, lora if use_lora else {}))
        x = rms_norm(x, params["final_norm"], config.norm_eps)
        return x, {"k": ck, "v": cv}

    def _step_mixed(self, params, cache, tokens, q_positions, kv_lens,
                    cu_q_lens, block_tables, out_rows, proposals, prop_lens,
                    temps, top_ks, top_ps, seeds, counters, lora=None,
                    lora_idx=None):
        """Unified mixed step + on-device seeded acceptance sampling.

        out_rows (S, W): flat hidden-state rows whose logits sequence s
        reads (decode: its single row, W times; spec verify: the rows
        after proposal positions 0..k; prefill finals: the chunk's last
        row). proposals (S, W) / prop_lens (S,): the deterministic draft
        under test (length 0 for plain rows). Row (s, j) carries generation
        counter counters[s] + j — the SAME absolute-index keying as the
        plain sampler, so a row with no proposal degenerates bit-identically
        to _step_sample.

        Returns (accept (S, W) bool, samples (S, W) int32, cache):
          accept[s, j]  — proposal j passes (greedy rows: argmax matches;
                          temp>0 rows: u < p(proposal), the rejection test
                          against the FILTERED target distribution — the
                          draft is a point mass, so q(proposal) = 1)
          samples[s, j] — the token to commit when j is the first rejected
                          slot (temp>0: a residual sample with the proposal
                          masked out) or the bonus slot (the full filtered
                          distribution under the plain sampler's key).
        The host commits proposals[s, :n_acc] + [samples[s, n_acc]]."""
        x, cache = self._backbone_mixed(params, cache, tokens, q_positions,
                                        kv_lens, cu_q_lens, block_tables,
                                        lora, lora_idx)
        S, W = out_rows.shape
        rows = x[out_rows.reshape(-1)]                       # (S*W, d)
        # Same head expression as _step/_step_verify: fp32 accumulation via
        # preferred_element_type so unified and split ticks round alike.
        logits = jnp.matmul(rows,
                            params["lm_head"].astype(self.config.dtype),
                            preferred_element_type=jnp.float32)
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

        def rep(a):
            return jnp.repeat(a, W)

        scaled = self._filter_logits(logits, rep(temps), rep(top_ks),
                                     rep(top_ps))
        j_idx = jnp.tile(jnp.arange(W), S)
        n = rep(counters) + j_idx                            # (S*W,)
        is_bonus = j_idx >= rep(prop_lens)
        prop_flat = proposals.reshape(-1)

        def one_row(seed, counter, lg, prop):
            base = jax.random.fold_in(jax.random.key(seed), counter)
            # `full` uses EXACTLY the plain sampler's key (_device_sample's
            # `one`): bonus slots and spec-off rows reproduce the
            # non-speculative stream bit for bit. u / resid fold in fixed
            # subkeys so a replayed request re-derives the identical
            # accept/reject trajectory (failover + migration determinism).
            full = jax.random.categorical(base, lg)
            u = jax.random.uniform(jax.random.fold_in(base, 101))
            resid = jax.random.categorical(
                jax.random.fold_in(base, 102),
                lg.at[prop].set(self.NEG_INF))
            return full, u, resid, jax.nn.softmax(lg)[prop]

        full, u, resid, p_prop = jax.vmap(one_row)(
            rep(seeds), n, scaled, prop_flat)
        grow = rep(temps) <= 0.0
        accept = jnp.where(grow, greedy == prop_flat, u < p_prop)
        samples = jnp.where(
            grow, greedy,
            jnp.where(is_bonus, full.astype(jnp.int32),
                      resid.astype(jnp.int32)))
        return accept.reshape(S, W), samples.reshape(S, W), cache

    def step_mixed(self, tokens, q_positions, kv_lens, cu_q_lens,
                   block_tables, out_rows, proposals, prop_lens, temps,
                   top_ks, top_ps, seeds, counters, lora_idx=None):
        """One unified ragged launch for a mixed decode / spec-verify /
        prefill batch, bucketed on total token count T rather than the
        (batch, Bq) product. Returns (accept (S, W) bool, samples (S, W)
        int32) as host numpy-convertible arrays."""
        self._note_shapes("mixed", tokens, out_rows, block_tables)
        lora, idx = self._lora_args(lora_idx, len(kv_lens))
        accept, samples, self.cache = self._step_mixed_jit(
            self.params, self.cache, tokens, q_positions, kv_lens,
            cu_q_lens, block_tables, out_rows, proposals, prop_lens, temps,
            top_ks, top_ps, seeds, counters, lora, idx)
        return accept, samples

    def warm_mixed(self, T: int, S: int, W: int):
        """Precompile the mixed-step program for token bucket T without
        touching cache state: cu_q_lens all zero makes every row padding,
        so every KV write drops and the outputs are ignored."""
        import numpy as np

        z = lambda *s: np.zeros(s, np.int32)
        self.step_mixed(
            z(T), z(S), z(S), z(S + 1), z(S, self.max_blocks_per_seq),
            z(S, W), z(S, W), z(S), np.zeros(S, np.float32), z(S),
            np.ones(S, np.float32), z(S), z(S))

    def _lora_args(self, lora_idx, batch: int):
        if self.lora is None:
            return {}, None
        idx = (jnp.zeros(batch, dtype=jnp.int32) if lora_idx is None
               else jnp.asarray(lora_idx, dtype=jnp.int32))
        return self.lora.lora_pytree(), idx

    def step(self, tokens, q_positions, kv_lens, q_lens, block_tables,
             lora_idx=None):
        """Run one bucketed step; inputs are host arrays already padded to a
        (batch, Bq) bucket by the engine. Returns logits (S, vocab)."""
        self._note_shapes("step", tokens, block_tables)
        lora, idx = self._lora_args(lora_idx, len(tokens))
        logits, self.cache = self._step_jit(
            self.params, self.cache, tokens, q_positions, kv_lens, q_lens,
            block_tables, lora, idx)
        return logits

    def step_verify(self, tokens, q_positions, kv_lens, q_lens, block_tables,
                    lora_idx=None):
        """One bucketed verify step: returns greedy token ids (S, Bq) —
        position j's id is the model's next token after consuming
        tokens[:, :j+1] (the speculative-decoding acceptance input)."""
        self._note_shapes("verify", tokens, block_tables)
        lora, idx = self._lora_args(lora_idx, len(tokens))
        toks, self.cache = self._step_verify_jit(
            self.params, self.cache, tokens, q_positions, kv_lens, q_lens,
            block_tables, lora, idx)
        return toks

    # ---- on-device sampling ---------------------------------------------

    NEG_INF = -1e30

    def _filter_logits(self, logits, temps, top_ks, top_ps):
        """Temperature / top-k / top-p filtering shared by the plain sampler
        and the mixed-step acceptance sampler — ONE implementation, so the
        unified and split tick paths round identically (their bit-identity
        rides on it). top-p keeps the smallest prefix with mass >= p
        (crossing token included, vLLM semantics). Returns filtered scaled
        logits; sampling from softmax of them is the target distribution."""
        S, V = logits.shape
        scaled = logits / jnp.maximum(temps[:, None], 1e-6)
        sorted_desc = -jnp.sort(-scaled, axis=-1)
        k_eff = jnp.where(top_ks > 0, top_ks, V)
        kth = jnp.take_along_axis(
            sorted_desc, jnp.clip(k_eff - 1, 0, V - 1)[:, None], axis=1)
        scaled = jnp.where(scaled >= kth, scaled, self.NEG_INF)
        probs = jax.nn.softmax(scaled, axis=-1)
        sp = -jnp.sort(-probs, axis=-1)
        csum = jnp.cumsum(sp, axis=-1)
        # Keep token j iff the probability mass BEFORE it is < top_p (the
        # crossing token stays; robust to fp32 cumsum never reaching 1.0,
        # which would otherwise collapse top_p=1.0 to greedy).
        keep_sorted = (csum - sp) < top_ps[:, None]
        cutoff = jnp.min(jnp.where(keep_sorted, sp, jnp.inf), axis=-1,
                         keepdims=True)
        return jnp.where(probs >= cutoff, scaled, self.NEG_INF)

    def _device_sample(self, logits, temps, top_ks, top_ps, seeds, counters):
        """Vectorized per-sequence sampling on device: greedy (temp 0),
        temperature, top-k, top-p, seeded. Keeps the decode loop free of
        (S, vocab) device->host logit transfers — only sampled token ids
        cross the wire (the latency win that makes async decode possible)."""
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        scaled = self._filter_logits(logits, temps, top_ks, top_ps)

        def one(seed, counter, lg):
            key = jax.random.fold_in(jax.random.key(seed), counter)
            return jax.random.categorical(key, lg)

        sampled = jax.vmap(one)(seeds, counters, scaled).astype(jnp.int32)
        return jnp.where(temps <= 0.0, greedy, sampled)

    def _step_sample(self, params, cache, tokens, q_positions, kv_lens,
                     q_lens, block_tables, temps, top_ks, top_ps, seeds,
                     counters, lora=None, lora_idx=None):
        logits, cache = self._step(params, cache, tokens, q_positions,
                                   kv_lens, q_lens, block_tables, lora,
                                   lora_idx)
        toks = self._device_sample(logits, temps, top_ks, top_ps, seeds,
                                   counters)
        return toks, cache

    def step_sample(self, tokens, q_positions, kv_lens, q_lens, block_tables,
                    temps, top_ks, top_ps, seeds, counters, lora_idx=None):
        """Unified step + on-device sampling. `tokens` may be a DEVICE array
        (the previous step's output — async chaining without host sync).
        Returns the sampled token ids as a device array; the caller decides
        when to fetch (overlap the transfer with the next dispatch)."""
        self._note_shapes("sample", tokens, block_tables)
        lora, idx = self._lora_args(lora_idx, len(tokens))
        toks, self.cache = self._step_sample_jit(
            self.params, self.cache, tokens, q_positions, kv_lens, q_lens,
            block_tables, temps, top_ks, top_ps, seeds, counters, lora, idx)
        return toks

    # ---- multi-step decode ----------------------------------------------
    #
    # One dispatch generates n_steps tokens per sequence via lax.scan:
    # sample -> feed back -> advance positions, entirely on device. The
    # host sees ONE execute round-trip for n tokens instead of n — the
    # decode-throughput lever when dispatch latency (remote TPU relays,
    # slow hosts) rivals per-token compute. Pages for all n tokens must be
    # preallocated (block tables are static across the scan); the engine
    # guarantees that before dispatching.

    def _step_sample_multi(self, n_steps: int, params, cache, tokens,
                           q_positions, kv_lens, q_lens, block_tables,
                           temps, top_ks, top_ps, seeds, counters,
                           lora=None, lora_idx=None):
        def body(carry, step):
            cache, toks = carry
            logits, cache = self._step(
                params, cache, toks, q_positions + step, kv_lens + step,
                q_lens, block_tables, lora, lora_idx)
            sampled = self._device_sample(logits, temps, top_ks, top_ps,
                                          seeds, counters + step)
            return (cache, sampled[:, None]), sampled

        (cache, _), out = jax.lax.scan(
            body, (cache, tokens), jnp.arange(n_steps))
        return out.T, cache    # (S, n_steps)

    def step_sample_multi(self, n_steps: int, tokens, q_positions, kv_lens,
                          q_lens, block_tables, temps, top_ks, top_ps,
                          seeds, counters, lora_idx=None):
        """n_steps decode tokens per sequence in one dispatch. kv_lens /
        counters are the FIRST step's values (advance on device). Returns
        device int32 (S, n_steps)."""
        self._note_shapes(f"multi{n_steps}", tokens, block_tables)
        fn = self._multi_jits.get(n_steps)
        if fn is None:
            fn = jax.jit(partial(self._step_sample_multi, n_steps),
                         donate_argnums=(1,))
            self._multi_jits[n_steps] = fn
        lora, idx = self._lora_args(lora_idx, len(tokens))
        toks, self.cache = fn(
            self.params, self.cache, tokens, q_positions, kv_lens, q_lens,
            block_tables, temps, top_ks, top_ps, seeds, counters, lora, idx)
        return toks

    # ---- disaggregated KV handoff (llm/disagg.py) -----------------------

    def gather_pages(self, block_ids: Sequence[int]):
        """Fetch the KV pages backing `block_ids` as host arrays, each
        (n_layers, n_kv_heads, n_pages, block_size, head_dim) — the export
        side of the prefill->decode handoff. One device-side gather per
        cache side; the host copies are the raw buffers the zero-pickle
        framing streams."""
        import numpy as np

        ids = jnp.asarray(list(block_ids), dtype=jnp.int32)
        k = np.asarray(self.cache["k"][:, :, ids])
        v = np.asarray(self.cache["v"][:, :, ids])
        return k, v

    def scatter_pages(self, block_ids: Sequence[int], k_pages, v_pages):
        """Write adopted KV pages (gather_pages layout) into this runner's
        pool at `block_ids` — the import side of the handoff."""
        ids = jnp.asarray(list(block_ids), dtype=jnp.int32)
        dtype = self.cache["k"].dtype
        self.cache["k"] = self.cache["k"].at[:, :, ids].set(
            jnp.asarray(k_pages, dtype=dtype))
        self.cache["v"] = self.cache["v"].at[:, :, ids].set(
            jnp.asarray(v_pages, dtype=dtype))

    def batch_bucket(self, n: int) -> int:
        return _bucket(n, self.BATCH_BUCKETS)

    def chunk_buckets(self) -> list:
        """The static prefill-chunk bucket ladder: powers of two from 8 up
        to (and always including) chunk_size. Single source of truth for
        runtime bucketing AND warmup precompilation — a diverging copy
        means some runtime bucket never gets warmed."""
        buckets, b = [], 8
        while b < self.chunk_size:
            buckets.append(b)
            b *= 2
        buckets.append(self.chunk_size)
        return buckets

    def chunk_bucket(self, n: int) -> int:
        return _bucket(n, self.chunk_buckets())
